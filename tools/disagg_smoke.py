#!/usr/bin/env python
"""CI disaggregated-serving smoke: the prefill/decode pool-split
contract, driven through REAL replica subprocesses (ci_check.sh
stage 16).

Four stages, every assertion fatal (nonzero exit):

  1. BASELINE — a COLOCATED router over 2 replica processes completes
     two phases of shared-prefix traffic (cold burst, then exact
     repeats); the per-request greedy tokens become the oracle.
     Migration must move BITS, not meaning: any disaggregated tier
     must reproduce these tokens exactly.
  2. DISAGGREGATED — the same tier with --router_prefill_replicas 1:
     cold prompts land on the prefill pool (replica 0), finished
     chains migrate their KV pages over the wire (page_fetch /
     page_push), and the EXACT repeats re-home to the decode pool
     (replica 1) where the migrated pages serve as prefix hits.
     Bars: token-exact both phases, >= 1 chain migrated with zero
     failures, every repeat served by the decode pool, zero lost,
     `trace_main --check` clean (a successful migration is an event,
     never an anomaly).
  3. replica_kill@req:N — a PREFILL replica is SIGKILLed mid-burst
     holding in-flight work and chains mid-migration.  Bars: every
     accepted request completes TOKEN-EXACT vs baseline (the router
     fails over to the decode pool — role preference is a preference,
     not a partition), zero lost, the replica respawns, and the trace
     allows only the injected fault + the router's reaction
     (replica_lost, migration_failed: a kill mid-transfer fails that
     migration LOUDLY but costs no request).
  4. page_fetch_stall@replica1:S — the decode replica's migration
     client stalls before every fetch window (a congested fabric).
     Bars: token-exact, zero lost, chains STILL migrate (slow wire =
     efficiency loss, never a correctness event).

Usage: python tools/disagg_smoke.py [--keep DIR]
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

MODEL_FLAGS = [
    "--model", "transformer_small", "--num_classes", "64",
    "--serve_max_seq_len", "48", "--serve_max_batch", "4",
    "--serve_queue_size", "32", "--heartbeat_secs", "0.2",
    "--kv_page_size", "16", "--kv_pool_pages", "25",
    "--seed", "7",
]
PAGE = 16
BUDGET = 8
REQUESTS = 8


def make_prompts():
    """Shared-prefix cold burst: 2 'system prompts' of 2 full pages
    each, per-request tails — every chain distinct, every chain
    crossing page boundaries (pages must actually migrate)."""
    rng = np.random.default_rng(42)
    groups = [rng.integers(0, 64, (2 * PAGE,)).astype(np.int32)
              for _ in range(2)]
    prompts = []
    for i in range(REQUESTS):
        tail = rng.integers(0, 64, (1 + i % 6,)).astype(np.int32)
        prompts.append(np.concatenate([groups[i % 2], tail]))
    return prompts


def build_tier(workdir, *, prefill_replicas=0, fault_env=None,
               deadline_s=120.0):
    from dtf_tpu.serve.router import Router, replica_spawner
    rendezvous = os.path.join(workdir, "rdv")
    trace_dir = os.path.join(workdir, "trace")
    os.makedirs(trace_dir, exist_ok=True)
    cmd = [sys.executable, "-m", "dtf_tpu.cli.replica_main",
           "--serve_random_init", "--rendezvous_dir", rendezvous,
           *MODEL_FLAGS]
    env_extra = {"DTF_TRACE_DIR": trace_dir}
    if fault_env:
        env_extra["DTF_FAULT"] = fault_env
    spawn = replica_spawner(cmd, rendezvous, env_extra=env_extra)
    # health timeout 15s, not router_smoke's 5s: lazy chunk-shape
    # compiles stall the engine loop (and so its heartbeat) for ~5s on
    # a loaded CPU box, and a false replica_lost would dirty the
    # BASELINE trace.  The kill arm doesn't care — a SIGKILL drops the
    # wire connection, which the router notices immediately.
    router = Router(2, rendezvous, spawn=spawn, page_size=PAGE,
                    probe_interval_s=0.25, health_timeout_s=15.0,
                    deadline_s=deadline_s, replica_inflight=32,
                    respawn_backoff_s=0.2, max_respawns=4,
                    prefill_replicas=prefill_replicas,
                    migrate_timeout_s=60.0)
    from dtf_tpu.obs import trace
    trace.configure(trace_dir, stream="router")
    t0 = time.time()
    router.start(wait_s=600)
    print(f"  tier up in {time.time() - t0:.1f}s")
    return router, trace_dir


def run_traffic(router, prompts):
    from dtf_tpu.serve import Backpressure, DeadlineExceeded
    handles = [router.submit(p, max_new_tokens=BUDGET) for p in prompts]
    results, lost = [], 0
    for h in handles:
        try:
            results.append(h.result(timeout=router.deadline_s + 30))
        except (Backpressure, DeadlineExceeded) as e:
            results.append(e)
            lost += 1
    return results, lost


def wait_migrations(router, want, timeout_s=90.0):
    """Poll until >= ``want`` chains migrated and none are pending.
    Returns the final stats; the CALLER judges failures (a kill arm
    expects some)."""
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        ms = router.migration_stats()
        if ms["migrated"] >= want and ms["pending"] == 0:
            return ms
        time.sleep(0.25)
    return router.migration_stats()


def teardown(router, trace_dir):
    from dtf_tpu.obs import trace
    router.stop(drain=True)
    trace.disable()


def check_trace(trace_dir, allow=()):
    cmd = [sys.executable, "-m", "dtf_tpu.cli.trace_main", trace_dir,
           "--check"]
    for kind in allow:
        cmd += ["--allow", kind]
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          cwd=REPO, timeout=120)
    if proc.returncode != 0:
        print(proc.stdout[-3000:], file=sys.stderr)
        print(proc.stderr[-2000:], file=sys.stderr)
        raise SystemExit(
            f"trace check FAILED for {trace_dir} (allow={allow})")


def assert_exact(results, oracle, stage):
    for i, (got, want) in enumerate(zip(results, oracle)):
        if isinstance(got, Exception):
            raise SystemExit(f"{stage}: request {i} was LOST "
                             f"({got!r}) — zero lost is the bar")
        if got.tokens != want:
            raise SystemExit(
                f"{stage}: request {i} diverged from the colocated "
                f"oracle\n  want {want}\n  got  {got.tokens} "
                f"(replica {got.replica})")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--keep", default="",
                    help="keep work dirs under this path (debug)")
    args = ap.parse_args()
    root = args.keep or tempfile.mkdtemp(prefix="dtf_disagg_smoke_")
    os.makedirs(root, exist_ok=True)
    from dtf_tpu import chaos
    prompts = make_prompts()

    # -- 1. colocated oracle --------------------------------------------
    print("disagg smoke [1/4]: colocated baseline (the token oracle)")
    chaos.disable()
    router, tdir = build_tier(os.path.join(root, "colocated"))
    cold, lost = run_traffic(router, prompts)
    warm, lost2 = run_traffic(router, prompts)
    teardown(router, tdir)
    if lost or lost2:
        raise SystemExit("baseline: requests lost on a healthy "
                         "colocated tier")
    check_trace(tdir, allow=())
    oracle_cold = [r.tokens for r in cold]
    oracle_warm = [r.tokens for r in warm]
    if oracle_cold != oracle_warm:
        raise SystemExit("baseline: colocated repeats diverged — "
                         "greedy decode is not deterministic here?")
    print(f"  oracle OK: {len(oracle_cold)} requests")

    # -- 2. disaggregated tier ------------------------------------------
    print("disagg smoke [2/4]: disaggregated 1p:1d tier (migrate + "
          "re-home)")
    router, tdir = build_tier(os.path.join(root, "disagg"),
                              prefill_replicas=1)
    cold, lost = run_traffic(router, prompts)
    assert_exact(cold, oracle_cold, "disagg/cold")
    if any(r.replica != 0 for r in cold):
        raise SystemExit(
            f"disagg: cold prompts leaked past the prefill pool "
            f"(replicas {[r.replica for r in cold]})")
    ms = wait_migrations(router, want=1)
    if ms["migrated"] < 1 or ms["failed"] or ms["pending"]:
        raise SystemExit(f"disagg: migration never settled ({ms})")
    warm, lost2 = run_traffic(router, prompts)
    assert_exact(warm, oracle_warm, "disagg/warm")
    if lost or lost2:
        raise SystemExit("disagg: requests lost")
    off_pool = [r.replica for r in warm if r.replica == 0]
    if off_pool:
        raise SystemExit(
            f"disagg: {len(off_pool)} repeats served by the PREFILL "
            f"pool — re-homing never landed")
    teardown(router, tdir)
    check_trace(tdir, allow=())
    print(f"  disagg OK: token-exact, {ms['migrated']} chains "
          f"migrated, 0 failed, repeats on the decode pool")

    # -- 3. kill a prefill replica mid-burst ----------------------------
    print("disagg smoke [3/4]: replica_kill@req:4 on the prefill pool")
    chaos.configure("replica_kill@req:4", rank=0)
    router, tdir = build_tier(os.path.join(root, "kill"),
                              prefill_replicas=1)
    cold, lost = run_traffic(router, prompts)
    assert_exact(cold, oracle_cold, "kill/cold")
    if lost:
        raise SystemExit(f"kill: {lost} requests lost")
    failovers = router.metrics.get("router_failover_total").value
    if failovers < 1:
        raise SystemExit("kill: the SIGKILL stranded nothing — the "
                         "fault never fired?")
    deadline = time.time() + 300
    while time.time() < deadline and not all(
            router.replica_healthy(i) for i in range(2)):
        time.sleep(0.25)
    if not all(router.replica_healthy(i) for i in range(2)):
        raise SystemExit("kill: the prefill replica never respawned")
    warm, lost2 = run_traffic(router, prompts)
    assert_exact(warm, oracle_warm, "kill/warm")
    if lost2:
        raise SystemExit("kill: post-respawn repeats lost requests")
    teardown(router, tdir)
    chaos.disable()
    check_trace(tdir, allow=("injected_fault", "replica_lost",
                             "migration_failed"))
    print(f"  kill OK: token-exact, 0 lost, failovers={failovers}, "
          f"prefill replica respawned")

    # -- 4. stalled migration fabric ------------------------------------
    print("disagg smoke [4/4]: page_fetch_stall@replica1:0.05 "
          "(congested wire)")
    router, tdir = build_tier(os.path.join(root, "stall"),
                              prefill_replicas=1,
                              fault_env="page_fetch_stall@replica1:0.05")
    cold, lost = run_traffic(router, prompts)
    assert_exact(cold, oracle_cold, "stall/cold")
    ms = wait_migrations(router, want=1)
    if ms["migrated"] < 1 or ms["pending"]:
        raise SystemExit(f"stall: chains stopped migrating under a "
                         f"slow fabric ({ms}) — a stall is an "
                         f"efficiency loss, not a correctness event")
    warm, lost2 = run_traffic(router, prompts)
    assert_exact(warm, oracle_warm, "stall/warm")
    if lost or lost2:
        raise SystemExit("stall: requests lost")
    teardown(router, tdir)
    check_trace(tdir, allow=("injected_fault",))
    print(f"  stall OK: token-exact, {ms['migrated']} chains migrated "
          f"through the stalled fabric")

    if not args.keep:
        shutil.rmtree(root, ignore_errors=True)
    print("disagg smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
