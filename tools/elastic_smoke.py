#!/usr/bin/env python
"""Elastic-training smoke — the ci_check stage-15 gate.

The headline contract, every bar enforced by nonzero exit: losing
capacity turns preemption into a THROUGHPUT DIP, not an outage.

  1. HOST LOSS → SHRINK: transformer_small under ZeRO-3 on 4 virtual
     devices, ``host_loss@step:4`` injected (self-SIGKILL — the
     unprompted-SIGKILL rank-exit pattern) under the ``cli/launch.py``
     supervisor with ``--elastic``: the supervisor classifies the loss
     apart from a crash and resumes on 2 devices at the sealed step-4
     checkpoint instead of crash-looping — the canonical (stage-0)
     ZeRO checkpoint re-slices onto the surviving mesh through the
     train/zero.py layout contract.
  2. TRAJECTORY-EXACT vs ORACLE: the per-step losses of the shrunken
     window are BIT-IDENTICAL to an oracle run launched FRESH on 2
     devices from the same checkpoint (both compute on the same
     topology, so even float reassociation agrees).  The 4-device
     prefix is additionally pinned against a 4-device prep run.
  3. GROW-BACK: when capacity re-announces (elastic_rejoin.json,
     written here once the shrunken run has sealed step-6), the
     supervisor drains the job at a checkpoint boundary (SIGTERM ⇒
     emergency sealed checkpoint ⇒ exit 75) and relaunches on 4
     devices; the run completes all steps, exit 0.
  4. ``trace_main --check --allow injected_fault --allow host_loss``
     (``device_loss`` for arm 5) is clean — the injected fault fired
     and NOTHING ELSE went anomalous — and the ``elastic_resume``
     trace events pin which steps ran on which topology.
  5. DEVICE LOSS arm: ``device_loss@step:2`` (EXIT_DEVICE_LOST, 76)
     classifies as device_loss and resumes on half the devices to
     completion.

Usage: python tools/elastic_smoke.py [--steps 20] [--keep DIR]
"""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the smoke's own process only supervises + reads traces; subprocess
# device counts come from launch_local's devices_per_process (an
# inherited XLA_FLAGS would fight it)
os.environ.pop("XLA_FLAGS", None)

import argparse      # noqa: E402
import glob          # noqa: E402
import json          # noqa: E402
import shutil        # noqa: E402
import subprocess    # noqa: E402
import tempfile      # noqa: E402
import threading     # noqa: E402
import time          # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

FULL = 4          # full topology (virtual devices)
KILL = 4          # host-loss step; must be a multiple of the
                  # checkpoint interval (2) or the fault re-fires on
                  # every resume (exact-match chaos semantics)
GROW_AFTER = 6    # re-announce capacity once this step's checkpoint
                  # manifest is sealed (guarantees a 2-device window)


def _train_cmd(model_dir: str, trace_dir: str, steps: int, extra=()):
    return [sys.executable, "-m", "dtf_tpu.cli.lm_main",
            "--use_synthetic_data", "--model", "transformer_small",
            "--seq_len", "64", "--batch_size", "8",
            "--train_steps", str(steps), "--log_steps", "1",
            "--skip_eval", "--verbose", "0",
            "--step_time_guard_factor", "0",
            "--zero_stage", "3",
            "--resume", "--checkpoint_steps", "2",
            "--model_dir", model_dir, "--trace_dir", trace_dir, *extra]


def _loss_by_step(trace_dir: str) -> dict:
    out: dict = {}
    for path in glob.glob(os.path.join(trace_dir, "trace_rank*.jsonl")):
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("kind") == "event" and \
                        rec.get("name") == "train_loss":
                    out.setdefault(int(rec["step"]), set()).add(rec["loss"])
    return out


def _elastic_resumes(trace_dir: str) -> list:
    """[(step, devices)] from the elastic_resume trace events."""
    out = []
    for path in glob.glob(os.path.join(trace_dir, "trace_rank*.jsonl")):
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("kind") == "event" and \
                        rec.get("name") == "elastic_resume":
                    out.append((int(rec["step"]), int(rec["devices"])))
    return sorted(out)


def _subprocess_train(model_dir, trace_dir, steps, devices,
                      extra=()) -> int:
    env = dict(os.environ)
    env["XLA_FLAGS"] = (f"--xla_force_host_platform_device_count="
                        f"{devices}")
    return subprocess.run(_train_cmd(model_dir, trace_dir, steps,
                                     extra=extra),
                          env=env, cwd=REPO).returncode


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--keep", default="",
                    help="keep artifacts under this dir (default: "
                         "temp, removed)")
    args = ap.parse_args(argv)

    from dtf_tpu.cli.launch import launch_local
    from dtf_tpu.cli.trace_main import main as trace_main
    from dtf_tpu.train import elastic

    base = args.keep or tempfile.mkdtemp(prefix="elastic_smoke_")
    os.makedirs(base, exist_ok=True)
    try:
        # ---- arm 1+3: host loss under the elastic supervisor --------
        print(f"== elastic_smoke [1/5]: host_loss@step:{KILL} on "
              f"{FULL} devices under --elastic — shrink to "
              f"{FULL // 2}, then grow back ==")
        m1 = os.path.join(base, "m1")
        t1 = os.path.join(base, "t1")
        logs = os.path.join(base, "logs")
        os.makedirs(logs, exist_ok=True)
        meta = os.path.join(m1, "checkpoints.meta",
                            f"manifest_{GROW_AFTER}.json")

        def announcer():
            # the healed host's agent, emulated: once the SHRUNKEN run
            # has sealed the step-6 checkpoint (so a 2-device window
            # provably exists), re-announce full capacity
            while not os.path.exists(meta):
                time.sleep(0.1)
            elastic.announce_rejoin(logs, FULL)

        th = threading.Thread(target=announcer, daemon=True)
        th.start()
        rc = launch_local(
            _train_cmd(m1, t1, args.steps,
                       extra=("--fault", f"host_loss@step:{KILL}")),
            num_processes=1, coordinator="localhost:0", log_dir=logs,
            devices_per_process=FULL, max_restarts=2,
            restart_backoff_s=0.1, elastic=True, min_devices=2)
        if rc != 0:
            print(f"elastic_smoke: supervised run exited {rc}",
                  file=sys.stderr)
            return 1
        ev_path = os.path.join(logs, "supervisor_events.jsonl")
        with open(ev_path) as f:
            ev = [json.loads(line) for line in f if line.strip()]
        shrinks = [e for e in ev if e["event"] == "elastic_shrink"]
        if not (shrinks and shrinks[0]["classification"] == "host_loss"
                and shrinks[0]["total_devices"] == FULL // 2):
            print(f"elastic_smoke: expected a host_loss shrink to "
                  f"{FULL // 2} devices; events: {shrinks}",
                  file=sys.stderr)
            return 1
        if not any(e["event"] == "elastic_grow" for e in ev):
            print("elastic_smoke: the run never grew back "
                  "(capacity re-announce not consumed?)",
                  file=sys.stderr)
            return 1
        resumes = _elastic_resumes(t1)
        if (len(resumes) != 2 or resumes[0] != (KILL, FULL // 2)
                or resumes[1][1] != FULL
                or resumes[1][0] < GROW_AFTER):
            print(f"elastic_smoke: elastic_resume events "
                  f"{resumes} do not match (shrink at {KILL} to "
                  f"{FULL // 2}, grow at >= {GROW_AFTER} to {FULL})",
                  file=sys.stderr)
            return 1
        grow_step = resumes[1][0]
        got = _loss_by_step(t1)
        want_steps = set(range(1, args.steps + 1))
        if set(got) != want_steps or any(len(v) != 1
                                         for v in got.values()):
            print(f"elastic_smoke: trajectory incomplete or "
                  f"double-trained: {sorted(got)}", file=sys.stderr)
            return 1
        print(f"  shrink at step {KILL} -> {FULL // 2} devices, grow "
              f"at step {grow_step} -> {FULL}; all {args.steps} steps "
              f"trained exactly once")

        # ---- arm 2: the shrunken window vs a fresh N/2 oracle --------
        print(f"== elastic_smoke [2/5]: steps {KILL + 1}..{grow_step} "
              f"bit-identical to a fresh {FULL // 2}-device oracle "
              f"from the same checkpoint ==")
        prep_m = os.path.join(base, "prep_m")
        prep_t = os.path.join(base, "prep_t")
        # the prep run must be CONFIG-IDENTICAL to the elastic run's
        # first phase (train_steps feeds the LR schedule), so it runs
        # the same 20-step command and stops at step KILL via an
        # injected crash AFTER the sealed checkpoint — its model_dir
        # is then byte-for-byte the checkpoint the elastic run (and
        # the oracle) resumed from
        rc_prep = _subprocess_train(prep_m, prep_t, args.steps, FULL,
                                    extra=("--fault",
                                           f"crash@step:{KILL}"))
        from dtf_tpu.chaos import EXIT_INJECTED_CRASH
        if rc_prep != EXIT_INJECTED_CRASH:
            print(f"elastic_smoke: prep run exited {rc_prep} (expected "
                  f"the injected crash, {EXIT_INJECTED_CRASH})",
                  file=sys.stderr)
            return 1
        prep = _loss_by_step(prep_t)
        for step in range(1, KILL + 1):
            if got[step] != prep[step]:
                print(f"elastic_smoke: 4-device prefix diverged at "
                      f"step {step}: {sorted(got[step])} != "
                      f"{sorted(prep[step])}", file=sys.stderr)
                return 1
        oracle_m = os.path.join(base, "oracle_m")
        oracle_t = os.path.join(base, "oracle_t")
        # the oracle resumes from a COPY of the prep checkpoint — the
        # same bytes the elastic run resumed from (deterministic
        # training makes the two step-K checkpoints identical; the
        # prefix check above is the witness)
        shutil.copytree(prep_m, oracle_m)
        if _subprocess_train(oracle_m, oracle_t, args.steps,
                             FULL // 2) != 0:
            print("elastic_smoke: oracle run failed", file=sys.stderr)
            return 1
        oracle = _loss_by_step(oracle_t)
        for step in range(KILL + 1, grow_step + 1):
            if got[step] != oracle[step]:
                print(f"elastic_smoke: step {step} loss diverged from "
                      f"the fresh N/2 oracle: {sorted(got[step])} != "
                      f"{sorted(oracle[step])}", file=sys.stderr)
                return 1
        print(f"  steps {KILL + 1}..{grow_step} bit-identical to the "
              f"oracle (and the {FULL}-device prefix to the prep run)")

        # ---- arm 4: anomaly cleanliness ------------------------------
        print("== elastic_smoke [3/5]: trace_main --check --allow "
              "injected_fault --allow host_loss ==")
        if trace_main([t1, "--check", "--allow", "injected_fault",
                       "--allow", "host_loss"]) != 0:
            print("elastic_smoke: elastic trace contains unexpected "
                  "anomalies", file=sys.stderr)
            return 1
        if trace_main([t1, "--check"]) == 0:
            print("elastic_smoke: injected fault never fired",
                  file=sys.stderr)
            return 1

        # ---- arm 5: device loss (exit 76) ----------------------------
        print("== elastic_smoke [4/5]: device_loss@step:2 (exit 76) "
              "classifies + resumes on half the devices ==")
        m2 = os.path.join(base, "m2")
        t2 = os.path.join(base, "t2")
        logs2 = os.path.join(base, "logs2")
        rc = launch_local(
            _train_cmd(m2, t2, 6,
                       extra=("--fault", "device_loss@step:2")),
            num_processes=1, coordinator="localhost:0", log_dir=logs2,
            devices_per_process=FULL, max_restarts=1,
            restart_backoff_s=0.1, elastic=True, min_devices=2)
        if rc != 0:
            print(f"elastic_smoke: device-loss arm exited {rc}",
                  file=sys.stderr)
            return 1
        with open(os.path.join(logs2, "supervisor_events.jsonl")) as f:
            ev2 = [json.loads(line) for line in f if line.strip()]
        if not any(e["event"] == "elastic_shrink"
                   and e["classification"] == "device_loss"
                   for e in ev2):
            print("elastic_smoke: device loss not classified/shrunk",
                  file=sys.stderr)
            return 1
        got2 = _loss_by_step(t2)
        if set(got2) != set(range(1, 7)) or any(len(v) != 1
                                                for v in got2.values()):
            print(f"elastic_smoke: device-loss arm trajectory "
                  f"incomplete: {sorted(got2)}", file=sys.stderr)
            return 1

        print("== elastic_smoke [5/5]: device-loss trace cleanliness ==")
        if trace_main([t2, "--check", "--allow", "injected_fault",
                       "--allow", "device_loss"]) != 0:
            print("elastic_smoke: device-loss trace contains "
                  "unexpected anomalies", file=sys.stderr)
            return 1

        print(f"elastic_smoke: OK — host loss at step {KILL} on {FULL} "
              f"devices resumed on {FULL // 2} (trajectory "
              f"bit-identical to the fresh oracle), grew back at step "
              f"{grow_step}; device loss resharded too")
        return 0
    finally:
        if not args.keep:
            shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
