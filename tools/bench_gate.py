#!/usr/bin/env python
"""Perf-regression gate: the committed BENCH history as a CI contract.

BENCH_r01→r05 record a 15.4× win over the TF baseline; nothing until
now prevented a PR from silently giving it back — the artifacts were
trajectory documentation, not a gate.  This tool compares a CANDIDATE
bench artifact against the committed history with noise-aware
thresholds and exits nonzero on regression, loudly naming the metric.

What it reads (all committed at the repo root):
  BENCH_r*.json      — training benches ({"parsed": {...}} wrappers or
                       bare bench.py JSON): the headline metric plus
                       nested sub-benches ("lm", "input_pipeline"),
                       each with value / value_min / value_max (or
                       tps_min/tps_max) spread fields.
  BENCH_serve*.json  — bench_serve.py --out artifacts: a "metrics"
                       list of BenchmarkMetric lines + "bars_failed".
  BENCH_zero*.json   — tools/zero_smoke.py --out artifacts: the ZeRO
                       overlap/calibration gauges as a "metrics" list
                       + "bars_failed" (same shape as serve).

Thresholds (documented contract, deliberately simple):
  * baseline per metric = the newest HISTORICAL artifact carrying it
    (the value the repo currently claims — regressing vs an old peak a
    later PR knowingly traded away is not a failure; regressing vs the
    current claim is).
  * noise margin per metric = clamp(2 × worst relative spread seen in
    history, MARGIN_FLOOR, MARGIN_CAP).  The spread is the artifact's
    own value_min/value_max (min over windows vs max over windows) —
    the repeatability protocol every bench already records.  A metric
    with no recorded spread gets the floor.
  * direction from the unit/name: throughput ("…/sec…", "tokens/s",
    "mfu", hit counts) must not DROP below baseline × (1 − margin);
    latency/time ("s", "ms", names containing latency/gap/wait/lag)
    must not RISE above baseline × (1 + margin).  Unknown-direction
    metrics are reported, never gated.
  * a BENCH_serve candidate with a non-empty "bars_failed" fails
    outright — the bench's own acceptance bars outrank any margin.

Usage:
  python tools/bench_gate.py                      # newest committed
      artifact of EACH family (training BENCH_r*, serving BENCH_serve*)
      gated against that family's earlier history (the ci_check stage:
      proves the committed history is self-consistent)
  python tools/bench_gate.py --candidate NEW.json # gate a fresh run
  python tools/bench_gate.py --smoke              # the gate's own
      contract, per family: passes on the committed history AND fails
      on a synthetically degraded copy (ci_check asserts both)
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MARGIN_FLOOR = 0.05     # 5%: below the tunnel jitter every BENCH shows
MARGIN_CAP = 0.60       # a metric noisier than this gates in name only
SMOKE_DEGRADE = 0.50    # --smoke halves throughput / doubles latency

HIGHER_TOKENS = ("/sec", "/s/", "per_sec", "per_second", "tokens/s",
                 "images/s")
HIGHER_NAMES = ("mfu", "hit", "throughput", "ratio", "eff", "tflop")
LOWER_UNITS = ("s", "ms")
LOWER_NAMES = ("latency", "gap", "wait", "lag", "time_to", "ttft",
               "step_ms")


def direction(name: str, unit: str) -> Optional[str]:
    """'higher' / 'lower' / None (ungated)."""
    name_l, unit_l = name.lower(), (unit or "").lower()
    if any(k in unit_l for k in HIGHER_TOKENS):
        return "higher"
    if any(k in name_l for k in LOWER_NAMES):
        return "lower"
    if any(k in name_l for k in HIGHER_NAMES):
        return "higher"
    if unit_l in LOWER_UNITS:
        return "lower"
    return None


def _spread(rec: dict) -> Optional[float]:
    """Relative window spread from the artifact's own repeatability
    fields — (max − min) / value."""
    value = rec.get("value")
    lo = rec.get("value_min", rec.get("tps_min"))
    hi = rec.get("value_max", rec.get("tps_max"))
    if not isinstance(value, (int, float)) or not value:
        return None
    lo = lo if isinstance(lo, (int, float)) else value
    hi = hi if isinstance(hi, (int, float)) else value
    return abs(float(hi) - float(lo)) / abs(float(value))


def extract_metrics(obj, out: Dict[str, dict]):
    """Walk an artifact for dicts shaped {"metric": name, "value": v}.
    First occurrence of a name wins (the headline; nested re-runs of
    the same metric under alternative configs — input_pipeline's
    "default" arm — are measurement context, not tracked claims)."""
    if isinstance(obj, dict):
        name = obj.get("metric")
        if isinstance(name, str) and isinstance(obj.get("value"),
                                                (int, float)):
            if name not in out:
                out[name] = {"value": float(obj["value"]),
                             "unit": str(obj.get("unit", "")),
                             "spread": _spread(obj)}
        for v in obj.values():
            extract_metrics(v, out)
    elif isinstance(obj, list):
        for v in obj:
            extract_metrics(v, out)


def load_artifact(path: str) -> Tuple[Dict[str, dict], List[str]]:
    """(metrics, failed bars) from one artifact file.  Handles the
    committed {"parsed": {...}} wrapper, bare bench.py JSON, and the
    bench_serve {"metrics": [...], "bars_failed": [...]} shape."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict) and "parsed" in data:
        data = data["parsed"]
    metrics: Dict[str, dict] = {}
    extract_metrics(data, metrics)
    bars = list(data.get("bars_failed", [])) if isinstance(data, dict) \
        else []
    return metrics, bars


def default_history() -> List[str]:
    pats = (os.path.join(REPO, "BENCH_r*.json"),
            os.path.join(REPO, "BENCH_serve*.json"),
            os.path.join(REPO, "BENCH_zero*.json"))
    return sorted(p for pat in pats for p in glob.glob(pat))


def families(history: List[str]) -> Dict[str, List[str]]:
    """Group artifacts into tracked families (training BENCH_r*,
    serving BENCH_serve*, ZeRO-overlap BENCH_zero*) so the default/
    smoke modes gate the newest artifact of EACH family — a
    lexicographic history[-1] would permanently pick one family once
    committed and stop gating the others' claims entirely."""
    out: Dict[str, List[str]] = {}
    for path in history:
        base = os.path.basename(path)
        fam = ("serve" if base.startswith("BENCH_serve")
               else "zero" if base.startswith("BENCH_zero")
               else "train")
        out.setdefault(fam, []).append(path)
    return {fam: sorted(paths) for fam, paths in out.items()}


def gate(history: List[str], candidate: str,
         margin_floor: float = MARGIN_FLOOR) -> int:
    """0 = no regression; 1 = regression (or failed serve bars);
    2 = unusable inputs."""
    history = [os.path.abspath(p) for p in history]
    candidate = os.path.abspath(candidate)
    prior = [p for p in history if p != candidate]
    if not prior:
        print(f"bench_gate: no history to gate {candidate} against "
              f"(need at least one earlier BENCH artifact)",
              file=sys.stderr)
        return 2
    cand_metrics, cand_bars = load_artifact(candidate)
    if not cand_metrics:
        print(f"bench_gate: no gateable metrics in {candidate}",
              file=sys.stderr)
        return 2

    # baseline = newest prior artifact carrying the metric; noise =
    # worst relative spread seen anywhere in history (candidate incl.)
    baseline: Dict[str, dict] = {}
    worst_spread: Dict[str, float] = {}
    for path in prior:                     # sorted: newest last wins
        metrics, _ = load_artifact(path)
        for name, rec in metrics.items():
            baseline[name] = {**rec, "from": os.path.basename(path)}
            if rec["spread"] is not None:
                worst_spread[name] = max(worst_spread.get(name, 0.0),
                                         rec["spread"])
    for name, rec in cand_metrics.items():
        if rec["spread"] is not None:
            worst_spread[name] = max(worst_spread.get(name, 0.0),
                                     rec["spread"])

    failures: List[str] = []
    if cand_bars:
        failures.append(f"candidate bench bars failed: {cand_bars}")
    gated = reported = 0
    for name, rec in sorted(cand_metrics.items()):
        base = baseline.get(name)
        if base is None:
            continue          # a brand-new metric has no claim to keep
        d = direction(name, rec["unit"] or base["unit"])
        margin = min(max(2.0 * worst_spread.get(name, 0.0),
                         margin_floor), MARGIN_CAP)
        cur, ref = rec["value"], base["value"]
        if d is None or not ref:
            reported += 1
            print(f"  (report-only) {name}: {cur:g} vs {ref:g} "
                  f"[{base['from']}]")
            continue
        gated += 1
        if d == "higher":
            floor = ref * (1.0 - margin)
            verdict = cur >= floor
            bound = f">= {floor:g}"
        else:
            ceil = ref * (1.0 + margin)
            verdict = cur <= ceil
            bound = f"<= {ceil:g}"
        status = "ok" if verdict else "REGRESSION"
        print(f"  [{status}] {name}: {cur:g} (baseline {ref:g} from "
              f"{base['from']}, margin {margin:.0%}, need {bound})")
        if not verdict:
            failures.append(
                f"{name}: {cur:g} vs baseline {ref:g} "
                f"({base['from']}) outside the {margin:.0%} noise band")
    print(f"bench_gate: {gated} metric(s) gated, {reported} "
          f"report-only, candidate {os.path.basename(candidate)} vs "
          f"{len(prior)} historical artifact(s)")
    if failures:
        for f_ in failures:
            print(f"bench_gate: FAIL — {f_}", file=sys.stderr)
        return 1
    print("bench_gate: OK — no regression")
    return 0


def degrade(path: str, out_path: str, factor: float = SMOKE_DEGRADE):
    """Write a copy of an artifact with every gateable metric pushed
    the WRONG way (throughput × factor, latency ÷ factor) — the
    synthetic regression the gate smoke must catch."""
    with open(path) as f:
        data = json.load(f)

    def walk(obj):
        if isinstance(obj, dict):
            name = obj.get("metric")
            if isinstance(name, str) and isinstance(obj.get("value"),
                                                    (int, float)):
                d = direction(name, str(obj.get("unit", "")))
                if d == "higher":
                    obj["value"] = obj["value"] * factor
                elif d == "lower":
                    obj["value"] = obj["value"] / factor
            for v in obj.values():
                walk(v)
        elif isinstance(obj, list):
            for v in obj:
                walk(v)

    walk(data)
    with open(out_path, "w") as f:
        json.dump(data, f)


def smoke(history: List[str]) -> int:
    """The gate's own contract, PER FAMILY (training + serving): the
    committed history passes, an injected regression fails.  Nonzero
    unless both hold for every family with enough history to gate."""
    gated_any = False
    for fam, paths in sorted(families(history).items()):
        if len(paths) < 2:
            print(f"bench_gate --smoke: family {fam!r} has only "
                  f"{len(paths)} artifact(s) — nothing to gate yet")
            continue
        gated_any = True
        candidate = paths[-1]
        print(f"bench_gate --smoke [{fam} 1/2]: committed history must "
              f"pass ({os.path.basename(candidate)})")
        if gate(paths, candidate) != 0:
            print(f"bench_gate --smoke: committed {fam} history FAILED "
                  f"its own gate — fix the artifacts or the thresholds",
                  file=sys.stderr)
            return 1
        print(f"bench_gate --smoke [{fam} 2/2]: injected regression "
              f"must fail")
        with tempfile.TemporaryDirectory(prefix="bench_gate_") as tmp:
            degraded = os.path.join(tmp, os.path.basename(candidate))
            degrade(candidate, degraded)
            rc = gate(paths, degraded)
        if rc == 0:
            print(f"bench_gate --smoke: the gate PASSED a 2x-degraded "
                  f"{fam} artifact — thresholds are vacuous",
                  file=sys.stderr)
            return 1
    if not gated_any:
        print("bench_gate --smoke: no family has >= 2 artifacts",
              file=sys.stderr)
        return 2
    print("bench_gate --smoke: OK (history passes, regression caught)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python tools/bench_gate.py",
        description="Gate a bench artifact against the committed "
                    "BENCH history (noise-aware thresholds).")
    ap.add_argument("--history", nargs="*", default=None,
                    help="history artifacts (default: the repo's "
                         "BENCH_r*.json + BENCH_serve*.json)")
    ap.add_argument("--candidate", default="",
                    help="artifact to gate (default: the newest "
                         "history artifact, gated vs the earlier ones)")
    ap.add_argument("--margin_floor", type=float, default=MARGIN_FLOOR,
                    help=f"minimum relative noise margin (default "
                         f"{MARGIN_FLOOR})")
    ap.add_argument("--smoke", action="store_true",
                    help="self-test: history passes AND an injected "
                         "regression fails")
    args = ap.parse_args(argv)
    history = args.history if args.history else default_history()
    if not history:
        print("bench_gate: no BENCH artifacts found", file=sys.stderr)
        return 2
    if args.smoke:
        return smoke(history)
    if args.candidate:
        return gate(history, args.candidate,
                    margin_floor=args.margin_floor)
    # default: gate the newest artifact of EACH family against its
    # earlier history (one regressed family fails the whole gate)
    rc = 0
    for fam, paths in sorted(families(history).items()):
        if len(paths) < 2:
            continue
        print(f"bench_gate: family {fam!r} — gating "
              f"{os.path.basename(paths[-1])}")
        rc = max(rc, gate(paths, paths[-1],
                          margin_floor=args.margin_floor))
    return rc


if __name__ == "__main__":
    sys.exit(main())
