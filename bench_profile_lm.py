"""LM step roofline: where does the flagship's step go? (VERDICT r3 #3)

Step history as the kernels improved: ~254 ms (r3) → ~228 (r4
scratch-store bwd kernels) → ~223 ms (r5 fused single-pass backward,
mfu_model ~0.59).

Sibling of bench_profile.py (the ResNet roofline), for the LM flagship
(transformer_tpu: 12x768, 6 heads x d_head 128, seq 2048, bf16, AdamW,
per-chip batch 16).  Independent views of one step:

1. measured wall time, with and without in-step accuracy metrics (the
   reference's own benchmark-purity flag, common.py:277-278: the
   argmax reads the full [B*S, 32k] f32 logits every step);
2. XLA cost_analysis aggregates -> achieved FLOP/s + HBM bandwidth
   (NOTE: XLA does not count the Pallas attention kernels' FLOPs, so
   an analytic model-FLOPs MFU is reported alongside);
3. per-dot table from the optimized HLO: FLOPs + minimal bytes per
   matmul class, compute/bandwidth floors;
4. isolated component timings (tunnel-jitter-proof fori_loop
   differencing): flash attention f+b x layers, lm_head+CE f+b;
5. the blocked-CE measurement (r3 #3's proposed lever): computing the
   loss over row chunks with remat instead of materializing the
   [B*S, 32k] f32 logits.  MEASURED NEGATIVE on this chip: the head
   is compute-bound, not logits-bandwidth-bound — isolated f+b 24.3
   (materialized) vs 21.3-24.3 ms (chunked, best case ~12%/~3 ms of a
   254 ms step), because chunking adds a full logits recompute pass
   (+1.65 TFLOP) to save ~17 GB of traffic that XLA largely overlaps
   with compute anyway.  Kept out of the production loss path; this
   bench carries the evidence.

Prints ONE JSON line.
"""

import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bench import peak_tflops
from bench_lm import _loop_time as _bench_lm_loop_time
from bench_lm import build_trainer
from bench_profile import conv_table, hbm_gbps

from bench_lm import D_FF, D_MODEL, LAYERS, SEQ, VOCAB  # flagship dims

BATCH = 16
HEADS, D_HEAD = 6, D_MODEL // 6

# shared tunnel-jitter-proof harness (bench_lm documents the rationale)
_loop_time = functools.partial(_bench_lm_loop_time, n1=8, n2=72, reps=6)


def build_step(report_acc: bool):
    """The flagship step — same recipe object as bench_lm's headline
    (build_trainer), so the roofline decomposes exactly the benched
    step."""
    trainer, rt = build_trainer(BATCH, remat=False, seq=SEQ, heads=HEADS,
                                report_acc=report_acc)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, VOCAB, (BATCH, SEQ)).astype(np.int32)
    labels = np.roll(tokens, -1, axis=1)
    state = trainer.init_state(jax.random.key(0), (tokens, labels))
    sharded = rt.shard_batch((tokens, labels))
    return trainer, state, sharded


def step_time(step_fn, state, sharded, warmup=3):
    """``step_fn``: the jitted trainer.train_step OR the AOT-compiled
    executable (reusing the AOT object avoids a second multi-minute
    compile of the same 137M-param graph on this host).  Timing via
    bench.timed_train_steps (sync-cancelling)."""
    from bench import timed_train_steps
    for _ in range(warmup):
        state, m = step_fn(state, *sharded)
    jax.device_get(m["loss"])
    med, _, _, _, state = timed_train_steps(step_fn, state, sharded,
                                            short=3, long=13)
    return med, state


def isolated_attention():
    from dtf_tpu.ops.flash_attention import flash_attention
    key = jax.random.key(0)
    q = jax.random.normal(key, (BATCH, SEQ, HEADS, D_HEAD), jnp.bfloat16)
    k = jax.random.normal(key, (BATCH, SEQ, HEADS, D_HEAD), jnp.bfloat16)
    v = jax.random.normal(key, (BATCH, SEQ, HEADS, D_HEAD), jnp.bfloat16)

    def fb(i, qq):
        g = jax.grad(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, causal=True).astype(jnp.float32)),
            argnums=(0, 1, 2))(qq, k, v)
        return (g[0] + g[1] + g[2]).astype(jnp.bfloat16)
    return _loop_time(fb, q)


def isolated_head_ce(chunk_rows=None):
    import optax
    n = BATCH * SEQ
    key = jax.random.key(0)
    x = jax.random.normal(key, (n, D_MODEL), jnp.bfloat16)
    w = jax.random.normal(key, (D_MODEL, VOCAB), jnp.bfloat16) * 0.02
    labels = jax.random.randint(key, (n,), 0, VOCAB)

    def ce(x, w):
        if chunk_rows is None:
            logits = (x @ w).astype(jnp.float32)
            return jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(
                    logits, labels))
        xs = x.reshape(n // chunk_rows, chunk_rows, D_MODEL)
        ls = labels.reshape(n // chunk_rows, chunk_rows)

        @jax.checkpoint
        def chunk_loss(xc, lc):
            logits = (xc @ w).astype(jnp.float32)
            return jnp.sum(
                optax.softmax_cross_entropy_with_integer_labels(
                    logits, lc))
        tot, _ = lax.scan(
            lambda acc, args: (acc + chunk_loss(*args), None),
            jnp.float32(0.0), (xs, ls))
        return tot / n

    def fb(i, xx):
        g = jax.grad(ce, argnums=(0, 1))(xx, w)
        # fold BOTH grads into the carry (scaled to numerical no-ops):
        # a discarded g[1] lets XLA dead-code-eliminate the ~1.65 TFLOP
        # weight-gradient matmul and undercount the backward
        return (xx + g[0] * jnp.bfloat16(1e-30)
                + jnp.sum(g[1]).astype(jnp.bfloat16) * jnp.bfloat16(1e-30))
    return _loop_time(fb, x)


def isolated_embed_ln():
    """Embed f+b (the bwd is a scatter-add into the [32k, 768] table —
    a suspected TPU sink, measured negligible) and one LayerNorm f+b
    (×24 in the step).  Closes the decomposition's remainder."""
    import flax.linen as nn
    key = jax.random.key(0)
    tokens = jax.random.randint(key, (BATCH, SEQ), 0, VOCAB)
    emb = nn.Embed(VOCAB, D_MODEL, dtype=jnp.bfloat16)
    eparams = emb.init(key, tokens)

    def eloss(p, tokens):
        return jnp.sum(emb.apply(p, tokens).astype(jnp.float32) ** 2)

    def efb(i, carry):
        # params depend on the carry so the grad is loop-VARIANT —
        # XLA hoists loop-invariant computations out of fori_loop and
        # the differenced timing would measure a scalar add
        p = jax.tree_util.tree_map(
            lambda l: l + carry.astype(l.dtype), eparams)
        g = jax.tree_util.tree_leaves(jax.grad(eloss)(p, tokens))[0]
        return carry + jnp.sum(g).astype(jnp.float32) * 1e-20

    embed_s = _loop_time(efb, jnp.float32(0.0))

    x = jax.random.normal(key, (BATCH * SEQ, D_MODEL), jnp.bfloat16)
    ln = nn.LayerNorm(dtype=jnp.bfloat16)
    lp = ln.init(key, x)

    def lnfb(i, xx):
        g = jax.grad(lambda p, x: jnp.sum(
            ln.apply(p, x).astype(jnp.float32) ** 2), argnums=1)(lp, xx)
        # 1e-30, not 0: mul-by-zero would let XLA DCE the backward
        return xx + g.astype(jnp.bfloat16) * jnp.bfloat16(1e-30)

    ln_s = _loop_time(lnfb, x, n1=8, n2=136)
    return embed_s, ln_s


def main():
    device = jax.devices()[0]
    peak = peak_tflops(device) or 0.0
    gbps = hbm_gbps(device) or 0.0

    trainer, state, sharded = build_step(report_acc=True)
    compiled = trainer.train_step.lower(state, *sharded).compile()
    ca = compiled.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
    hlo = compiled.as_text()
    # time the AOT executable itself — the jit path would recompile
    # the identical graph
    step_s, state = step_time(compiled, state, sharded)

    trainer2, state2, sharded2 = build_step(report_acc=False)
    step_noacc_s, _ = step_time(trainer2.train_step, state2, sharded2)

    xla_flops = float(ca.get("flops", 0.0))
    xla_bytes = float(ca.get("bytes accessed", 0.0))

    # XLA:TPU lowers every dot to a 1x1 convolution — the ResNet
    # roofline's conv_table parses exactly this form.  Its channel
    # heuristic targets spatial convs; for 1x1 dot-convs the exact
    # symmetric identity is K = sqrt(prod(op1)*prod(op2)/prod(out))
    # (prod(op1)*prod(op2) = rows*K * K*cols and prod(out) = rows*cols),
    # so recompute flops = 2*prod(out)*K per row.
    dots = conv_table(hlo)
    for r in dots:
        p_out = float(np.prod(r["out"], dtype=np.float64))
        p_ops = (np.prod(r["kernel"], dtype=np.float64)
                 * np.prod(r["act"], dtype=np.float64))
        if p_out > 0 and p_ops > 0:
            r["flops"] = 2.0 * p_out * float(np.sqrt(p_ops / p_out))
    dots.sort(key=lambda r: -r["flops"])
    dot_flops = sum(r["flops"] for r in dots)
    dot_floor_ms = sum(max(r["flops"] / (peak * 1e12),
                           r["bytes_min"] / (gbps * 1e9))
                       for r in dots) * 1e3 if peak and gbps else None
    # aggregate per op class ("fc1/dot_general" → fc1)
    by_class: dict = {}
    for r in dots:
        parts = r.get("name", "").split("/")
        cls = parts[-2] if len(parts) >= 2 else (parts[-1] or "?")
        agg = by_class.setdefault(cls, {"n": 0, "flops": 0.0, "bytes": 0.0})
        agg["n"] += 1
        agg["flops"] += r["flops"]
        agg["bytes"] += r["bytes_min"]
    classes = [
        {"class": c, "n": a["n"], "tflops": round(a["flops"] / 1e12, 2),
         "floor_ms": round(max(a["flops"] / (peak * 1e12),
                               a["bytes"] / (gbps * 1e9)) * 1e3, 2)
         if peak and gbps else None}
        for c, a in sorted(by_class.items(),
                           key=lambda kv: -kv[1]["flops"])]

    attn_fb = isolated_attention()
    head_fb = isolated_head_ce()
    head_fb_chunked = isolated_head_ce(chunk_rows=8192)
    embed_fb, ln_fb = isolated_embed_ln()

    # analytic model FLOPs (XLA's count excludes the Pallas kernels):
    # 6*matmul_params per token + attention 12*S*d_model per token f+b
    n_params = sum(x.size for x in
                   jax.tree_util.tree_leaves(state.params))
    embed_params = VOCAB * D_MODEL + SEQ * D_MODEL
    matmul_params = n_params - embed_params
    tokens = BATCH * SEQ
    attn_flops = LAYERS * 4 * BATCH * HEADS * SEQ * SEQ * D_HEAD / 2 * 3.5
    model_flops = 6.0 * matmul_params * tokens + attn_flops

    out = {
        "metric": "lm_step_roofline",
        "value": round(model_flops / step_noacc_s / (peak * 1e12), 4)
        if peak else None,
        "unit": "model-flops mfu (no-acc step)",
        "vs_baseline": None,
        "step_ms": round(step_s * 1e3, 2),
        "step_noacc_ms": round(step_noacc_s * 1e3, 2),
        "tokens_per_sec_noacc": round(tokens / step_noacc_s, 0),
        "xla_flops_t": round(xla_flops / 1e12, 2),
        # same denominator as the headline model-flops MFU (the acc-on
        # compile's flops are fine: argmax contributes none), so the
        # xla_mfu↔value gap is purely the Pallas FLOPs XLA doesn't see
        "xla_mfu": (round(xla_flops / step_noacc_s / (peak * 1e12), 4)
                    if peak else None),
        "model_flops_t": round(model_flops / 1e12, 2),
        "xla_bytes_gb": round(xla_bytes / 1e9, 2),
        "achieved_hbm_gbps": round(xla_bytes / step_s / 1e9, 1),
        "compute_floor_ms": (round(model_flops / (peak * 1e12) * 1e3, 2)
                             if peak else None),
        "hbm_floor_ms": (round(xla_bytes / (gbps * 1e9) * 1e3, 2)
                         if gbps else None),
        # measured component split (isolated, f+b, per step)
        "attention_fb_ms_total": round(attn_fb * LAYERS * 1e3, 2),
        "head_ce_fb_ms": round(head_fb * 1e3, 2),
        "head_ce_fb_chunked_ms": round(head_fb_chunked * 1e3, 2),
        "blocked_ce_saving_ms": round((head_fb - head_fb_chunked) * 1e3, 2),
        "embed_fb_ms": round(embed_fb * 1e3, 2),
        # 2 per block + the final ln_f = 25 LayerNorms in the step
        "layernorms_fb_ms_total": round(ln_fb * (2 * LAYERS + 1) * 1e3, 2),
        "acc_metrics_cost_ms": round((step_s - step_noacc_s) * 1e3, 2),
        "n_dots_in_hlo": len(dots),
        "dot_flops_t": round(dot_flops / 1e12, 2),
        "dot_floor_sum_ms": (round(dot_floor_ms, 2)
                             if dot_floor_ms is not None else None),
        "dot_classes": classes[:12],
        "peak_tflops": peak, "peak_hbm_gbps": gbps,
        "device_kind": device.device_kind,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
