"""ResNet-50 step roofline: where does the time go, per HLO conv?

Backs the "~30% MFU is the XLA ceiling" claim with numbers instead of
an assertion (VERDICT r2 weak #3).  Three independent views of the
same compiled step:

1. measured wall-time split: fwd / fwd+bwd / full step (the update is
   the remainder) — same method as bench.py's roofline notes;
2. XLA's aggregate cost_analysis (flops, bytes accessed) → achieved
   FLOP/s and HBM bandwidth vs the chip's peaks;
3. a per-convolution table parsed from the optimized HLO: every conv's
   FLOPs and minimal HBM traffic, its compute-bound and bandwidth-bound
   time floors, and the summed floor vs the measured step — the gap IS
   the scheduling/fusion overhead XLA leaves on the table.

Prints ONE JSON line with the top-N convs by time floor; docs/DESIGN.md
carries the prose conclusion.
"""

import json
import re
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from bench import PEAK_BF16_TFLOPS, peak_tflops

# v5e public spec: 819 GB/s HBM bandwidth per chip
HBM_GBPS = {"v5 lite": 819.0, "v5e": 819.0, "v4": 1228.0, "v5p": 2765.0,
            "v6e": 1640.0}


def hbm_gbps(device) -> float | None:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in HBM_GBPS.items():
        if key in kind:
            return val
    return None


_DEF = re.compile(r"^\s*(?:ROOT\s+)?%(\S+?)\s*=\s*"
                  r"(bf16|f32|s32|pred|u8)\[([0-9,]*)\]")
_CONV = re.compile(r"convolution\(%(\S+?),\s*%(\S+?)\)")
_OPNAME = re.compile(r'op_name="[^"]*?/([^/"]+/[^/"]+)"')


def conv_table(hlo_text: str):
    """Per-convolution flops + minimal bytes from the optimized HLO.
    Operands are %fusion references, so shapes come from a first-pass
    symbol table.  flops = 2 * prod(output) * kernel_elems /
    out_channels (the kernel dim shared with the output)."""
    shapes: dict = {}
    for line in hlo_text.splitlines():
        m = _DEF.match(line)
        if m:
            shapes[m.group(1)] = (
                m.group(2),
                [int(x) for x in m.group(3).split(",") if x])
    rows = []
    for line in hlo_text.splitlines():
        if "convolution(" not in line:
            continue
        md = _DEF.match(line)
        mc = _CONV.search(line)
        if not md or not mc:
            continue
        out_dt = md.group(2)
        out = [int(x) for x in md.group(3).split(",") if x]
        ops = [shapes.get(mc.group(1)), shapes.get(mc.group(2))]
        if not out or any(o is None or not o[1] for o in ops):
            continue
        kernel = min(ops, key=lambda s: int(np.prod(s[1])))
        act = ops[0] if kernel is ops[1] else ops[1]
        k_elems = int(np.prod(kernel[1]))
        out_elems = int(np.prod(out))
        # out channels: HWIO kernels put O last and NHWC outputs put C
        # last — prefer that match (the largest-dim heuristic alone can
        # grab the batch dim, e.g. in_channels 256 vs batch 256).
        # Dots lowered to 1x1 convs (the LM roofline) carry trailing
        # size-1 window dims that would satisfy the last==last test
        # with out_ch=1 — strip them first.
        k_dims = list(kernel[1])
        o_dims = list(out)
        while k_dims and k_dims[-1] == 1:
            k_dims.pop()
        while o_dims and o_dims[-1] == 1:
            o_dims.pop()
        if not k_dims or not o_dims:
            continue
        if k_dims[-1] == o_dims[-1]:
            out_ch = k_dims[-1]
        else:
            out_ch = next((d for d in sorted(k_dims, reverse=True)
                           if d in o_dims), None)
        if not out_ch:
            continue
        flops = 2.0 * out_elems * (k_elems / out_ch)
        bpe = 2 if out_dt == "bf16" else 4
        bytes_min = bpe * (out_elems + k_elems + int(np.prod(act[1])))
        name = _OPNAME.search(line)
        rows.append(dict(out=out, kernel=kernel[1], act=act[1], flops=flops,
                         bytes_min=bytes_min,
                         name=name.group(1) if name else ""))
    return rows


def main():
    from dtf_tpu.config import Config
    from dtf_tpu.data.base import IMAGENET
    from dtf_tpu.models import build_model
    from dtf_tpu.runtime import initialize
    from dtf_tpu.train import Trainer

    batch = 256
    remat = "--remat" in sys.argv  # selective conv_out/bn_stats policy
    fp8 = "--fp8_resid" in sys.argv  # fp8 wgrad-residual probe
    cfg = Config(model="resnet50", dataset="imagenet", dtype="bf16",
                 batch_size=batch, distribution_strategy="tpu",
                 skip_eval=True, train_steps=1)
    rt = initialize(cfg)
    model, l2 = build_model("resnet50", dtype=jnp.bfloat16, remat=remat,
                            fp8_residuals=fp8)
    trainer = Trainer(cfg, rt, model, l2, IMAGENET)
    rng = np.random.default_rng(0)
    images = rng.normal(127, 60, (batch, 224, 224, 3)).astype(np.float32)
    labels = rng.integers(0, 1000, (batch,), dtype=np.int32)
    state = trainer.init_state(jax.random.key(0), (images, labels))
    sharded = rt.shard_batch((images, labels))

    lowered = trainer.train_step.lower(state, *sharded)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
    hlo = compiled.as_text()

    # full step — sync-cancelling windows (bench.timed_train_steps: a
    # plain timed window bakes the ~105 ms tunnel sync into the time)
    from bench import timed_train_steps
    for _ in range(5):
        state, m = trainer.train_step(state, *sharded)
    jax.device_get(m["loss"])
    step_s, _, _, _, state = timed_train_steps(
        trainer.train_step, state, sharded)

    # fwd-only (loss value, no grad) — same sync-cancelling protocol
    # as the full step so the fwd/bwd split is internally consistent
    from bench import windowed_step_seconds

    def fwd_only(params, bstats, images, labels):
        logits, _, _ = trainer._apply(params, bstats, images, True)
        return jnp.mean(logits.astype(jnp.float32))

    fwd_jit = jax.jit(fwd_only)
    obox = {}

    def run_fwd(n):
        for _ in range(n):
            obox["o"] = fwd_jit(state.params, state.batch_stats, *sharded)

    run_fwd(5)
    jax.device_get(obox["o"])
    fwd_s, _, _ = windowed_step_seconds(
        run_fwd, lambda: jax.device_get(obox["o"]))

    device = jax.devices()[0]
    peak = peak_tflops(device) or 0.0
    gbps = hbm_gbps(device) or 0.0
    flops = float(ca.get("flops", 0.0))
    bytes_acc = float(ca.get("bytes accessed", 0.0))

    convs = conv_table(hlo)
    for c in convs:
        c["t_compute_us"] = c["flops"] / (peak * 1e12) * 1e6 if peak else None
        c["t_hbm_us"] = c["bytes_min"] / (gbps * 1e9) * 1e6 if gbps else None
        c["t_floor_us"] = max(c["t_compute_us"] or 0, c["t_hbm_us"] or 0)
    convs.sort(key=lambda c: -c["t_floor_us"])
    floor_sum_ms = sum(c["t_floor_us"] for c in convs) / 1e3

    top = [{"name": c.get("name", ""),
            "out": "x".join(map(str, c["out"])),
            "kernel": "x".join(map(str, c["kernel"])),
            "gflops": round(c["flops"] / 1e9, 1),
            "t_floor_us": round(c["t_floor_us"], 1),
            "bound": ("compute" if (c["t_compute_us"] or 0)
                      >= (c["t_hbm_us"] or 0) else "hbm")}
           for c in convs[:10]]

    print(json.dumps({
        "metric": "resnet50_step_roofline",
        "value": round(flops / step_s / (peak * 1e12), 4) if peak else None,
        "unit": "mfu",
        "vs_baseline": None,
        "remat": remat, "fp8_resid": fp8,
        "step_ms": round(step_s * 1e3, 2),
        "fwd_ms": round(fwd_s * 1e3, 2),
        "bwd_update_ms": round((step_s - fwd_s) * 1e3, 2),
        "xla_flops_g": round(flops / 1e9, 1),
        "xla_bytes_gb": round(bytes_acc / 1e9, 2),  # decimal GB, matches GB/s
        "hbm_floor_ms": (round(bytes_acc / (gbps * 1e9) * 1e3, 2)
                         if gbps else None),
        "compute_floor_ms": (round(flops / (peak * 1e12) * 1e3, 2)
                             if peak else None),
        "achieved_tflops": round(flops / step_s / 1e12, 1),
        "achieved_hbm_gbps": round(bytes_acc / step_s / 1e9, 1),
        "peak_tflops": peak, "peak_hbm_gbps": gbps,
        "n_convs_in_hlo": len(convs),
        "conv_floor_sum_ms": round(floor_sum_ms, 2),
        "top_convs_by_floor": top,
        "device_kind": device.device_kind,
    }))


if __name__ == "__main__":
    main()
