"""Optimizers.

The reference uses exactly one: Keras SGD with momentum 0.9
(common.get_optimizer, common.py:169-172).  Keras momentum semantics:

    v_t = momentum * v_{t-1} - lr_t * g_t
    w_t = w_{t-1} + v_t

which differs from optax.sgd's trace form (`w -= lr*(g + m*trace)`)
whenever the LR changes between steps — and the schedules here step the
LR, so we implement the Keras form exactly as an optax
GradientTransformation.

Loss scaling (fp16 parity, resnet_imagenet_main.py:182-187): handled in
the train step — loss is multiplied by `loss_scale` and gradients
divided back before this transform sees them (static scale; TPU bf16
needs none, which is the default path).
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax


class KerasSGDState(NamedTuple):
    velocity: optax.Updates


def keras_sgd(learning_rate: Callable, momentum: float = 0.9
              ) -> optax.GradientTransformation:
    """SGD with Keras-style momentum; `learning_rate` is fn(step)->f32,
    `step` is read from the caller-provided count in update's extra arg."""

    def init(params):
        return KerasSGDState(
            velocity=jax.tree_util.tree_map(jnp.zeros_like, params))

    def update(grads, state, params=None, *, step):
        lr = learning_rate(step)
        velocity = jax.tree_util.tree_map(
            lambda v, g: momentum * v - lr * g.astype(v.dtype),
            state.velocity, grads)
        return velocity, KerasSGDState(velocity=velocity)

    return optax.GradientTransformation(init, update)


class AdamWState(NamedTuple):
    adam: optax.OptState


def adamw(learning_rate: Callable, b1: float = 0.9, b2: float = 0.95,
          eps: float = 1e-8, weight_decay: float = 0.1
          ) -> optax.GradientTransformation:
    """AdamW with decoupled weight decay and a step-dependent LR — the
    transformer-LM optimizer (no reference counterpart; the reference is
    SGD-only).  Same `update(..., step=)` contract as keras_sgd."""
    base = optax.scale_by_adam(b1=b1, b2=b2, eps=eps)

    def init(params):
        return AdamWState(adam=base.init(params))

    def update(grads, state, params=None, *, step):
        updates, adam_state = base.update(grads, state.adam, params)
        lr = learning_rate(step)
        updates = jax.tree_util.tree_map(
            lambda u, p: (-lr * (u + weight_decay * p)).astype(p.dtype),
            updates, params)
        return updates, AdamWState(adam=adam_state)

    return optax.GradientTransformation(init, update)


# Optimizers whose init() depends only on param SHAPES/dtypes (their
# state is zeros regardless of param values).  The ZeRO path exploits
# this: it calls tx.init on zero-valued protos of the *flattened
# padded* shard layout instead of materializing full-size params
# (train/loop.py).  Any optimizer whose init reads param VALUES
# (e.g. LARS trust-ratio snapshots, Shampoo preconditioner seeds) must
# NOT be added here without also fixing that call site.
ZEROS_INIT_OPTIMIZERS = frozenset({"sgd", "momentum", "adamw"})


def build_optimizer(name: str, learning_rate: Callable,
                    momentum: float = 0.9) -> optax.GradientTransformation:
    if name in ("sgd", "momentum"):
        return keras_sgd(learning_rate, momentum)
    if name == "adamw":
        return adamw(learning_rate)
    raise ValueError(f"unknown optimizer {name!r}")


def opt_state_specs(name: str, param_specs, replicated):
    """PartitionSpec tree matching the optimizer state's structure, for
    tensor-parallel runs: moment buffers shard like their params."""
    if name in ("sgd", "momentum"):
        return KerasSGDState(velocity=param_specs)
    if name == "adamw":
        return AdamWState(adam=optax.ScaleByAdamState(
            count=replicated, mu=param_specs, nu=param_specs))
    raise ValueError(f"unknown optimizer {name!r}")
