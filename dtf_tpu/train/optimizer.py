"""Optimizers.

The reference uses exactly one: Keras SGD with momentum 0.9
(common.get_optimizer, common.py:169-172).  Keras momentum semantics:

    v_t = momentum * v_{t-1} - lr_t * g_t
    w_t = w_{t-1} + v_t

which differs from optax.sgd's trace form (`w -= lr*(g + m*trace)`)
whenever the LR changes between steps — and the schedules here step the
LR, so we implement the Keras form exactly as an optax
GradientTransformation.

Loss scaling (fp16 parity, resnet_imagenet_main.py:182-187): handled in
the train step — loss is multiplied by `loss_scale` and gradients
divided back before this transform sees them (static scale; TPU bf16
needs none, which is the default path).
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax


class KerasSGDState(NamedTuple):
    velocity: optax.Updates


def keras_sgd(learning_rate: Callable, momentum: float = 0.9
              ) -> optax.GradientTransformation:
    """SGD with Keras-style momentum; `learning_rate` is fn(step)->f32,
    `step` is read from the caller-provided count in update's extra arg."""

    def init(params):
        return KerasSGDState(
            velocity=jax.tree_util.tree_map(jnp.zeros_like, params))

    def update(grads, state, params=None, *, step):
        lr = learning_rate(step)
        velocity = jax.tree_util.tree_map(
            lambda v, g: momentum * v - lr * g.astype(v.dtype),
            state.velocity, grads)
        return velocity, KerasSGDState(velocity=velocity)

    return optax.GradientTransformation(init, update)


def build_optimizer(name: str, learning_rate: Callable,
                    momentum: float = 0.9) -> optax.GradientTransformation:
    if name in ("sgd", "momentum"):
        return keras_sgd(learning_rate, momentum)
    raise ValueError(f"unknown optimizer {name!r}")
