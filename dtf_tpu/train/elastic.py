"""Elastic training: shrink/grow resume across topology loss.

A preempted pod slice or a dead host used to mean waiting (or a crash
loop burning the restart budget on a fault no restart-at-size can
fix).  The pieces that make resuming SMALLER safe were deliberately
pre-staged and this module is the thin layer that binds them:

  - ZeRO checkpoints are written in the canonical stage-0 layout
    (full-shaped params + optimizer state, train/loop.py
    ``canonical_state``), so a checkpoint is TOPOLOGY-FREE: restoring
    it onto an arbitrary surviving mesh is ``staged_state`` — each
    leaf re-slices through the train/zero.py layout contract
    (``pad_flat`` zero-pads to the NEW nd·k, so a non-dividing new dp
    costs pad rows that provably stay zero, not correctness).
  - The data stream is a pure function of position (PR 6): per-shard
    data-service positions are derived from the restored step alone,
    and worker count is a non-identity — so the stream remaps to the
    surviving host set with no bookkeeping.
  - Parallelization is re-resolved against whatever the relaunch
    attaches: ``--plan auto`` re-ranks the lattice for the surviving
    mesh (per-shard batch + grad-accum recomputed, GLOBAL batch and
    step semantics invariant); plain mirrored re-meshes over the local
    devices.

The supervisor half lives in ``cli/launch.py`` (stdlib-only by design
— it keeps copies of the contracts below; parity is pinned by
tests/test_elastic.py): device/host loss is CLASSIFIED apart from
ordinary crashes (EXIT_DEVICE_LOST, heartbeat-lost kills, unprompted
SIGKILLs), an ``--elastic`` policy shrinks the topology instead of
burning the restart budget, a ``--min_devices`` floor refuses loudly,
and a re-announced capacity (``elastic_rejoin.json``) grows the job
back at a checkpoint boundary.

The headline contract (tools/elastic_smoke.py, ci_check stage 15):
train on N devices, lose a host at step K, resume on N/2 with the
per-step loss trajectory BIT-IDENTICAL to an oracle launched fresh on
N/2 from the same checkpoint — then grow back to N.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
from typing import List

import jax

from dtf_tpu.obs import trace
from dtf_tpu.train import zero as zero_lib

log = logging.getLogger("dtf_tpu")

# Exit-code / env / rendezvous contracts shared with cli/launch.py and
# dtf_tpu/chaos (both keep stdlib-only copies so the supervisor never
# imports the package it supervises; parity is test-pinned).
EXIT_DEVICE_LOST = 76
DEVICES_ENV = "DTF_ELASTIC_DEVICES"
REJOIN_FILE = "elastic_rejoin.json"

# XLA runtime error-text markers that mean THE ACCELERATORS ARE GONE
# (slice preemption, PCIe/ICI fault, TPU driver reset) rather than a
# bug in the step: jaxlib surfaces them as XlaRuntimeError with a
# status-code prefix.  Matched case-insensitively against both the
# exception type name and its message — jaxlib moves the exception
# class between releases (jax.errors / jaxlib.xla_extension), so the
# classifier keys on the STABLE parts: the runtime's status vocabulary.
_DEVICE_LOSS_MARKERS = (
    "device_lost", "device lost", "data_loss",
    "failed_precondition: device", "device or resource busy",
    "tpu driver", "device is in an invalid state",
)


class DeviceLost(RuntimeError):
    """An XLA runtime failure classified as accelerator loss: the host
    survives but its chips are gone.  The train loop converts the
    runtime's exception into this, and the runner exits
    ``EXIT_DEVICE_LOST`` so an ``--elastic`` supervisor RESHARDS onto
    the surviving topology instead of burning the crash budget on a
    fault no same-size restart can fix."""

    def __init__(self, step: int, cause: BaseException):
        super().__init__(
            f"device loss at step {step}: "
            f"{type(cause).__name__}: {cause}")
        self.step = int(step)
        self.cause = cause


def is_device_loss(exc: BaseException) -> bool:
    """True when ``exc`` is the XLA runtime reporting accelerator loss
    (vs an ordinary step-function error, which must keep crashing the
    normal way — misclassifying a NaN-shaped bug as device loss would
    make the supervisor shrink a healthy topology forever)."""
    name = type(exc).__name__.lower()
    if "xlaruntimeerror" not in name and "runtimeerror" not in name:
        return False
    text = f"{type(exc).__name__}: {exc}".lower()
    return any(m in text for m in _DEVICE_LOSS_MARKERS)


def announce_rejoin(log_dir: str, devices: int) -> str:
    """Re-announce capacity to a shrunken job's supervisor: a healed
    host's agent (or an operator, or the elastic smoke) writes
    ``{"devices": N}`` atomically into the supervisor's log dir.  The
    supervisor's grow-back probe consumes it — once the announced count
    covers the full topology, the job drains at a checkpoint boundary
    and relaunches at full size."""
    path = os.path.join(log_dir, REJOIN_FILE)
    fd, tmp = tempfile.mkstemp(dir=log_dir, suffix=".tmp")
    with os.fdopen(fd, "w") as f:
        json.dump({"devices": int(devices)}, f)
    os.replace(tmp, path)
    log.info("elastic: re-announced %d device(s) at %s", devices, path)
    return path


def check_reshardable(pspecs, leaves, mesh_shape: dict) -> List[str]:
    """Violation messages for leaves that CANNOT shard onto a mesh of
    ``mesh_shape`` — empty when the whole tree reshards.

    The ZeRO flat-slice layout reshards onto ANY data-parallel degree
    by construction (``pad_flat`` zero-pads to the new nd·k), so the
    only real constraints are the leaves whose MODEL partition spec
    pins a tensor dimension to a mesh axis: expert leaves riding
    'data' need the new dp to divide their expert dimension, and
    TP/PP-sharded dims need the (usually unchanged) model axis to
    divide theirs.  A violating resume must refuse with the leaf path,
    not garble state or die in a device_put stack trace."""
    problems: List[str] = []

    def visit(path, spec, leaf):
        if isinstance(spec, zero_lib.Replicated) or spec is None:
            return
        shape = tuple(leaf.shape)
        for d, part in enumerate(spec):
            if part is None:
                continue
            ways = 1
            for a in (part if isinstance(part, (tuple, list)) else (part,)):
                ways *= int(mesh_shape[a])
            if ways > 1 and shape[d] % ways:
                problems.append(
                    f"{jax.tree_util.keystr(path)}: dim {d} "
                    f"({shape[d]}) is not divisible by its mesh axes "
                    f"{part!r} (size {ways})")

    jax.tree_util.tree_map_with_path(visit, pspecs, leaves,
                                     is_leaf=zero_lib.is_spec)
    return problems


def note_elastic_resume(runtime, resumed_step: int) -> None:
    """Under elastic supervision (DEVICES_ENV exported): verify the
    attached topology matches the supervisor's surviving-capacity
    accounting, and stamp the resume point into the trace so the smoke
    (and post-mortems) can reconstruct which steps ran on which
    topology.  A no-op outside elastic supervision."""
    want = os.environ.get(DEVICES_ENV)
    if not want:
        return
    have = jax.device_count()
    if int(want) != have:
        raise RuntimeError(
            f"elastic supervisor sized this attempt for {want} "
            f"device(s) but the runtime attached {have} — the relaunch "
            f"topology does not match the supervisor's accounting "
            f"(stale XLA_FLAGS? a partially-healed slice?); refusing "
            f"to train mis-sharded")
    if resumed_step:
        trace.event("elastic_resume", step=int(resumed_step),
                    devices=have, replicas=runtime.num_replicas)
        log.info("elastic resume: step %d on %d device(s) "
                 "(%d data replicas)", resumed_step, have,
                 runtime.num_replicas)


def replan_for_surviving(cfg, surviving_devices: int):
    """Re-resolve a ``--plan auto`` config against a surviving device
    count — the reshard-time planning step a shrunken relaunch
    performs implicitly (the relaunched runner's ``resolve_plan`` sees
    only the surviving devices).  Exposed as a pure function so the
    invariants are test-pinnable without relaunching anything: the
    GLOBAL batch never changes (a plan compiles parallelism flags,
    never the batch), and an infeasible surviving mesh dies loudly at
    resolve time, not as an OOM mid-compile."""
    from dtf_tpu.plan import resolve_plan
    from dtf_tpu.plan.mesh_spec import mesh_spec
    mesh = mesh_spec("", live_devices=int(surviving_devices))
    out = resolve_plan(cfg, mesh=mesh)
    if out.batch_size != cfg.batch_size:
        raise AssertionError(
            f"plan re-resolution changed the global batch "
            f"({cfg.batch_size} -> {out.batch_size}) — step semantics "
            f"would silently differ across the shrink")
    return out
