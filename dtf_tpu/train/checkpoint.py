"""Checkpoint / resume with integrity verification.

Reference behavior to match (SURVEY §5.4): the Horovod mains attach a
rank-0-only per-epoch `ModelCheckpoint('./checkpoint-{epoch}.h5')`
(resnet_imagenet_main_horovod.py:258-259) with
BroadcastGlobalVariablesCallback(0) as the restore-consistency story.
The reference has no resume flag; we add one (`--resume`) because on
TPU pods restart-from-checkpoint is the whole failure-recovery story.

TPU-native shape: orbax saves the full TrainState (params, batch_stats,
optimizer velocity, step).  In multi-process runs every process calls
save/restore collectively (orbax coordinates the write; with fully
replicated state the writing is effectively coordinator-led, matching
the rank-0 semantics), and the restored arrays are device_put back with
the replicated sharding — the broadcast-equivalent.

Crash-hardening on top (this is what makes `--resume` trustworthy on a
preemptible pod):

  integrity manifests — every completed save is sealed with a digest
      manifest (``<model_dir>/checkpoints.meta/manifest_<step>.json``:
      per-file size + sha256, written atomically AFTER orbax finishes).
      Restore verifies the newest step against its manifest and FALLS
      BACK to the newest *verified* step on corruption or truncation,
      emitting a structured ``ckpt_integrity`` anomaly instead of
      crashing — a half-written checkpoint (the process died mid-save)
      degrades a restart by one checkpoint interval, not to scratch.
  host-side state  — the manifest carries the host training position
      (global step, epoch, step-in-epoch, data-pipeline scheme + seed)
      so a resumed run can reposition its data stream exactly; with the
      position-derived pipeline RNGs (data/cifar.py) that makes the
      resumed batch sequence bit-identical to the uninterrupted run.
  synchronous seals — interval/preemption saves pass ``sync=True``:
      save + wait + manifest before the caller proceeds, so the
      supervisor can restart the rank the moment it exits knowing the
      newest checkpoint is durable and verified.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import List, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from dtf_tpu import chaos
from dtf_tpu.obs import trace

log = logging.getLogger("dtf_tpu")


# ---------------------------------------------------------------------------
# Integrity manifests (module-level: the serve bridge's structure-free
# loader shares them with the Checkpointer)
# ---------------------------------------------------------------------------

def meta_dir(ckpt_directory: str) -> str:
    """Manifest directory for a checkpoints root.  A SIBLING of the
    orbax root, never inside it — orbax owns its directory's layout and
    step scanning."""
    return ckpt_directory.rstrip("/") + ".meta"


def manifest_path(ckpt_directory: str, step: int) -> str:
    return os.path.join(meta_dir(ckpt_directory), f"manifest_{int(step)}.json")


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def write_manifest(ckpt_directory: str, step: int,
                   host_state: Optional[dict] = None) -> str:
    """Seal a COMPLETED step directory: digest every file, write the
    manifest atomically (tmp + rename).  Must only be called after the
    orbax save finished (Checkpointer.wait does this ordering)."""
    step_dir = os.path.join(ckpt_directory, str(int(step)))
    files = {}
    for root, _, names in os.walk(step_dir):
        for name in sorted(names):
            full = os.path.join(root, name)
            rel = os.path.relpath(full, step_dir)
            files[rel] = {"size": os.path.getsize(full),
                          "sha256": _sha256(full)}
    payload = {"step": int(step), "files": files,
               "host_state": dict(host_state or {})}
    path = manifest_path(ckpt_directory, step)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def read_manifest(ckpt_directory: str, step: int) -> Optional[dict]:
    """The manifest dict, or None when missing/unreadable (a torn or
    corrupt manifest reads as 'unverified', not as 'corrupt payload' —
    the payload may be fine and restore is still attempted)."""
    try:
        with open(manifest_path(ckpt_directory, step)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def verify_step(ckpt_directory: str, step: int) -> str:
    """Integrity verdict for one step: ``"ok"`` (manifest present, every
    file matches), ``"corrupt"`` (manifest present, a file is missing /
    resized / digest-mismatched — truncation and bit-rot both land
    here), or ``"unverified"`` (no readable manifest: a legacy
    checkpoint, or the process died between the save and the seal)."""
    manifest = read_manifest(ckpt_directory, step)
    if manifest is None:
        return "unverified"
    step_dir = os.path.join(ckpt_directory, str(int(step)))
    for rel, info in manifest.get("files", {}).items():
        full = os.path.join(step_dir, rel)
        try:
            if os.path.getsize(full) != info["size"]:
                return "corrupt"
            if _sha256(full) != info["sha256"]:
                return "corrupt"
        except OSError:
            return "corrupt"
    return "ok"


def truncate_largest_file(directory: str) -> Optional[str]:
    """Halve the largest file under ``directory`` — the SHARED chaos
    payload behind every ckpt_truncate fault (the torn-write a
    preempted save or interrupted upload leaves behind, minus the
    nondeterminism).  Returns the truncated path, or None when the
    tree holds no files."""
    largest: Tuple[int, Optional[str]] = (0, None)
    for root, _, names in os.walk(os.path.abspath(directory)):
        for name in names:
            full = os.path.join(root, name)
            try:
                size = os.path.getsize(full)
            except OSError:
                continue
            if size > largest[0]:
                largest = (size, full)
    size, victim = largest
    if victim is None:
        return None
    with open(victim, "r+b") as f:
        f.truncate(max(size // 2, 1))
    log.error("chaos: truncated %s (%d -> %d bytes)", victim, size,
              max(size // 2, 1))
    return victim


def _chaos_truncate_newest(ckpt_directory: str) -> None:
    """ckpt_truncate@latest fault action against a train checkpoint
    tree: halve the largest payload file of the NEWEST step
    directory."""
    try:
        steps = sorted(int(d) for d in os.listdir(ckpt_directory)
                       if d.isdigit())
    except OSError:
        return
    if not steps:
        return
    truncate_largest_file(os.path.join(ckpt_directory, str(steps[-1])))


class Checkpointer:
    """TrainState save/restore under <model_dir>/checkpoints, with
    digest manifests under <model_dir>/checkpoints.meta."""

    def __init__(self, model_dir: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(os.path.join(model_dir, "checkpoints"))
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep))
        # steps saved but not yet sealed with a manifest (wait() seals)
        self._pending: List[Tuple[int, Optional[dict]]] = []
        # which step the last restore() actually used (fallbacks move it
        # below latest_step; callers reposition their data stream on it)
        self.last_restored_step: Optional[int] = None

    def save(self, state, step: Optional[int] = None,
             host_state: Optional[dict] = None, sync: bool = False) -> None:
        """Save; ``host_state`` rides the integrity manifest (data
        position / seed — the host half of crash-exact resume).
        ``sync=True`` waits for the write AND seals the manifest before
        returning — the durability interval/preemption saves need."""
        step = int(state.step) if step is None else int(step)
        with trace.span("checkpoint_save", step=step, sync=sync):
            self._mgr.save(step, args=ocp.args.StandardSave(state))
            self._pending.append((step, host_state))
            if sync:
                self.wait()
        log.info("checkpoint saved: step %d -> %s%s", step, self.directory,
                 " (sealed)" if sync else "")

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> List[int]:
        return sorted(int(s) for s in self._mgr.all_steps())

    def verify(self, step: int) -> str:
        return verify_step(self.directory, step)

    def verified_steps(self) -> List[int]:
        return [s for s in self.all_steps() if self.verify(s) == "ok"]

    def host_state(self, step: int) -> Optional[dict]:
        m = read_manifest(self.directory, step)
        return None if m is None else m.get("host_state") or None

    def restore(self, abstract_state, step: Optional[int] = None,
                sharding=None):
        """Restores into the structure of `abstract_state` (a concrete or
        ShapeDtypeStruct TrainState); placed with `sharding` if given —
        restore-then-rebroadcast semantics.

        With ``step=None`` (the resume path) candidates are tried newest
        first; a step whose manifest verification fails, or whose orbax
        restore raises (truncated / mid-write directory), is skipped
        with a structured ``ckpt_integrity`` anomaly and the next older
        step is tried — restart survives a torn newest checkpoint by
        losing one interval, not the run.  An explicit ``step`` is
        restored as asked (verification failure raises)."""
        if chaos.ckpt_truncate():
            _chaos_truncate_newest(self.directory)
        explicit = step is not None
        candidates = [int(step)] if explicit else list(
            reversed(self.all_steps()))
        if not candidates:
            return None
        abstract = jax.tree_util.tree_map(
            ocp.utils.to_shape_dtype_struct, abstract_state)
        newest = candidates[0]
        for s in candidates:
            verdict = self.verify(s)
            if verdict == "corrupt":
                trace.anomaly("ckpt_integrity", step=s, verdict=verdict,
                              action="raise" if explicit else "fallback")
                log.error("checkpoint step %d FAILED integrity "
                          "verification (%s)", s, verdict)
                if explicit:
                    raise OSError(
                        f"checkpoint step {s} under {self.directory} failed "
                        f"integrity verification")
                continue
            try:
                with trace.span("checkpoint_restore", step=s,
                                verified=(verdict == "ok")):
                    restored = self._mgr.restore(
                        s, args=ocp.args.StandardRestore(abstract))
                    if sharding is not None:
                        restored = jax.device_put(restored, sharding)
            except Exception as e:  # noqa: BLE001 — orbax raises many types
                if explicit:
                    raise
                trace.anomaly("ckpt_integrity", step=s, verdict="unreadable",
                              error=type(e).__name__, action="fallback")
                log.error("checkpoint step %d unreadable (%s: %s) — "
                          "falling back", s, type(e).__name__, e)
                continue
            if s != newest:
                log.warning("checkpoint restore FELL BACK: step %d "
                            "(newest %d failed verification/restore) — "
                            "one checkpoint interval of work re-trains",
                            s, newest)
            self.last_restored_step = s
            log.info("checkpoint restored: step %d from %s (%s)", s,
                     self.directory, verdict)
            return restored
        trace.anomaly("ckpt_integrity", step=newest, verdict="none_usable",
                      action="from_scratch")
        log.error("NO checkpoint under %s survived verification — "
                  "resume falls back to training from scratch",
                  self.directory)
        return None

    def gc(self, keep: int) -> List[int]:
        """Cross-run GC by VERIFIED-set: delete every step except the
        newest ``keep`` sha256-verified ones.  Orbax's ``max_to_keep``
        only prunes within one run; a long resume chain accumulates
        every previous run's checkpoints in the same model_dir — this
        is the lever that bounds them (opt-in: ``--checkpoint_keep``).

        Safety rules (the reason this is by verified-set, not by age):
          - steps NEWER than the newest verified step are never deleted
            — an unverified newest may be another process's in-flight
            save, and a newest-only-unverified state must keep its
            fallback chain intact;
          - with NO verified step at all, nothing is deleted (GC must
            never convert "all unverified" into "nothing left");
          - deletion enumerates the directory directly (not the orbax
            manager's cached view), so previous runs' steps are seen.

        Returns the deleted step numbers."""
        if keep <= 0:
            return []
        try:
            steps = sorted(int(name) for name in os.listdir(self.directory)
                           if name.isdigit()
                           and os.path.isdir(os.path.join(self.directory,
                                                          name)))
        except OSError:
            return []
        # newest-first, stopping after `keep` verified steps: every
        # step older than the newest verified one is deleted unless it
        # is in the keep-set, so re-hashing the long doomed tail (a
        # resume chain's worth of multi-GB payloads) changes nothing
        verified: List[int] = []
        for s in reversed(steps):
            if self.verify(s) == "ok":
                verified.append(s)
                if len(verified) == keep:
                    break
        if not verified:
            log.warning("checkpoint gc: no sha256-verified step under "
                        "%s — nothing deleted", self.directory)
            return []
        keep_set = set(verified)
        newest_verified = verified[0]
        doomed = [s for s in steps
                  if s not in keep_set and s < newest_verified]
        import shutil
        for s in doomed:
            shutil.rmtree(os.path.join(self.directory, str(s)),
                          ignore_errors=True)
            try:
                os.unlink(manifest_path(self.directory, s))
            except OSError:
                pass
        if doomed:
            log.info("checkpoint gc: kept %d verified step(s) %s, "
                     "deleted %s", len(keep_set), sorted(keep_set), doomed)
        return doomed

    def wait(self) -> None:
        """Block until in-flight saves land, then seal them with
        manifests (and drop manifests orphaned by max_to_keep pruning).
        EVERY exit path must reach this (or close()) — an abort that
        orphans an async orbax write is exactly the truncation the
        integrity check exists to catch."""
        self._mgr.wait_until_finished()
        pending, self._pending = self._pending, []
        for step, host_state in pending:
            step_dir = os.path.join(self.directory, str(step))
            if os.path.isdir(step_dir):  # may have been pruned already
                write_manifest(self.directory, step, host_state)
        self._prune_manifests()

    def _prune_manifests(self) -> None:
        live = {int(s) for s in self._mgr.all_steps()}
        mdir = meta_dir(self.directory)
        try:
            names = os.listdir(mdir)
        except OSError:
            return
        for name in names:
            if not (name.startswith("manifest_") and name.endswith(".json")):
                continue
            try:
                step = int(name[len("manifest_"):-len(".json")])
            except ValueError:
                continue
            if step not in live:
                try:
                    os.unlink(os.path.join(mdir, name))
                except OSError:
                    pass

    def close(self) -> None:
        try:
            self.wait()
        except Exception:  # noqa: BLE001 — closing must not mask the abort
            log.exception("checkpointer: wait() failed during close")
        self._mgr.close()


def export_model(export_dir: str, state) -> str:
    """--export_dir parity (flags_core.define_base): write the final
    inference variables (params + batch_stats, no optimizer state) as a
    standalone orbax checkpoint — the SavedModel-export equivalent.
    Returns the written path."""
    path = os.path.abspath(os.path.join(export_dir, "model"))
    ckptr = ocp.StandardCheckpointer()
    payload = {"params": state.params, "batch_stats": state.batch_stats}
    with trace.span("checkpoint_export"):
        try:
            ckptr.save(path, payload, force=True)
            ckptr.wait_until_finished()
        finally:
            # abort path included: never orphan the async write thread
            ckptr.close()
    log.info("model exported to %s", path)
    return path


def load_train_checkpoint(model_dir: str, step: Optional[int] = None):
    """Load-for-inference: restore a train-format checkpoint written by
    :class:`Checkpointer` WITHOUT knowing the TrainState structure, and
    return only ``{"params", "batch_stats"}`` (host-global arrays).

    The restore is structure-free (orbax rebuilds the pytree from the
    checkpoint's own metadata), so a serving process does not need the
    training run's optimizer/loss-scale configuration — including
    ZeRO runs at ANY stage: their checkpoints are written in the
    canonical stage-0 layout (CheckpointCallback's ``state_transform``
    = Trainer.canonical_state), so params arrive full-shaped and the
    optimizer state is simply dropped.  Returns None when
    ``model_dir`` has no checkpoint.

    Same integrity fallback as the trainer's restore: a corrupt or
    mid-write newest step (the training run may still be saving, or
    died saving) falls back to the newest verified step with a
    structured anomaly — a serving process never crashes on a torn
    checkpoint it can route around."""
    directory = os.path.abspath(os.path.join(model_dir, "checkpoints"))
    if not os.path.isdir(directory):
        return None
    # enumerate step dirs directly rather than through CheckpointManager:
    # the manager infers the run's ITEM layout from the union of every
    # step directory, so one junk/mid-write step dir (a loose file where
    # it expects an item) poisons restores of the GOOD steps too.
    # Per-step StandardCheckpointer restores are isolated: a broken step
    # fails only itself and the fallback walks on.
    try:
        steps = sorted((int(name) for name in os.listdir(directory)
                        if name.isdigit()
                        and os.path.isdir(os.path.join(directory, name))),
                       reverse=True)
    except OSError:
        return None
    candidates = [int(step)] if step is not None else steps
    restored, used_step = None, None
    for s in candidates:
        verdict = verify_step(directory, s)
        if verdict == "corrupt":
            trace.anomaly("ckpt_integrity", step=s, verdict=verdict,
                          action="raise" if step is not None
                          else "fallback")
            if step is not None:
                raise OSError(
                    f"checkpoint step {s} under {directory} failed "
                    f"integrity verification")
            log.error("serve bridge: checkpoint step %d failed "
                      "verification — falling back", s)
            continue
        ckptr = ocp.StandardCheckpointer()
        try:
            with trace.span("checkpoint_restore", step=s):
                restored = ckptr.restore(
                    os.path.join(directory, str(s), "default"))
            used_step = s
            break
        except Exception as e:  # noqa: BLE001
            if step is not None:
                raise
            trace.anomaly("ckpt_integrity", step=s, verdict="unreadable",
                          error=type(e).__name__, action="fallback")
            log.error("serve bridge: checkpoint step %d unreadable "
                      "(%s) — falling back", s, type(e).__name__)
            continue
        finally:
            ckptr.close()
    if used_step is None:
        return None
    if not isinstance(restored, dict) or "params" not in restored:
        raise ValueError(
            f"checkpoint at {directory} step {used_step} is not a TrainState "
            f"(keys: {sorted(restored) if isinstance(restored, dict) else type(restored)})")
    log.info("serve bridge: loaded train checkpoint step %s from %s",
             used_step, directory)
    return {"params": restored["params"],
            "batch_stats": restored.get("batch_stats") or {}}


def load_exported_model(export_dir: str) -> dict:
    """Restore variables written by `export_model` (for serving/tests)."""
    path = os.path.abspath(os.path.join(export_dir, "model"))
    ckptr = ocp.StandardCheckpointer()
    try:
        return ckptr.restore(path)
    finally:
        ckptr.close()


class CheckpointCallback:
    """Per-epoch save (the ModelCheckpoint-callback equivalent), plus:

      every_steps  — synchronous sealed saves every N global steps (the
          preemption-granularity knob: a pod whose ranks can vanish any
          minute should not rely on epoch boundaries)
      on_preempt   — the emergency save the loop triggers at the step
          boundary after SIGTERM/SIGINT: save + wait + manifest, so the
          checkpoint is durable before the process exits EXIT_PREEMPTED
      host_state_fn(step) — host-side resume payload (data position,
          seed) carried in each save's manifest
      keep         — cross-run GC budget (--checkpoint_keep): after the
          final wait() seals everything, delete all but the newest
          `keep` verified steps (Checkpointer.gc safety rules apply)
      state_transform(state) — applied to the live state before EVERY
          save.  The ZeRO path passes Trainer.canonical_state here so
          checkpoints are always written in the stage-0 layout
          (full-shaped params + optimizer state): any stage restores
          into any other stage and into serving via the bridge, at the
          cost of one param-sized gather per save
    """

    def __init__(self, model_dir: str, max_to_keep: int = 3,
                 every_steps: int = 0, host_state_fn=None, keep: int = 0,
                 state_transform=None):
        self.ckpt = Checkpointer(model_dir, max_to_keep=max_to_keep)
        self.every_steps = int(every_steps or 0)
        self.host_state_fn = host_state_fn
        self.keep = int(keep or 0)
        self.state_transform = state_transform

    def _saveable(self, state):
        return (state if self.state_transform is None
                else self.state_transform(state))

    def _host(self, step: int) -> Optional[dict]:
        if self.host_state_fn is None:
            return {"global_step": int(step)}
        payload = dict(self.host_state_fn(int(step)) or {})
        payload.setdefault("global_step", int(step))
        return payload

    def on_batch_end(self, batch: int, logs=None):
        if not self.every_steps or not logs or "state" not in logs:
            return
        step = int(logs["step"])
        if step and step % self.every_steps == 0:
            self.ckpt.save(self._saveable(logs["state"]), step=step,
                           host_state=self._host(step), sync=True)

    def on_epoch_end(self, epoch: int, logs=None):
        if logs and "state" in logs:
            step = int(jax.device_get(logs["state"].step))
            if self.ckpt.latest_step() == step:
                return  # an interval save already sealed this boundary
            # ASYNC, like the pre-manifest behavior: the epoch-boundary
            # save overlaps the next epoch's steps; its manifest seals
            # at the next wait() (train end / preempt / close).  A
            # crash in that window leaves the step "unverified" — still
            # restorable, just not digest-guaranteed.  Only interval
            # and preemption saves pay for synchronous durability.
            self.ckpt.save(self._saveable(logs["state"]),
                           host_state=self._host(step))

    def on_preempt(self, logs=None):
        if not logs or "state" not in logs:
            return
        step = int(logs["step"])
        if self.ckpt.latest_step() == step:
            self.ckpt.wait()  # already saved this boundary — just seal
            return
        self.ckpt.save(self._saveable(logs["state"]), step=step,
                       host_state=self._host(step), sync=True)

    def on_train_end(self, logs=None):
        self.ckpt.wait()
        if self.keep and jax.process_index() == 0:
            # after wait(): this run's saves are sealed (verified), so
            # they anchor the verified-set the GC keeps; rank-0-only —
            # deletion is not a collective
            self.ckpt.gc(self.keep)
