"""Checkpoint / resume.

Reference behavior to match (SURVEY §5.4): the Horovod mains attach a
rank-0-only per-epoch `ModelCheckpoint('./checkpoint-{epoch}.h5')`
(resnet_imagenet_main_horovod.py:258-259) with
BroadcastGlobalVariablesCallback(0) as the restore-consistency story.
The reference has no resume flag; we add one (`--resume`) because on
TPU pods restart-from-checkpoint is the whole failure-recovery story.

TPU-native shape: orbax saves the full TrainState (params, batch_stats,
optimizer velocity, step).  In multi-process runs every process calls
save/restore collectively (orbax coordinates the write; with fully
replicated state the writing is effectively coordinator-led, matching
the rank-0 semantics), and the restored arrays are device_put back with
the replicated sharding — the broadcast-equivalent.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax
import orbax.checkpoint as ocp

from dtf_tpu.obs import trace

log = logging.getLogger("dtf_tpu")


class Checkpointer:
    """TrainState save/restore under <model_dir>/checkpoints."""

    def __init__(self, model_dir: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(os.path.join(model_dir, "checkpoints"))
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep))

    def save(self, state, step: Optional[int] = None) -> None:
        step = int(state.step) if step is None else int(step)
        with trace.span("checkpoint_save", step=step):
            self._mgr.save(step, args=ocp.args.StandardSave(state))
        log.info("checkpoint saved: step %d -> %s", step, self.directory)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, abstract_state, step: Optional[int] = None,
                sharding=None):
        """Restores into the structure of `abstract_state` (a concrete or
        ShapeDtypeStruct TrainState); placed with `sharding` if given —
        restore-then-rebroadcast semantics."""
        step = self._mgr.latest_step() if step is None else step
        if step is None:
            return None
        with trace.span("checkpoint_restore", step=int(step)):
            abstract = jax.tree_util.tree_map(
                ocp.utils.to_shape_dtype_struct, abstract_state)
            restored = self._mgr.restore(
                step, args=ocp.args.StandardRestore(abstract))
            if sharding is not None:
                restored = jax.device_put(restored, sharding)
        log.info("checkpoint restored: step %d from %s", step, self.directory)
        return restored

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()


def export_model(export_dir: str, state) -> str:
    """--export_dir parity (flags_core.define_base): write the final
    inference variables (params + batch_stats, no optimizer state) as a
    standalone orbax checkpoint — the SavedModel-export equivalent.
    Returns the written path."""
    path = os.path.abspath(os.path.join(export_dir, "model"))
    ckptr = ocp.StandardCheckpointer()
    payload = {"params": state.params, "batch_stats": state.batch_stats}
    with trace.span("checkpoint_export"):
        ckptr.save(path, payload, force=True)
        ckptr.wait_until_finished()
    ckptr.close()
    log.info("model exported to %s", path)
    return path


def load_train_checkpoint(model_dir: str, step: Optional[int] = None):
    """Load-for-inference: restore a train-format checkpoint written by
    :class:`Checkpointer` WITHOUT knowing the TrainState structure, and
    return only ``{"params", "batch_stats"}`` (host-global arrays).

    The restore is structure-free (orbax rebuilds the pytree from the
    checkpoint's own metadata), so a serving process does not need the
    training run's optimizer/loss-scale configuration — including
    ZeRO-sharded runs, whose sliced optimizer state is simply dropped.
    Returns None when ``model_dir`` has no checkpoint."""
    directory = os.path.abspath(os.path.join(model_dir, "checkpoints"))
    if not os.path.isdir(directory):
        return None
    mgr = ocp.CheckpointManager(directory)
    try:
        step = mgr.latest_step() if step is None else step
        if step is None:
            return None
        with trace.span("checkpoint_restore", step=int(step)):
            restored = mgr.restore(step, args=ocp.args.StandardRestore())
    finally:
        mgr.close()
    if not isinstance(restored, dict) or "params" not in restored:
        raise ValueError(
            f"checkpoint at {directory} step {step} is not a TrainState "
            f"(keys: {sorted(restored) if isinstance(restored, dict) else type(restored)})")
    log.info("serve bridge: loaded train checkpoint step %s from %s",
             step, directory)
    return {"params": restored["params"],
            "batch_stats": restored.get("batch_stats") or {}}


def load_exported_model(export_dir: str) -> dict:
    """Restore variables written by `export_model` (for serving/tests)."""
    path = os.path.abspath(os.path.join(export_dir, "model"))
    ckptr = ocp.StandardCheckpointer()
    try:
        return ckptr.restore(path)
    finally:
        ckptr.close()


class CheckpointCallback:
    """Per-epoch save — the ModelCheckpoint-callback equivalent."""

    def __init__(self, model_dir: str, max_to_keep: int = 3):
        self.ckpt = Checkpointer(model_dir, max_to_keep=max_to_keep)

    def on_epoch_end(self, epoch: int, logs=None):
        if logs and "state" in logs:
            self.ckpt.save(logs["state"])

    def on_train_end(self, logs=None):
        self.ckpt.wait()
