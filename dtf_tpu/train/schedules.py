"""Learning-rate schedules as pure functions of the global step.

The reference drives LR three ways (SURVEY §2.1/§7):
  1. CIFAR per-batch callback schedule — linear scaling ×bs/128, steps
     at epochs 91/136/182 (resnet_cifar_main.py:34-65 +
     common.LearningRateBatchScheduler:36-73).
  2. ImageNet per-batch callback schedule — ×bs/256, 5-epoch linear
     warmup, steps at 30/60/80 (resnet_imagenet_main.py:37-71).
  3. Tensor schedule PiecewiseConstantDecayWithWarmup — same shape,
     computed in-graph (common.py:76-140, via --use_tensor_lr).

Under XLA the callback/tensor distinction disappears: every schedule is
a jit-traceable fn(step)->f32, evaluated inside the train step — which
is exactly what the "tensor LR" path wanted to be.  The callback-path
semantics (epoch-granular decay, fractional-epoch warmup) are preserved
exactly.

Horovod's LearningRateWarmupCallback(warmup_epochs=3)
(resnet_cifar_main_horovod.py, SURVEY §3.3) is the `warmup_epochs`
argument on either schedule.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax.numpy as jnp

BASE_LEARNING_RATE = 0.1  # common.py:32

# (multiplier, epoch_to_start) tables, verbatim semantics:
CIFAR_LR_SCHEDULE = ((0.1, 91), (0.01, 136), (0.001, 182))   # cifar_main.py:34-36
IMAGENET_LR_SCHEDULE = ((1.0, 5), (0.1, 30), (0.01, 60), (0.001, 80))  # imagenet_main.py:37-39

Schedule = Callable[[jnp.ndarray], jnp.ndarray]


def piecewise_by_epoch(batch_size: int, steps_per_epoch: int,
                       base_batch: int, table: Sequence,
                       warmup_epochs: float = 0.0) -> Schedule:
    """Epoch-granular piecewise-constant decay with optional per-step
    linear warmup; linear scaling rule `BASE_LR * batch / base_batch`."""
    initial_lr = BASE_LEARNING_RATE * batch_size / base_batch

    def fn(step):
        step = step.astype(jnp.float32)
        epoch = jnp.floor(step / steps_per_epoch)
        lr = jnp.float32(initial_lr)
        for mult, start_epoch in table:
            lr = jnp.where(epoch >= start_epoch, initial_lr * mult, lr)
        if warmup_epochs > 0:
            warmup_steps = warmup_epochs * steps_per_epoch
            frac_epoch = step / steps_per_epoch
            warmup_lr = initial_lr * (frac_epoch / warmup_epochs)
            lr = jnp.where(step < warmup_steps, warmup_lr, lr)
        return lr

    return fn


def cifar_schedule(batch_size: int, steps_per_epoch: int) -> Schedule:
    """resnet_cifar_main.learning_rate_schedule: no warmup, ÷128 scaling.
    Note the reference's epoch counter is `on_epoch_begin`-driven, i.e.
    floor(step/steps_per_epoch) — identical here."""
    return piecewise_by_epoch(batch_size, steps_per_epoch, 128,
                              CIFAR_LR_SCHEDULE)


def imagenet_schedule(batch_size: int, steps_per_epoch: int) -> Schedule:
    """resnet_imagenet_main.learning_rate_schedule: fractional-epoch
    5-epoch warmup then steps at 30/60/80, ÷256 scaling.  The reference
    computes warmup on `epoch + batch/batches_per_epoch` — i.e. pure
    step fraction, matching here."""
    initial_lr = BASE_LEARNING_RATE * batch_size / 256
    warmup_mult, warmup_end = IMAGENET_LR_SCHEDULE[0]

    def fn(step):
        step = step.astype(jnp.float32)
        frac_epoch = step / steps_per_epoch
        epoch = jnp.floor(frac_epoch)
        lr = jnp.float32(initial_lr)
        for mult, start_epoch in IMAGENET_LR_SCHEDULE:
            lr = jnp.where(epoch >= start_epoch, initial_lr * mult, lr)
        warmup_lr = initial_lr * warmup_mult * frac_epoch / warmup_end
        return jnp.where(frac_epoch < warmup_end, warmup_lr, lr)

    return fn


def piecewise_constant_with_warmup(batch_size: int, epoch_size: int,
                                   warmup_epochs: int = 5,
                                   boundaries: Sequence[int] = (30, 60, 80),
                                   multipliers: Sequence[float] = (1.0, 0.1, 0.01, 0.001),
                                   ) -> Schedule:
    """Parity with common.PiecewiseConstantDecayWithWarmup (:76-140), the
    --use_tensor_lr path: step-boundary decay (not epoch-floor) and
    warmup to the *unmultiplied* rescaled LR."""
    if len(boundaries) != len(multipliers) - 1:
        raise ValueError("len(boundaries) must be len(multipliers) - 1")
    steps_per_epoch = epoch_size // batch_size
    rescaled_lr = BASE_LEARNING_RATE * batch_size / 256
    step_boundaries = [float(steps_per_epoch) * b for b in boundaries]
    lr_values = [rescaled_lr * m for m in multipliers]
    warmup_steps = warmup_epochs * steps_per_epoch

    def fn(step):
        step = step.astype(jnp.float32)
        lr = jnp.float32(lr_values[0])
        for b, v in zip(step_boundaries, lr_values[1:]):
            lr = jnp.where(step > b, v, lr)
        warmup_lr = rescaled_lr * step / max(warmup_steps, 1)
        return jnp.where(step < warmup_steps, warmup_lr, lr)

    return fn


def horovod_schedule(num_replicas: int, steps_per_epoch: int,
                     warmup_epochs: float = 3.0,
                     base_lr: float = BASE_LEARNING_RATE) -> Schedule:
    """Horovod-parity LR: the reference's horovod mains drop the
    piecewise schedule entirely and run a constant ``0.1 * hvd.size()``
    (resnet_cifar_main_horovod.py:164) ramped by
    ``LearningRateWarmupCallback(warmup_epochs=3)`` — a linear climb
    from the unscaled base LR to the size-scaled LR over the first three
    epochs (:229-232)."""
    scaled = base_lr * num_replicas

    def fn(step):
        step = step.astype(jnp.float32)
        frac = jnp.minimum(step / (warmup_epochs * steps_per_epoch), 1.0)
        return jnp.float32(base_lr) + (scaled - base_lr) * frac

    return fn


def lm_schedule(total_steps: int, peak_lr: float = 3e-4,
                final_frac: float = 0.1) -> Schedule:
    """Standard LM recipe (no reference counterpart — the reference is
    vision-only): linear warmup over the first tenth of training (capped
    at 2000 steps) then cosine decay to `final_frac` of the peak."""
    warmup = max(1, min(2000, total_steps // 10))
    decay_steps = max(total_steps - warmup, 1)

    def fn(step):
        step = step.astype(jnp.float32)
        warm = peak_lr * step / warmup
        progress = jnp.clip((step - warmup) / decay_steps, 0.0, 1.0)
        cos = final_frac + (1 - final_frac) * 0.5 * (
            1 + jnp.cos(jnp.pi * progress))
        return jnp.where(step < warmup, warm, peak_lr * cos).astype(jnp.float32)

    return fn


def constant(lr: float) -> Schedule:
    def fn(step):
        return jnp.float32(lr)
    return fn


def for_dataset(dataset: str, batch_size: int, steps_per_epoch: int,
                epoch_size: int, use_tensor_lr: bool = False,
                train_epochs: int = 1) -> Schedule:
    if dataset.startswith("cifar"):
        return cifar_schedule(batch_size, steps_per_epoch)
    if dataset == "lm":
        return lm_schedule(steps_per_epoch * max(train_epochs, 1))
    if use_tensor_lr:
        return piecewise_constant_with_warmup(batch_size, epoch_size)
    return imagenet_schedule(batch_size, steps_per_epoch)
