"""ZeRO weight-update sharding — the shared per-leaf layout + collective
helpers behind stages 1-3 (PAPERS.md: Xu et al. 2020, arXiv 2004.13336).

One layout contract, used by the train step (train/loop.py), the
stage-3 parameter store, and the canonical-checkpoint conversions:

  - a leaf already sharded over 'data' (MoE experts riding the batch
    axis) keeps its full LOCAL shape — each data shard holds distinct
    experts, there is nothing left to slice;
  - every other leaf's ZeRO slice is a padded flat buffer: the LOCAL
    (TP/PP) shard flattened, zero-padded to nd·k, and split into nd
    chunks of k — PartitionSpec ('data',), composed with 'model' when
    the param itself shards there (each (data, model) coordinate owns
    one k-slice of its model shard).

Everything here is a pure function of (PartitionSpec, leaf) and runs
either inside ``shard_map`` (the collective forms) or as host-side
shape math.  The padding rows are zeros at init and STAY zero under
every supported optimizer (zero grads in, zero updates out — see
optimizer.ZEROS_INIT_OPTIMIZERS), which is what makes dropping and
re-creating them across a checkpoint round-trip exact.

``comm_off=True`` variants replace each cross-'data' collective with a
local op of the same output shape (values are garbage).  They exist
for ONE purpose: the ``--zero_probe`` timing twin — a compiled step
whose wall time is the step minus its data-axis collectives, so the
EXPOSED (non-overlapped) communication time is a measured number
rather than a model claim.  Never use a comm_off result as state.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from dtf_tpu.models.partition import spec_axes
from dtf_tpu.runtime.mesh import DATA_AXIS, MODEL_AXIS, SEQ_AXIS


class Replicated:
    """Canonical-spec sentinel for leaves that are genuinely replicated
    in BOTH layouts (the optimizer step count): distinguishes them from
    replicated *params*, whose ZeRO slice is a flat ('data',) buffer."""


REP = Replicated()


def is_spec(x) -> bool:
    return isinstance(x, (P, Replicated))


def zero_leaf_spec(spec):
    """ZeRO-slice PartitionSpec for one param-shaped leaf (the layout
    the optimizer state — and stage-3 params — live in)."""
    if isinstance(spec, Replicated):
        return P()
    axes = spec_axes(spec)
    if DATA_AXIS in axes:
        return spec
    if MODEL_AXIS in axes:
        return P((DATA_AXIS, MODEL_AXIS))
    return P(DATA_AXIS)


def pad_flat(p, nd: int):
    """Flatten and zero-pad to a multiple of ``nd`` (the slice grid);
    padding lives at the tail and is trimmed off after gather."""
    flat = p.reshape(-1)
    k = -(-flat.size // nd)
    pad = nd * k - flat.size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat


def local_shape(spec, shape, mesh_shape) -> tuple:
    """The shard_map-local shape of a leaf sharded by ``spec`` on a
    mesh of ``mesh_shape`` (dims divided by their axis sizes)."""
    if isinstance(spec, Replicated) or spec is None:
        return tuple(shape)
    out = list(shape)
    for d, part in enumerate(spec):
        if part is None:
            continue
        for a in (part if isinstance(part, (tuple, list)) else (part,)):
            out[d] //= mesh_shape[a]
    return tuple(out)


# ---------------------------------------------------------------------------
# shard_map-local leaf ops (spec = the leaf's MODEL partition spec)
# ---------------------------------------------------------------------------

def slice_leaf(spec, p, nd: int, idx):
    """This data shard's ZeRO slice of a local param leaf."""
    if isinstance(spec, Replicated):
        return p
    if DATA_AXIS in spec_axes(spec):
        return p
    flat = pad_flat(p, nd)
    k = flat.shape[0] // nd
    return lax.dynamic_slice_in_dim(flat, idx * k, k)


def gather_leaf(spec, s, shape, dtype, nd: int, comm_off: bool = False):
    """Rebuild the full LOCAL leaf (``shape``/``dtype``) from its ZeRO
    slice — the stage-3 per-leaf parameter all-gather (and the
    canonical-checkpoint re-gather)."""
    if isinstance(spec, Replicated):
        return s
    if DATA_AXIS in spec_axes(spec):
        return s.astype(dtype)
    if comm_off:
        full = jnp.tile(s, nd)        # shape-right stand-in, no wire
    else:
        full = lax.all_gather(s, DATA_AXIS, axis=0, tiled=True)
    size = 1
    for d in shape:
        size *= d
    return full[:size].reshape(shape).astype(dtype)


def scatter_leaf(spec, g, nd: int, reduce_axes, mesh_shape,
                 comm_off: bool = False, idx=None, wire=jnp.float32):
    """Reduce-scatter one local grad leaf into this shard's f32 slice
    (mean over the batch-splitting axes).  Leaves sharded over 'data'
    (experts) keep their local shape: reverse-mode all_to_all already
    summed their true grads, so they divide to the global-mean
    convention instead of psum-ing.

    ``wire`` is the reduce-scatter WIRE dtype (``--zero_wire``): bf16
    halves the stage-2/3 scatter volume — the collective then also
    SUMS in bf16, which is the documented trade (the same one
    ``--ps_wire bf16`` ships on the async-PS path).  The returned
    slice is always f32, so the cross-microbatch accumulation carry
    (``slice_zeros``) and the optimizer update math keep full
    precision whatever crosses the wire.  Expert leaves are exempt:
    their true grads were already summed exactly by the all_to_all
    transpose — there is no wire volume left to trade."""
    sharded = spec_axes(spec) if not isinstance(spec, Replicated) else set()
    if DATA_AXIS in sharded:
        axes = tuple(a for a in reduce_axes if a not in sharded)
        if axes and not comm_off:
            g = lax.pmean(g, axes)
        denom = 1
        for a in reduce_axes:
            if a in sharded:
                denom *= mesh_shape[a]
        return (g / denom).astype(jnp.float32)
    flat = pad_flat(g.astype(wire), nd)
    if comm_off:
        k = flat.shape[0] // nd
        return (lax.dynamic_slice_in_dim(flat, idx * k, k)
                .astype(jnp.float32) / nd)
    s = lax.psum_scatter(flat, DATA_AXIS, scatter_dimension=0,
                         tiled=True).astype(jnp.float32) / nd
    return lax.pmean(s, SEQ_AXIS)


def slice_zeros(spec, p, nd: int):
    """f32 zeros shaped like ``scatter_leaf``'s output for a local leaf
    ``p`` — the stage-2 sharded grad-accumulation carry."""
    if not isinstance(spec, Replicated) and DATA_AXIS in spec_axes(spec):
        return jnp.zeros(p.shape, jnp.float32)
    k = -(-p.size // nd)
    return jnp.zeros((k,), jnp.float32)


def tree_map_specs(fn, specs, *trees):
    """tree_map with PartitionSpec/Replicated leaves treated as leaves
    of the spec tree."""
    return jax.tree_util.tree_map(fn, specs, *trees, is_leaf=is_spec)


def concrete_specs(specs):
    """Replace Replicated sentinels with P() — the form shard_map's
    in/out_specs and NamedSharding accept."""
    return tree_map_specs(
        lambda s: P() if isinstance(s, Replicated) else s, specs)
