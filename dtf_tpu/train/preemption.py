"""Preemption-aware graceful shutdown.

On TPU pods SIGTERM is the preemption signal: the scheduler gives a
rank a short grace window before the hard kill.  The reference's story
was "Workers will need to restart training" (SURVEY §5.4) — work since
the last per-epoch checkpoint was simply lost.  Here SIGTERM/SIGINT is
caught, the train loop finishes the in-flight step, writes an
EMERGENCY checkpoint at the next step boundary (synchronous —
``Checkpointer.wait()`` before exit, so the save is durable and its
integrity manifest is committed), and the process exits with the
distinct ``EXIT_PREEMPTED`` code the launch.py supervisor classifies as
"preempted": restart WITHOUT consuming the crash-restart budget.

The handler is cooperative, not preemptive: it only sets a flag; the
loop polls it at step boundaries (``triggered()``), so device state is
never torn mid-step.  A second SIGINT restores the default handler —
an operator mashing Ctrl-C still gets the hard kill.
"""

from __future__ import annotations

import logging
import signal
import threading
from typing import Optional

log = logging.getLogger("dtf_tpu")

# Same value as cli/launch.py EXIT_PREEMPTED and chaos.EXIT_PREEMPTED
# (the supervisor is stdlib-only by design; parity is test-pinned).
EXIT_PREEMPTED = 75

_SIGNALS = (signal.SIGTERM, signal.SIGINT)


class Preempted(Exception):
    """Raised by the train loop at the step boundary after a
    preemption signal, once the emergency checkpoint is durable.
    Callers (cli/runner) translate it into SystemExit(EXIT_PREEMPTED)."""

    def __init__(self, step: int, signum: int):
        self.step = int(step)
        self.signum = int(signum)
        super().__init__(
            f"preempted by signal {signum} at step {step} "
            f"(emergency checkpoint written)")


class PreemptionGuard:
    """Installs SIGTERM/SIGINT handlers that latch the signal number.

    Only the main thread can install signal handlers; off-main-thread
    construction (tests driving run() from a worker) degrades to a
    guard that never triggers — the process keeps its default signal
    behavior."""

    def __init__(self):
        self._signum: Optional[int] = None
        self._old = {}
        self.active = False
        try:
            for sig in _SIGNALS:
                self._old[sig] = signal.signal(sig, self._handle)
            self.active = True
        except ValueError:  # not the main thread
            self._old = {}
            log.warning("preemption guard: not the main thread — "
                        "SIGTERM will NOT trigger a graceful checkpoint")

    def _handle(self, signum, frame):
        if self._signum is not None and signum == signal.SIGINT:
            # second Ctrl-C: the operator wants out NOW
            self.restore()
            raise KeyboardInterrupt
        first = self._signum is None
        self._signum = signum
        if first:
            log.warning("received signal %d — will write an emergency "
                        "checkpoint at the next step boundary and exit "
                        "%d (preempted)", signum, EXIT_PREEMPTED)

    def latch(self, signum: int = signal.SIGTERM) -> None:
        """Latch a preemption WITHOUT a delivered signal — the metadata
        poller's entry point (a pending GCE preemption is visible on
        the metadata server before the SIGTERM lands).  Same downstream
        path: the loop sees triggered() at the next step boundary."""
        self._handle(int(signum), None)

    @property
    def triggered(self) -> Optional[int]:
        return self._signum

    def restore(self) -> None:
        for sig, old in self._old.items():
            try:
                signal.signal(sig, old)
            except (ValueError, TypeError):
                pass
        self._old = {}
        self.active = False


_guard: Optional[PreemptionGuard] = None
_lock = threading.Lock()


def install() -> PreemptionGuard:
    """Install (or return) the process-global guard."""
    global _guard
    with _lock:
        if _guard is None or not _guard.active:
            _guard = PreemptionGuard()
        return _guard


def restore() -> None:
    """Uninstall the global guard and restore prior signal handlers."""
    global _guard
    with _lock:
        if _guard is not None:
            _guard.restore()
        _guard = None


def triggered() -> Optional[int]:
    """The latched preemption signal number, or None.  Fast: one global
    read — safe to poll every step."""
    g = _guard
    if g is None:
        return None
    return g.triggered


def latch(signum: int = signal.SIGTERM) -> None:
    """Latch a preemption on the global guard (no-op when none is
    installed — a bare poller without install() has nothing to feed)."""
    g = _guard
    if g is not None:
        g.latch(signum)


# GCE/TPU-VM metadata preemption endpoint: returns the string "TRUE"
# once the instance has a pending/acting preemption.  DTF_METADATA_URL
# overrides (tests run a local fake; other clouds have equivalents).
DEFAULT_METADATA_URL = ("http://metadata.google.internal/computeMetadata"
                        "/v1/instance/preempted")


class MetadataPoller:
    """Daemon-thread poll of the cloud metadata preemption endpoint.

    Most schedulers deliver SIGTERM directly and the PreemptionGuard
    handles it; this poller covers the window where the preemption is
    only visible on the metadata server (and hosts where the signal is
    swallowed by a wrapper).  On "TRUE" it feeds the SAME latch, so the
    downstream path — emergency checkpoint at the step boundary, exit
    EXIT_PREEMPTED, unbudgeted supervisor restart — is identical and
    stays test-pinned once.

    Off by default (--preemption_poll_s 0).  An unreachable endpoint
    (not on GCE) logs once at INFO and keeps polling quietly — the
    poller must be safe to leave enabled in any environment."""

    def __init__(self, poll_s: float, url: Optional[str] = None):
        import os
        if poll_s <= 0:
            raise ValueError(f"poll_s must be > 0, got {poll_s}")
        self.poll_s = float(poll_s)
        self.url = (url or os.environ.get("DTF_METADATA_URL")
                    or DEFAULT_METADATA_URL)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._unreachable_logged = False
        self.preempted = False

    def poll_once(self) -> bool:
        """One metadata query; True when a preemption is pending.
        Network errors are 'not preempted' (logged once)."""
        import urllib.error
        import urllib.request
        req = urllib.request.Request(
            self.url, headers={"Metadata-Flavor": "Google"})
        try:
            with urllib.request.urlopen(
                    req, timeout=max(self.poll_s, 1.0)) as resp:
                body = resp.read(64).decode("utf-8", "replace")
            return body.strip().upper() == "TRUE"
        except (urllib.error.URLError, OSError, ValueError):
            if not self._unreachable_logged:
                self._unreachable_logged = True
                log.info("preemption poller: metadata endpoint %s "
                         "unreachable — polling continues quietly "
                         "(expected off-GCE)", self.url)
            return False

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            if self.poll_once():
                self.preempted = True
                log.warning("preemption poller: metadata server reports "
                            "a pending preemption — latching SIGTERM "
                            "(emergency checkpoint at the next step "
                            "boundary)")
                latch(signal.SIGTERM)
                return

    def start(self) -> "MetadataPoller":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="dtf-preempt-poll")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.poll_s + 2.0)
            self._thread = None
