"""Preemption-aware graceful shutdown.

On TPU pods SIGTERM is the preemption signal: the scheduler gives a
rank a short grace window before the hard kill.  The reference's story
was "Workers will need to restart training" (SURVEY §5.4) — work since
the last per-epoch checkpoint was simply lost.  Here SIGTERM/SIGINT is
caught, the train loop finishes the in-flight step, writes an
EMERGENCY checkpoint at the next step boundary (synchronous —
``Checkpointer.wait()`` before exit, so the save is durable and its
integrity manifest is committed), and the process exits with the
distinct ``EXIT_PREEMPTED`` code the launch.py supervisor classifies as
"preempted": restart WITHOUT consuming the crash-restart budget.

The handler is cooperative, not preemptive: it only sets a flag; the
loop polls it at step boundaries (``triggered()``), so device state is
never torn mid-step.  A second SIGINT restores the default handler —
an operator mashing Ctrl-C still gets the hard kill.
"""

from __future__ import annotations

import logging
import signal
import threading
from typing import Optional

log = logging.getLogger("dtf_tpu")

# Same value as cli/launch.py EXIT_PREEMPTED and chaos.EXIT_PREEMPTED
# (the supervisor is stdlib-only by design; parity is test-pinned).
EXIT_PREEMPTED = 75

_SIGNALS = (signal.SIGTERM, signal.SIGINT)


class Preempted(Exception):
    """Raised by the train loop at the step boundary after a
    preemption signal, once the emergency checkpoint is durable.
    Callers (cli/runner) translate it into SystemExit(EXIT_PREEMPTED)."""

    def __init__(self, step: int, signum: int):
        self.step = int(step)
        self.signum = int(signum)
        super().__init__(
            f"preempted by signal {signum} at step {step} "
            f"(emergency checkpoint written)")


class PreemptionGuard:
    """Installs SIGTERM/SIGINT handlers that latch the signal number.

    Only the main thread can install signal handlers; off-main-thread
    construction (tests driving run() from a worker) degrades to a
    guard that never triggers — the process keeps its default signal
    behavior."""

    def __init__(self):
        self._signum: Optional[int] = None
        self._old = {}
        self.active = False
        try:
            for sig in _SIGNALS:
                self._old[sig] = signal.signal(sig, self._handle)
            self.active = True
        except ValueError:  # not the main thread
            self._old = {}
            log.warning("preemption guard: not the main thread — "
                        "SIGTERM will NOT trigger a graceful checkpoint")

    def _handle(self, signum, frame):
        if self._signum is not None and signum == signal.SIGINT:
            # second Ctrl-C: the operator wants out NOW
            self.restore()
            raise KeyboardInterrupt
        first = self._signum is None
        self._signum = signum
        if first:
            log.warning("received signal %d — will write an emergency "
                        "checkpoint at the next step boundary and exit "
                        "%d (preempted)", signum, EXIT_PREEMPTED)

    @property
    def triggered(self) -> Optional[int]:
        return self._signum

    def restore(self) -> None:
        for sig, old in self._old.items():
            try:
                signal.signal(sig, old)
            except (ValueError, TypeError):
                pass
        self._old = {}
        self.active = False


_guard: Optional[PreemptionGuard] = None
_lock = threading.Lock()


def install() -> PreemptionGuard:
    """Install (or return) the process-global guard."""
    global _guard
    with _lock:
        if _guard is None or not _guard.active:
            _guard = PreemptionGuard()
        return _guard


def restore() -> None:
    """Uninstall the global guard and restore prior signal handlers."""
    global _guard
    with _lock:
        if _guard is not None:
            _guard.restore()
        _guard = None


def triggered() -> Optional[int]:
    """The latched preemption signal number, or None.  Fast: one global
    read — safe to poll every step."""
    g = _guard
    if g is None:
        return None
    return g.triggered
