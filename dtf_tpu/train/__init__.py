from dtf_tpu.train.loop import Trainer, TrainState  # noqa: F401
from dtf_tpu.train import schedules  # noqa: F401
