"""The SPMD training/eval loop — the `model.fit` equivalent.

Re-expresses the reference's Keras-fit semantics (SURVEY §7.3) in a
custom jitted loop:
  - per-step LR schedule inside the compiled step (replacing
    LearningRateBatchScheduler, common.py:36-73)
  - TimeHistory BenchmarkMetric cadence (utils.logs)
  - `epochs_between_evals`, `train_steps` cap, `skip_eval`
    (reference resnet_cifar_main.py:176-214)
  - build_stats-compatible result dict (common.py:202-245)
  - fp16 static loss scaling parity (resnet_imagenet_main.py:182-187);
    bf16 (the TPU-native mixed mode) needs none

Parallelism: one SPMD core for every strategy (SURVEY §2.2).  The step
is `jit(shard_map(...))` over the runtime mesh: each data-shard computes
a local forward/backward (per-replica BatchNorm statistics — the
reference's implicit MirroredStrategy choice), gradients and metrics are
`lax.pmean`-ed over the 'data' axis (XLA emits the ICI/DCN all-reduce —
the NCCL-ring / collective-allreduce / grpc-push-pull equivalent), and
every replica applies an identical update.  Params live replicated;
state buffers are donated so updates are in-place in HBM.
"""

from __future__ import annotations

import logging
import os
import time
from functools import partial
from typing import Any, Callable, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from flax import struct
from jax.sharding import NamedSharding, PartitionSpec as P

from dtf_tpu import chaos
from dtf_tpu.config import Config
from dtf_tpu.data.base import DatasetSpec
from dtf_tpu.models.partition import spec_axes as _spec_axes
from dtf_tpu.models.registry import l2_weight_penalty
from dtf_tpu.obs import trace
from dtf_tpu.obs.watchdog import (Heartbeat, NanLossWatchdog,
                                  StepTimeWatchdog)
from dtf_tpu.runtime.mesh import (DATA_AXIS, MODEL_AXIS, SEQ_AXIS,
                                  MeshRuntime)
from dtf_tpu.train import preemption
from dtf_tpu.train import schedules as sched_lib
from dtf_tpu.train import zero as zero_lib
from dtf_tpu.train.optimizer import build_optimizer
from dtf_tpu.utils.logs import TimeHistory, build_stats

log = logging.getLogger("dtf_tpu")


@struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: Any
    # dynamic loss scaling only (--loss_scale dynamic): the live scale
    # and the count of consecutive finite steps; None under static
    # scaling (None is an empty pytree — costs nothing)
    loss_scale: Any = None
    good_steps: Any = None


# TF2 LossScaleOptimizer dynamic defaults (reference
# resnet_imagenet_main.py:182-187 wraps the optimizer in one)
DYNAMIC_SCALE_INIT = 2.0 ** 15
DYNAMIC_GROWTH_INTERVAL = 2000


# ZeRO slice layout + per-leaf collective helpers live in
# dtf_tpu/train/zero.py (shared with the canonical-checkpoint
# conversions); loop.py only orchestrates them per stage.


def per_example_cross_entropy(logits, labels):
    """Un-reduced CE with integer labels — one value per position."""
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels)


def cross_entropy(logits, labels):
    """Mean CE with integer labels; numerically identical to the
    reference's categorical CE over one-hot labels."""
    return jnp.mean(per_example_cross_entropy(logits, labels))


def sharded_per_example_cross_entropy(local_logits, labels, axis: str):
    """Un-reduced CE over vocab-sharded logits (Megatron's vocab-parallel
    softmax): a collective logsumexp over the model axis — the full
    vocab dimension never materializes on one shard.

    The two reductions are g-operator psums (`tp_psum`: sum forward,
    identity backward), which yields exactly the gradient of one loss
    replica; the max is stop-gradiented (it cancels analytically)."""
    from dtf_tpu.parallel.collectives import tp_psum

    vloc = local_logits.shape[-1]
    offset = lax.axis_index(axis) * vloc
    # stop_gradient *before* pmax: pmax has no differentiation rule,
    # and the max shift cancels analytically in the CE gradient anyway
    m = lax.pmax(jnp.max(lax.stop_gradient(local_logits), -1), axis)
    sumexp = tp_psum(
        jnp.sum(jnp.exp(local_logits - m[..., None]), -1), axis)
    lse = jnp.log(sumexp) + m
    local_label = labels - offset
    in_range = jnp.logical_and(local_label >= 0, local_label < vloc)
    safe = jnp.clip(local_label, 0, vloc - 1)
    picked = jnp.take_along_axis(local_logits, safe[..., None], -1)[..., 0]
    correct = tp_psum(jnp.where(in_range, picked, 0.0), axis)
    return lse - correct


def sharded_cross_entropy(local_logits, labels, axis: str):
    """Mean CE over vocab-sharded logits."""
    return jnp.mean(
        sharded_per_example_cross_entropy(local_logits, labels, axis))


def sharded_argmax(local_logits, axis: str):
    """Global argmax over vocab-sharded logits (metrics only — not
    differentiated).  Ties resolve to the lowest global index, matching
    jnp.argmax on the equivalent unsharded logits (within a shard
    jnp.argmax already picks the lowest; across shards the pmin does)."""
    # callers may sit inside a differentiated function (train-step
    # metrics) and pmax/pmin have no differentiation rule
    local_logits = lax.stop_gradient(local_logits)
    vloc = local_logits.shape[-1]
    offset = lax.axis_index(axis) * vloc
    local_max = jnp.max(local_logits, -1)
    local_arg = jnp.argmax(local_logits, -1) + offset
    best = lax.pmax(local_max, axis)
    sentinel = jnp.iinfo(local_arg.dtype).max
    cand = jnp.where(local_max == best, local_arg, sentinel)
    return lax.pmin(cand, axis)


class Trainer:
    """Builds jitted SPMD train/eval steps and runs the fit loop."""

    def __init__(self, cfg: Config, runtime: MeshRuntime, model,
                 l2_weight: float, spec: DatasetSpec,
                 schedule: Optional[Callable] = None,
                 param_spec_fn: Optional[Callable] = None,
                 vocab_axis: Optional[str] = None,
                 normalize_fn: Optional[Callable] = None):
        self.cfg = cfg
        self.rt = runtime
        self.model = model
        self.l2_weight = l2_weight
        self.spec = spec
        # uint8 wire: pipelines ship raw uint8 pixels and this runs as
        # the FIRST op inside the compiled train/eval step (f32 math
        # on-chip, fused by XLA into the first conv's input) — the
        # TPU-native placement of the reference's in-graph
        # normalization (imagenet_preprocessing.py:397-430).  None =
        # host-normalized f32 wire.
        self.normalize_fn = normalize_fn
        # vocab-sharded lm_head: logits arrive [B, S, V/mp] and the
        # loss/metrics go through the collective softmax forms
        self.vocab_axis = vocab_axis
        # tensor parallelism: fn(params) -> PartitionSpec tree sharding
        # params over the 'model' axis (e.g. transformer.
        # param_partition_specs).  The L2 penalty is sharding-aware
        # (l2_weight_penalty psums each sharded leaf over its axes).
        self.param_spec_fn = param_spec_fn

        # ---- epoch math (SURVEY §3.3/3.4 steps//size semantics) ----
        # cfg.batch_size is the GLOBAL batch. In horovod/parameter_server
        # parity modes the reference flag was per-worker; the CLI layer
        # multiplies by process count before we get here.
        self.global_batch = cfg.batch_size
        if self.global_batch % runtime.num_replicas:
            raise ValueError(
                f"global batch_size {self.global_batch} must be divisible by "
                f"the number of data-parallel replicas "
                f"({runtime.num_replicas}); pick a batch size that is a "
                f"multiple, or reduce --num_devices")
        self.grad_accum = max(int(cfg.grad_accum_steps or 1), 1)
        if (self.global_batch // runtime.num_replicas) % self.grad_accum:
            raise ValueError(
                f"per-replica batch "
                f"{self.global_batch // runtime.num_replicas} must be "
                f"divisible by grad_accum_steps ({self.grad_accum})")
        if spec.is_sequence:
            sp = runtime.mesh.shape[SEQ_AXIS]
            if spec.seq_len % sp:
                raise ValueError(
                    f"seq_len {spec.seq_len} must be divisible by "
                    f"seq_parallelism ({sp})")
        self.steps_per_epoch = spec.num_train // self.global_batch
        if self.steps_per_epoch == 0:
            raise ValueError(
                f"batch_size {self.global_batch} exceeds the training set "
                f"({spec.num_train} examples): zero steps per epoch")
        self.train_epochs = cfg.train_epochs
        if cfg.train_steps:
            # reference mains: train_steps caps to 1 epoch of that length
            self.steps_per_epoch = min(cfg.train_steps, self.steps_per_epoch)
            self.train_epochs = 1
        # --data_format: the reference honors channels_first by setting
        # the Keras image data format (resnet_cifar_main.py:94-98).
        # Here NCHW batches are accepted and transposed to NHWC inside
        # the compiled step (free: XLA folds the transpose into the
        # first conv's layout assignment); compute stays NHWC for the
        # MXU either way.
        self.channels_first = (cfg.data_format == "channels_first"
                               and not spec.is_sequence)

        if schedule is not None:
            self.schedule = schedule
        elif cfg.distribution_strategy == "horovod":
            # horovod-parity: constant size-scaled LR with 3-epoch warmup
            # replaces the piecewise schedule (SURVEY §3.3)
            self.schedule = sched_lib.horovod_schedule(
                runtime.num_replicas, max(self.steps_per_epoch, 1))
        else:
            self.schedule = sched_lib.for_dataset(
                spec.name, self.global_batch, max(self.steps_per_epoch, 1),
                spec.num_train, use_tensor_lr=cfg.use_tensor_lr,
                train_epochs=self.train_epochs)
        self.tx = build_optimizer(cfg.optimizer, self.schedule)
        self.dynamic_scale = cfg.loss_scale_value == "dynamic"
        self.loss_scale = (1.0 if self.dynamic_scale
                           else float(cfg.loss_scale_value))

        # ZeRO weight-update sharding (PAPERS.md: Xu et al. 2020),
        # stages 1-3 on the data axis (train/zero.py has the layout
        # contract).  Stage 1: optimizer state sliced, grads
        # reduce-scatter, updated slices all-gather back.  Stage 2: the
        # grad-accumulation carry holds 1/nd slices — each microbatch's
        # grads scatter as the backward produces them.  Stage 3: params
        # themselves live sliced and all-gather per leaf at the top of
        # the step.  Composes with TP/EP/PP param sharding:
        # model-sharded leaves slice their *local* shard over 'data'
        # (spec ('data','model')); expert leaves riding 'data' keep
        # locally-shaped state (zero_lib.zero_leaf_spec).
        self.zero_stage = cfg.zero_stage_effective
        self.zero = self.zero_stage >= 1
        # --zero_wire bf16: the per-microbatch grad reduce-scatter
        # crosses the wire (and sums) in bf16, halving stage-2/3
        # scatter volume; the returned slices and the cross-microbatch
        # accumulation carry stay f32 (the --ps_wire bf16 trade,
        # applied to the FSDP path — documented loss tolerance pinned
        # by tests/test_zero_stages.py)
        self.zero_wire = (jnp.bfloat16
                          if getattr(cfg, "zero_wire", "fp32") == "bf16"
                          else jnp.float32)

        if self.param_spec_fn is None and not self.zero:
            self._build_steps()
        # else: the state spec tree needs the concrete param structure —
        # steps are built in init_state

    # ------------------------------------------------------------------
    def init_state(self, rng: jax.Array, sample_batch) -> TrainState:
        """Seed-synced replicated init — the Horovod
        BroadcastGlobalVariablesCallback(0) equivalent (SURVEY §2.2):
        every process initializes from the same seed, so params are
        identical without a broadcast."""
        images = jnp.asarray(sample_batch[0][:1])
        if self.channels_first:
            images = jnp.transpose(images, (0, 2, 3, 1))
        if self.normalize_fn is not None:
            images = self.normalize_fn(images)
        # a seq- or model-sharded module calls collectives and can only
        # run inside shard_map; param *shapes* don't depend on those
        # axes (TP shards arrive by sharding the full arrays), so init
        # with an unsharded twin
        init_model = self.model
        clone_kw = {k: None
                    for k in ("seq_axis", "model_axis", "expert_axis",
                              "pipe_axis")
                    if getattr(init_model, k, None) is not None}
        if clone_kw and getattr(init_model, "interleave", 1) != 1:
            # param shapes don't depend on the visitation order either
            clone_kw["interleave"] = 1
        if clone_kw:
            init_model = init_model.clone(**clone_kw)
        variables = jax.jit(init_model.init, static_argnames=("train",))(
            rng, images, train=False)
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        if self.zero:
            # optimizer state over PADDED FLAT leaves [nd·k] (per
            # (data, model) coordinate when the param is model-sharded;
            # locally-shaped for expert leaves — zero_lib.zero_leaf_spec).
            # Init under jit with sharded out_shardings so the full
            # state never materializes on one device (the transient
            # spike would OOM exactly the model sizes this targets)
            from dtf_tpu.train.optimizer import (ZEROS_INIT_OPTIMIZERS,
                                                 opt_state_specs)
            # This proto trick only holds for value-independent inits
            # (state is zeros whatever the params are) — enforced so a
            # future optimizer can't silently get wrong ZeRO state.
            assert self.cfg.optimizer in ZEROS_INIT_OPTIMIZERS, (
                f"ZeRO init uses zero-valued protos; optimizer "
                f"{self.cfg.optimizer!r} is not registered as having a "
                f"value-independent init (optimizer.ZEROS_INIT_OPTIMIZERS)")
            is_p = lambda x: isinstance(x, P)
            nd = self.rt.mesh.shape[DATA_AXIS]
            mesh_shape = dict(self.rt.mesh.shape)
            pspecs = (self.param_spec_fn(params)
                      if self.param_spec_fn is not None
                      else jax.tree_util.tree_map(lambda _: P(), params))
            # elastic shrink/grow resumes land here with an ARBITRARY
            # surviving mesh: leaves whose model spec pins a tensor dim
            # to a mesh axis (experts over 'data', TP/PP over 'model')
            # must refuse a non-dividing topology loudly — the ZeRO
            # flat-slice layout itself reshards onto any nd by
            # construction (pad_flat zero-pads to the new grid)
            from dtf_tpu.train import elastic as elastic_lib
            problems = elastic_lib.check_reshardable(
                pspecs, params, mesh_shape)
            if problems:
                raise ValueError(
                    "model cannot shard onto this mesh (an elastic "
                    "resume must refuse, not garble): "
                    + "; ".join(problems))
            opt_pspecs = jax.tree_util.tree_map(zero_lib.zero_leaf_spec,
                                                pspecs, is_leaf=is_p)

            def proto_leaf(spec, p):
                axes = _spec_axes(spec)
                if DATA_AXIS in axes:
                    return jax.ShapeDtypeStruct(p.shape, p.dtype)
                msz = 1
                for a in axes:
                    msz *= mesh_shape[a]
                k = -(-(p.size // msz) // nd)
                return jax.ShapeDtypeStruct((nd * msz * k,), p.dtype)

            protos = jax.tree_util.tree_map(proto_leaf, pspecs, params,
                                            is_leaf=is_p)
            ospecs = opt_state_specs(self.cfg.optimizer, opt_pspecs, P())
            oshard = jax.tree_util.tree_map(
                lambda s: NamedSharding(self.rt.mesh, s), ospecs,
                is_leaf=is_p)
            opt_state = jax.jit(
                lambda: self.tx.init(jax.tree_util.tree_map(
                    lambda s: jnp.zeros(s.shape, s.dtype), protos)),
                out_shardings=oshard)()
        else:
            opt_state = self.tx.init(params)
        state = TrainState(
            step=jnp.zeros((), jnp.int32), params=params,
            batch_stats=batch_stats, opt_state=opt_state,
            loss_scale=(jnp.float32(DYNAMIC_SCALE_INIT)
                        if self.dynamic_scale else None),
            good_steps=(jnp.zeros((), jnp.int32)
                        if self.dynamic_scale else None))
        if self.zero:
            # static trees the stage-3 gather and the canonical-
            # checkpoint conversions close over: the model partition
            # specs, and each leaf's shard_map-LOCAL full shape
            self._zero_pspecs = pspecs
            self._zero_local_sds = jax.tree_util.tree_map(
                lambda spec, p: jax.ShapeDtypeStruct(
                    zero_lib.local_shape(spec, p.shape, mesh_shape),
                    p.dtype),
                pspecs, params, is_leaf=is_p)
            param_state_specs = pspecs
            if self.zero_stage == 3:
                # params themselves live as ZeRO slices
                param_state_specs = jax.tree_util.tree_map(
                    zero_lib.zero_leaf_spec, pspecs, is_leaf=is_p)
            state_specs = self._make_zero_state_specs(
                state, param_state_specs, opt_pspecs)
            self._state_specs = state_specs
            self._build_canonical(state, pspecs, opt_pspecs, state_specs)
            if self.zero_stage == 3:
                # move the seed-synced replicated init into the sliced
                # layout (the replicated copy is a transient of init;
                # restores go through staged_state and never rebuild it)
                state = state.replace(params=self._slice_params(params))
            self._build_steps(state_specs)
            shardings = jax.tree_util.tree_map(
                lambda s: NamedSharding(self.rt.mesh, s), state_specs,
                is_leaf=lambda x: isinstance(x, P))
            return jax.device_put(state, shardings)
        if self.param_spec_fn is None:
            # replicate across the mesh
            return jax.device_put(state, self.rt.replicated())
        # tensor parallelism: per-leaf shardings; kernels/moments split
        # over the 'model' axis, everything else replicated
        state_specs = self._make_state_specs(state)
        self._build_steps(state_specs)
        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(self.rt.mesh, s), state_specs,
            is_leaf=lambda x: isinstance(x, P))
        return jax.device_put(state, shardings)

    def _make_zero_state_specs(self, state: TrainState, param_specs,
                               opt_pspecs):
        from dtf_tpu.train.optimizer import opt_state_specs
        rep = P()
        return TrainState(
            step=rep,
            params=param_specs,
            batch_stats=jax.tree_util.tree_map(lambda _: rep,
                                               state.batch_stats),
            opt_state=opt_state_specs(self.cfg.optimizer, opt_pspecs, rep),
            loss_scale=rep if self.dynamic_scale else None,
            good_steps=rep if self.dynamic_scale else None)

    def _make_state_specs(self, state: TrainState):
        from dtf_tpu.train.optimizer import opt_state_specs
        pspecs = self.param_spec_fn(state.params)
        rep = P()
        return TrainState(
            step=rep,
            params=pspecs,
            batch_stats=jax.tree_util.tree_map(lambda _: rep,
                                               state.batch_stats),
            opt_state=opt_state_specs(self.cfg.optimizer, pspecs, rep),
            loss_scale=rep if self.dynamic_scale else None,
            good_steps=rep if self.dynamic_scale else None)

    # ------------------------------------------------------------------
    # Canonical checkpoint form (ZeRO stages).  Checkpoints are always
    # WRITTEN in the stage-0 layout — full-shaped params and optimizer
    # state — so a checkpoint saved at any ZeRO stage restores into any
    # other stage and into serving via the bridge's structure-free
    # loader.  The conversions are pure per-leaf reshapes/collectives
    # (train/zero.py): gather-trim-reshape out, pad-flatten-slice back
    # in.  Padding rows are zeros in every supported optimizer's state
    # (optimizer.ZEROS_INIT_OPTIMIZERS), so dropping them on save and
    # re-creating them on restore is exact — the round trip is
    # bit-identical, which is what keeps killed-at-K resume trajectory-
    # exact under ZeRO-3 (tests/test_zero_stages.py).
    # ------------------------------------------------------------------
    def _build_canonical(self, state: TrainState, pspecs, opt_pspecs,
                         state_specs):
        from dtf_tpu.train.optimizer import opt_state_specs
        mesh = self.rt.mesh
        nd = mesh.shape[DATA_AXIS]
        is_p = zero_lib.is_spec
        stage3 = self.zero_stage == 3
        local_sds = self._zero_local_sds
        # canonical spec/shape trees: params carry the model partition
        # specs; optimizer leaves mirror their params, with genuinely
        # replicated leaves (the adam step count) marked REP so the
        # converters know there is nothing to slice
        opt_canon_specs = opt_state_specs(self.cfg.optimizer, pspecs,
                                          zero_lib.REP)
        opt_local_sds = opt_state_specs(
            self.cfg.optimizer, local_sds,
            jax.ShapeDtypeStruct((), jnp.int32))
        canon_specs = TrainState(
            step=P(), params=zero_lib.concrete_specs(pspecs),
            batch_stats=jax.tree_util.tree_map(lambda _: P(),
                                               state.batch_stats),
            opt_state=zero_lib.concrete_specs(opt_canon_specs),
            loss_scale=P() if self.dynamic_scale else None,
            good_steps=P() if self.dynamic_scale else None)
        self._canon_specs = canon_specs

        def gather_opt_leaf(spec, sds, leaf):
            return zero_lib.gather_leaf(spec, leaf, sds.shape, sds.dtype,
                                        nd)

        def to_canonical_local(st: TrainState) -> TrainState:
            p = st.params
            if stage3:
                p = zero_lib.tree_map_specs(gather_opt_leaf, pspecs,
                                            local_sds, p)
            opt = zero_lib.tree_map_specs(gather_opt_leaf,
                                          opt_canon_specs, opt_local_sds,
                                          st.opt_state)
            return st.replace(params=p, opt_state=opt)

        def to_staged_local(st: TrainState) -> TrainState:
            idx = lax.axis_index(DATA_AXIS)
            p = st.params
            if stage3:
                p = zero_lib.tree_map_specs(
                    lambda spec, leaf: zero_lib.slice_leaf(spec, leaf, nd,
                                                           idx),
                    pspecs, p)
            opt = zero_lib.tree_map_specs(
                lambda spec, leaf: zero_lib.slice_leaf(spec, leaf, nd,
                                                       idx),
                opt_canon_specs, st.opt_state)
            return st.replace(params=p, opt_state=opt)

        self._to_canonical = jax.jit(jax.shard_map(
            to_canonical_local, mesh=mesh, in_specs=(state_specs,),
            out_specs=canon_specs, check_vma=False))
        self._to_staged = jax.jit(jax.shard_map(
            to_staged_local, mesh=mesh, in_specs=(canon_specs,),
            out_specs=state_specs, check_vma=False))

        def slice_params_local(p):
            idx = lax.axis_index(DATA_AXIS)
            return zero_lib.tree_map_specs(
                lambda spec, leaf: zero_lib.slice_leaf(spec, leaf, nd,
                                                       idx),
                pspecs, p)

        self._slice_params = jax.jit(jax.shard_map(
            slice_params_local, mesh=mesh,
            in_specs=(zero_lib.concrete_specs(pspecs),),
            out_specs=state_specs.params, check_vma=False))

        template = TrainState(
            step=jax.ShapeDtypeStruct((), jnp.int32),
            params=jax.tree_util.tree_map(
                lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype),
                state.params),
            batch_stats=jax.tree_util.tree_map(
                lambda b: jax.ShapeDtypeStruct(b.shape, b.dtype),
                state.batch_stats),
            opt_state=jax.eval_shape(self.tx.init, state.params),
            loss_scale=(jax.ShapeDtypeStruct((), jnp.float32)
                        if self.dynamic_scale else None),
            good_steps=(jax.ShapeDtypeStruct((), jnp.int32)
                        if self.dynamic_scale else None))
        # restore places directly into the canonical shardings (a TP
        # leaf never materializes replicated on one device)
        canon_shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), canon_specs,
            is_leaf=lambda x: isinstance(x, P))
        self._canonical_template = jax.tree_util.tree_map(
            lambda sds, sh: jax.ShapeDtypeStruct(sds.shape, sds.dtype,
                                                 sharding=sh),
            template, canon_shardings)

    def canonical_state(self, state: TrainState) -> TrainState:
        """The stage-0 (checkpoint wire) form of a live TrainState —
        identity for non-ZeRO runs."""
        if not self.zero:
            return state
        return self._to_canonical(state)

    def staged_state(self, canonical: TrainState) -> TrainState:
        """A restored canonical TrainState placed into THIS run's stage
        layout (sliced params/optimizer state, proper shardings)."""
        if not self.zero:
            return canonical
        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(self.rt.mesh, s), self._canon_specs,
            is_leaf=lambda x: isinstance(x, P))
        return self._to_staged(jax.device_put(canonical, shardings))

    def canonical_template(self):
        """ShapeDtypeStruct tree of the canonical checkpoint form (the
        restore template — stage-independent).  Only meaningful after
        init_state on a ZeRO run; non-ZeRO runs restore against the
        live state directly."""
        assert self.zero, "canonical_template is the ZeRO restore path"
        return self._canonical_template

    # ------------------------------------------------------------------
    def _apply(self, params, batch_stats, images, train):
        variables = {"params": params}
        if batch_stats:
            variables["batch_stats"] = batch_stats
        if train:
            # "aux_loss" collects regularizers sown by modules (MoE
            # load-balance); empty for every dense model
            mutable = (["batch_stats"] if batch_stats else []) + ["aux_loss"]
            out, mutated = self.model.apply(
                variables, images, train=True, mutable=mutable)
            new_stats = mutated.get("batch_stats", batch_stats) if batch_stats else batch_stats
            aux_leaves = jax.tree_util.tree_leaves(
                mutated.get("aux_loss", {}))
            aux = (jnp.sum(jnp.stack([a.astype(jnp.float32)
                                      for a in aux_leaves]))
                   if aux_leaves else jnp.zeros((), jnp.float32))
            return out, new_stats, aux
        return self.model.apply(variables, images, train=False), batch_stats

    def _build_steps(self, state_specs=None, comm_off=False):
        """Builds the jitted SPMD train/eval steps.  ``comm_off=True``
        builds and RETURNS the ``--zero_probe`` timing twin instead of
        installing it: the same step with every data-axis ZeRO
        collective replaced by a shape-right local stub (train/zero.py)
        — its wall time is the step minus those collectives, which is
        what turns exposed-comm into a measured number.  Twin results
        are garbage by construction and must never become state."""
        mesh = self.rt.mesh
        # token data shards [B, S] over (data, seq); vision shards dim 0
        if self.spec.is_sequence:
            data_spec = P(DATA_AXIS, SEQ_AXIS)
        else:
            data_spec = P(DATA_AXIS)
        # gradients/metrics average over every axis the batch is split
        # across; 'seq' has size 1 (identity) for vision runs
        reduce_axes = (DATA_AXIS, SEQ_AXIS)
        rep = P()
        loss_scale = self.loss_scale
        l2w = self.l2_weight

        # Per-leaf gradient reduction.  Replicated leaves pmean over
        # every batch-splitting axis (the NCCL-ring / collective
        # allreduce equivalent).  Leaves *sharded over* a batch axis
        # (MoE experts ride 'data') must not be pmean-ed there — that
        # would average different experts' grads; reverse-mode
        # all_to_all already summed their true grads across the group,
        # so they are divided by the axis size to match the global-mean
        # loss convention instead.
        param_specs = None if state_specs is None else state_specs.params
        if self.zero_stage == 3 and state_specs is not None:
            # state_specs.params is the SLICED layout; the step's grad
            # reduction / clipping / L2 reason about the gathered full
            # params, whose layout is the model partition specs
            param_specs = self._zero_pspecs
        local_sds = getattr(self, "_zero_local_sds", None)
        mesh_shape = dict(mesh.shape)
        nd = mesh_shape[DATA_AXIS]
        zero_stage = self.zero_stage
        zero_wire = self.zero_wire

        def reduce_grads(grads):
            if param_specs is None:
                return jax.lax.pmean(grads, reduce_axes)

            def red(spec, g):
                sharded = _spec_axes(spec)
                axes = tuple(a for a in reduce_axes if a not in sharded)
                if axes:
                    g = jax.lax.pmean(g, axes)
                denom = 1
                for a in reduce_axes:
                    if a in sharded:
                        denom *= mesh_shape[a]
                if denom > 1:
                    g = (g / denom).astype(g.dtype)
                return g

            return jax.tree_util.tree_map(
                red, param_specs, grads,
                is_leaf=lambda x: isinstance(x, P))

        clip_norm = self.cfg.clip_grad_norm

        def clip_grads(grads):
            """Clip to the TRUE global L2 norm: a leaf sharded over a
            mesh axis holds distinct elements per shard, so its local
            sum-of-squares is psum-ed over that axis; replicated leaves
            contribute their full sum once.  Every shard computes the
            same norm, so the scaling stays replica-consistent."""
            if not clip_norm:
                return grads

            def leaf_sumsq(spec, g):
                ss = jnp.sum(jnp.square(g.astype(jnp.float32)))
                axes = tuple(_spec_axes(spec)) if spec is not None else ()
                if axes:
                    ss = lax.psum(ss, axes)
                return ss

            if param_specs is None:
                parts = jax.tree_util.tree_map(
                    lambda g: leaf_sumsq(None, g), grads)
            else:
                parts = jax.tree_util.tree_map(
                    leaf_sumsq, param_specs, grads,
                    is_leaf=lambda x: isinstance(x, P))
            sumsq = sum(jax.tree_util.tree_leaves(parts))
            norm = jnp.sqrt(sumsq)
            factor = jnp.minimum(1.0, clip_norm / jnp.maximum(norm, 1e-12))
            return jax.tree_util.tree_map(
                lambda g: (g * factor).astype(g.dtype), grads)

        dynamic = self.dynamic_scale
        vocab_axis = self.vocab_axis
        zero = self.zero
        channels_first = self.channels_first
        # --report_accuracy_metrics false (reference common.py:277-278):
        # drop the in-step accuracy compute entirely for benchmark purity
        report_acc = self.cfg.report_accuracy_metrics

        def compute_ce(logits, labels):
            if vocab_axis is not None:
                return sharded_cross_entropy(logits, labels, vocab_axis)
            return cross_entropy(logits, labels)

        def compute_per_example_ce(logits, labels):
            if vocab_axis is not None:
                return sharded_per_example_cross_entropy(
                    logits, labels, vocab_axis)
            return per_example_cross_entropy(logits, labels)

        def compute_correct(logits, labels):
            """Per-position 0/1 correctness, float32."""
            if vocab_axis is not None:
                preds = sharded_argmax(logits, vocab_axis)
            else:
                preds = jnp.argmax(logits, -1)
            return (preds == labels).astype(jnp.float32)

        def compute_acc(logits, labels):
            if not report_acc:
                return jnp.zeros((), jnp.float32)
            return jnp.mean(compute_correct(logits, labels))

        accum = self.grad_accum
        normalize = self.normalize_fn

        def local_train_step(state: TrainState, images, labels):
            if channels_first:
                images = jnp.transpose(images, (0, 2, 3, 1))
            if normalize is not None:
                images = normalize(images)
            scale = state.loss_scale if dynamic else loss_scale

            is_p = zero_lib.is_spec
            zspecs = param_specs
            if zero:
                idx = lax.axis_index(DATA_AXIS)

            # ZeRO-3: the params the model computes with are gathered
            # PER LEAF from their 1/nd slices at the top of the step —
            # each leaf's all_gather is an independent op feeding that
            # leaf's first use, so XLA's latency-hiding scheduler can
            # overlap later layers' gathers with earlier layers' compute
            if zero_stage == 3:
                model_params = jax.tree_util.tree_map(
                    lambda spec, sds, s: zero_lib.gather_leaf(
                        spec, s, sds.shape, sds.dtype, nd, comm_off),
                    zspecs, local_sds, state.params, is_leaf=is_p)
            else:
                model_params = state.params

            def grad_of_chunk(params, batch_stats, imgs, lbls):
                def loss_fn(p):
                    logits, new_stats, aux = self._apply(
                        p, batch_stats, imgs, train=True)
                    ce = compute_ce(logits, lbls)
                    loss = ce + l2_weight_penalty(p, l2w, param_specs) + aux
                    return loss * scale, (loss, compute_acc(logits, lbls),
                                          new_stats)
                return jax.grad(loss_fn, has_aux=True)(params)

            def scatter_tree(grads):
                return jax.tree_util.tree_map(
                    lambda spec, g: zero_lib.scatter_leaf(
                        spec, g, nd, reduce_axes, mesh_shape, comm_off,
                        idx, wire=zero_wire),
                    zspecs, grads, is_leaf=is_p)

            g_slices_acc = None
            if accum == 1:
                grads, (loss, acc, new_stats) = grad_of_chunk(
                    model_params, state.batch_stats, images, labels)
            elif zero_stage >= 2:
                # ZeRO-2/3 sharded gradient accumulation: each chunk's
                # grads reduce-scatter into f32 slices AS THE BACKWARD
                # PRODUCES THEM (per-leaf psum_scatter adjacent to its
                # producing op — XLA can overlap the wire with compute
                # and free each full grad immediately), so the scan
                # carry holds 1/nd-sized slices instead of a second
                # full gradient buffer
                chunks = jax.tree_util.tree_map(
                    lambda x: x.reshape((accum, x.shape[0] // accum)
                                        + x.shape[1:]), (images, labels))

                def body(carry, chunk):
                    gacc, stats, lacc, aacc = carry
                    g, (l, a, stats) = grad_of_chunk(
                        model_params, stats, *chunk)
                    gacc = jax.tree_util.tree_map(jnp.add, gacc,
                                                  scatter_tree(g))
                    return (gacc, stats, lacc + l, aacc + a), None

                zeros = jax.tree_util.tree_map(
                    lambda spec, p: zero_lib.slice_zeros(spec, p, nd),
                    zspecs, model_params, is_leaf=is_p)
                (gsum, new_stats, lsum, asum), _ = lax.scan(
                    body, (zeros, state.batch_stats,
                           jnp.zeros((), jnp.float32),
                           jnp.zeros((), jnp.float32)), chunks)
                g_slices_acc = jax.tree_util.tree_map(
                    lambda s: s / accum, gsum)
                grads = None
                loss, acc = lsum / accum, asum / accum
            else:
                # sequential microbatches: grads accumulate in the scan
                # carry (one buffer, not A stacked copies); BN stats
                # thread through exactly as A consecutive steps would
                chunks = jax.tree_util.tree_map(
                    lambda x: x.reshape((accum, x.shape[0] // accum)
                                        + x.shape[1:]), (images, labels))

                def body(carry, chunk):
                    gacc, stats, lacc, aacc = carry
                    g, (l, a, stats) = grad_of_chunk(
                        model_params, stats, *chunk)
                    gacc = jax.tree_util.tree_map(jnp.add, gacc, g)
                    return (gacc, stats, lacc + l, aacc + a), None

                zeros = jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, jnp.promote_types(
                        p.dtype, jnp.float32)), model_params)
                (gsum, new_stats, lsum, asum), _ = lax.scan(
                    body, (zeros, state.batch_stats,
                           jnp.zeros((), jnp.float32),
                           jnp.zeros((), jnp.float32)), chunks)
                grads = jax.tree_util.tree_map(
                    lambda g, p: (g / accum).astype(p.dtype),
                    gsum, model_params)
                loss, acc = lsum / accum, asum / accum
            if dynamic or loss_scale != 1.0:
                # linear, so unscaling slices ≡ unscaling full grads
                if g_slices_acc is not None:
                    g_slices_acc = jax.tree_util.tree_map(
                        lambda g: g / scale, g_slices_acc)
                else:
                    grads = jax.tree_util.tree_map(lambda g: g / scale,
                                                   grads)
            # per-replica BN stats averaged on update — MirroredStrategy's
            # variable aggregation semantics
            new_stats = jax.lax.pmean(new_stats, reduce_axes)

            if zero:
                # ZeRO weight-update sharding: the gradient all-reduce
                # becomes a reduce-scatter (same ICI volume), each data
                # shard updates its 1/nd slice with its 1/nd optimizer
                # state, and (stages 1-2) the updated slices all-gather
                # back — stage 3 keeps them sliced for the next step's
                # per-leaf gather.  Composed with model sharding: a
                # TP/PP leaf slices its LOCAL shard (scatter/gather
                # stay pure-'data' collectives); an expert leaf riding
                # 'data' updates in place (its grads were already
                # summed by the all_to_all transpose — divide to the
                # global-mean convention like reduce_grads does).
                g_slices = (g_slices_acc if g_slices_acc is not None
                            else scatter_tree(grads))
                if clip_norm:
                    def slice_sumsq(spec, s):
                        # each slice holds distinct elements across
                        # 'data' (and 'model' for model-sharded leaves)
                        axes = {DATA_AXIS} | (_spec_axes(spec)
                                              & {MODEL_AXIS})
                        return lax.psum(jnp.sum(jnp.square(s)),
                                        tuple(sorted(axes)))
                    parts = jax.tree_util.tree_map(slice_sumsq, zspecs,
                                                   g_slices, is_leaf=is_p)
                    sumsq = sum(jax.tree_util.tree_leaves(parts))
                    norm = jnp.sqrt(sumsq)
                    factor = jnp.minimum(
                        1.0, clip_norm / jnp.maximum(norm, 1e-12))
                    g_slices = jax.tree_util.tree_map(
                        lambda s: s * factor, g_slices)

                if zero_stage == 3:
                    # params already live as slices — no re-slicing
                    p_slices = state.params
                else:
                    p_slices = jax.tree_util.tree_map(
                        lambda spec, p: zero_lib.slice_leaf(spec, p, nd,
                                                            idx),
                        zspecs, state.params, is_leaf=is_p)
                updates, new_opt = self.tx.update(
                    g_slices, state.opt_state, p_slices, step=state.step)
                new_slices = optax.apply_updates(p_slices, updates)

                if zero_stage == 3:
                    # stay sliced: the NEXT step's per-leaf gather is
                    # this stage's one param collective
                    params = new_slices
                else:
                    params = jax.tree_util.tree_map(
                        lambda spec, ns, p: zero_lib.gather_leaf(
                            spec, ns, p.shape, p.dtype, nd, comm_off),
                        zspecs, new_slices, state.params, is_leaf=is_p)
                grads = g_slices  # the dynamic-scale finite check below
            else:
                # DEVICE/NETWORK BOUNDARY: gradient all-reduce over the
                # batch-splitting axes (≡ NCCL ring / collective
                # allreduce / PS push-pull, SURVEY §3); includes 'seq'
                # when the sequence dimension is sharded
                grads = reduce_grads(grads)
                grads = clip_grads(grads)
                updates, new_opt = self.tx.update(
                    grads, state.opt_state, state.params, step=state.step)
                params = optax.apply_updates(state.params, updates)
            new_scale, new_good = state.loss_scale, state.good_steps
            if dynamic:
                # TF2 LossScaleOptimizer semantics: skip the update on
                # non-finite grads and halve the scale; double it after
                # DYNAMIC_GROWTH_INTERVAL consecutive finite steps
                finite = jnp.array(True)
                for g in jax.tree_util.tree_leaves(grads):
                    finite = jnp.logical_and(finite,
                                             jnp.all(jnp.isfinite(g)))
                # every shard must reach the same verdict: a leaf
                # sharded over an axis (experts over 'data', TP/PP
                # stacks over 'model') can overflow on one shard only,
                # and a split decision would silently desynchronize
                # the replicated leaves and the scale itself
                finite = jax.lax.pmin(
                    finite.astype(jnp.int32),
                    (DATA_AXIS, SEQ_AXIS, MODEL_AXIS)).astype(bool)
                keep = lambda new, old: jax.tree_util.tree_map(
                    lambda n, o: jnp.where(finite, n, o), new, old)
                params = keep(params, state.params)
                new_opt = keep(new_opt, state.opt_state)
                new_stats = keep(new_stats, state.batch_stats)
                grew = state.good_steps + 1 >= DYNAMIC_GROWTH_INTERVAL
                new_scale = jnp.where(
                    finite,
                    jnp.where(grew, scale * 2.0, scale),
                    jnp.maximum(scale * 0.5, 1.0))
                new_good = jnp.where(jnp.logical_and(finite,
                                                     jnp.logical_not(grew)),
                                     state.good_steps + 1, 0)
            metrics = {
                "loss": jax.lax.pmean(loss, reduce_axes),
                "learning_rate": self.schedule(state.step),
            }
            if report_acc:
                metrics["accuracy"] = jax.lax.pmean(acc, reduce_axes)
            if dynamic:
                metrics["loss_scale"] = new_scale
            return TrainState(step=state.step + 1, params=params,
                              batch_stats=new_stats, opt_state=new_opt,
                              loss_scale=new_scale,
                              good_steps=new_good), metrics

        def local_eval_step(state: TrainState, images, labels, mask):
            """Masked sums, not batch means: eval pipelines pad the final
            partial batch (shapes stay static for XLA) and flag padding
            with mask=0, so eval covers exactly the real examples once —
            the reference's full-set eval (imagenet_preprocessing.py:
            259-323), which a drop-remainder loop silently under-covers.
            Units: examples for vision, tokens for sequence data."""
            if channels_first:
                images = jnp.transpose(images, (0, 2, 3, 1))
            if normalize is not None:
                images = normalize(images)
            if zero_stage == 3:
                eval_params = jax.tree_util.tree_map(
                    lambda spec, sds, s: zero_lib.gather_leaf(
                        spec, s, sds.shape, sds.dtype, nd, comm_off),
                    param_specs, local_sds, state.params,
                    is_leaf=zero_lib.is_spec)
            else:
                eval_params = state.params
            logits, _ = self._apply(eval_params, state.batch_stats,
                                    images, train=False)
            per = compute_per_example_ce(logits, labels)  # [B] | [B,S/sp]
            w = mask[:, None] * jnp.ones_like(per) if per.ndim == 2 else mask
            loss_sum = lax.psum(jnp.sum(per * w), reduce_axes)
            if report_acc:
                correct = lax.psum(
                    jnp.sum(compute_correct(logits, labels) * w),
                    reduce_axes)
            else:
                correct = jnp.zeros((), jnp.float32)
            count = lax.psum(jnp.sum(w), reduce_axes)
            return loss_sum, correct, count

        # replicated prefix by default; a full per-leaf tree under TP
        state_spec = rep if state_specs is None else state_specs

        train_sharded = jax.shard_map(
            local_train_step, mesh=mesh,
            in_specs=(state_spec, data_spec, data_spec),
            out_specs=(state_spec, rep),
            check_vma=False)
        # the mask is per-example [B]: sharded over 'data' only, even
        # when token data additionally shards dim 1 over 'seq'
        eval_sharded = jax.shard_map(
            local_eval_step, mesh=mesh,
            in_specs=(state_spec, data_spec, data_spec, P(DATA_AXIS)),
            out_specs=(rep, rep, rep),
            check_vma=False)

        if comm_off:
            # the --zero_probe timing twin: returned, never installed,
            # never donated (its caller reuses the live state)
            return jax.jit(train_sharded)
        self.train_step = jax.jit(train_sharded, donate_argnums=(0,))
        self.eval_step = jax.jit(eval_sharded)
        return None

    # ------------------------------------------------------------------
    def _compile_with_ledger(self, ledger, state, sharded):
        """AOT-compile the train step and register its XLA flop/byte
        counts with the MFU ledger.  Returns the compiled executable —
        the SAME program the jit path would run (donation included), so
        cost analysis is free rather than a second compile.  Any
        failure degrades to the plain jit path with no registration:
        observability must never change whether a run trains."""
        try:
            compiled = self.train_step.lower(state, *sharded).compile()
        except Exception as e:  # noqa: BLE001 — see docstring
            log.debug("ledger: train-step AOT compile unavailable (%s) "
                      "— using the jit path, no MFU entry", e)
            return self.train_step
        ledger.register("train_step", compiled=compiled)
        return compiled

    # ------------------------------------------------------------------
    def _zero_overlap_probe(self, state: TrainState, batch, ledger,
                            window_step_s) -> None:
        """--zero_probe: turn the ZeRO-2/3 overlap claim into measured
        numbers (obs/ledger + registry gauges, BENCH_zero's inputs).

        Three measurements, all on the live mesh after training:
          1. standalone per-leaf reduce-scatter / all-gather of the
             param-shaped trees — the SERIALIZED collective wall, what
             the step would pay if nothing overlapped;
          2. a comm-stubbed twin of the compiled step (the same program
             minus the data-axis ZeRO collectives) — its wall is the
             step's compute+everything-else floor;
          3. the run's own median clean-window step time.

        exposed = max(0, step − twin) is the communication time the
        schedule failed to hide; exposed / serialized is the
        ``train_exposed_comm_frac`` gauge — strictly below 1.0 means
        the overlap is real, not a cost-model assumption."""
        mesh = self.rt.mesh
        nd = mesh.shape[DATA_AXIS]
        pspecs = self._zero_pspecs
        local_sds = self._zero_local_sds
        mesh_shape = dict(mesh.shape)
        grad_slice_specs = jax.tree_util.tree_map(
            zero_lib.zero_leaf_spec, pspecs, is_leaf=zero_lib.is_spec)
        reduce_axes = (DATA_AXIS, SEQ_AXIS)

        def scatter_local(p):
            idx = lax.axis_index(DATA_AXIS)
            # same wire dtype as the live step: the probe must price
            # the collectives the run actually emits (--zero_wire)
            return zero_lib.tree_map_specs(
                lambda spec, g: zero_lib.scatter_leaf(
                    spec, g.astype(jnp.float32), nd, reduce_axes,
                    mesh_shape, False, idx, wire=self.zero_wire),
                pspecs, p)

        def gather_local(s):
            return zero_lib.tree_map_specs(
                lambda spec, sds, leaf: zero_lib.gather_leaf(
                    spec, leaf, sds.shape, sds.dtype, nd),
                pspecs, local_sds, s)

        scatter_fn = jax.jit(jax.shard_map(
            scatter_local, mesh=mesh,
            in_specs=(zero_lib.concrete_specs(pspecs),),
            out_specs=zero_lib.concrete_specs(grad_slice_specs),
            check_vma=False))
        gather_fn = jax.jit(jax.shard_map(
            gather_local, mesh=mesh,
            in_specs=(zero_lib.concrete_specs(grad_slice_specs),),
            out_specs=zero_lib.concrete_specs(pspecs),
            check_vma=False))
        # full-param-shaped probe input (values irrelevant): global
        # shapes from the canonical template, placed per model specs
        pshard = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s),
            zero_lib.concrete_specs(pspecs),
            is_leaf=lambda x: isinstance(x, P))
        template = self._canonical_template.params
        full = jax.jit(
            lambda: jax.tree_util.tree_map(
                lambda sds: jnp.zeros(sds.shape, sds.dtype), template),
            out_shardings=pshard)()

        def timed(fn, arg, repeats: int = 5) -> float:
            jax.block_until_ready(fn(arg))  # compile outside the clock
            walls = []
            for _ in range(repeats):
                t0 = time.monotonic()
                jax.block_until_ready(fn(arg))
                # dtflint: sync-point (probe timing — the measurement IS
                # the sync)
                walls.append(time.monotonic() - t0)
            return sorted(walls)[len(walls) // 2]

        scatter_s = timed(scatter_fn, full)
        gather_s = timed(gather_fn, scatter_fn(full))
        twin = self._build_steps(self._state_specs, comm_off=True)
        twin_fn = lambda st: twin(st, *batch)[1]["loss"]
        nocomm_s = timed(twin_fn, state, repeats=3)
        step_s = sorted(window_step_s)[len(window_step_s) // 2]
        # stage >= 2 pays one reduce-scatter per microbatch plus one
        # param all-gather per step (stage 2: post-update; stage 3:
        # pre-compute) — the wall those would cost SERIALIZED
        serialized_s = self.grad_accum * scatter_s + gather_s
        exposed_s = max(0.0, step_s - nocomm_s)
        param_bytes = sum(
            int(np.prod(sds.shape)) * jnp.dtype(sds.dtype).itemsize
            for sds in jax.tree_util.tree_leaves(template))
        ledger.register("zero_scatter", flops=0.0,
                        bytes_accessed=float(param_bytes))
        ledger.observe("zero_scatter", scatter_s)
        ledger.register("zero_gather", flops=0.0,
                        bytes_accessed=float(param_bytes))
        ledger.observe("zero_gather", gather_s)
        from dtf_tpu.obs.registry import default_registry
        reg = default_registry()
        reg.gauge("train_zero_scatter_wall_s", unit="s").set(scatter_s)
        reg.gauge("train_zero_gather_wall_s", unit="s").set(gather_s)
        reg.gauge("train_zero_comm_serialized_s",
                  unit="s").set(serialized_s)
        reg.gauge("train_zero_step_nocomm_s", unit="s").set(nocomm_s)
        reg.gauge("train_exposed_comm_s", unit="s").set(exposed_s)
        frac = exposed_s / serialized_s if serialized_s > 0 else 0.0
        reg.gauge("train_exposed_comm_frac").set(frac)
        trace.event("zero_overlap", zero_stage=self.zero_stage,
                    scatter_wall_s=scatter_s, gather_wall_s=gather_s,
                    serialized_s=serialized_s, step_s=step_s,
                    nocomm_step_s=nocomm_s, exposed_s=exposed_s,
                    exposed_frac=frac)
        log.info("zero_probe: step %.2f ms, comm-off twin %.2f ms, "
                 "exposed comm %.2f ms vs %.2f ms serialized "
                 "(frac %.2f)", step_s * 1e3, nocomm_s * 1e3,
                 exposed_s * 1e3, serialized_s * 1e3, frac)

    # ------------------------------------------------------------------
    def evaluate(self, state: TrainState, eval_iter: Iterator,
                 heartbeat=None):
        """Weighted-exact eval: batches are (images, labels[, mask]);
        a missing mask means every example is real.  Returns
        (mean loss, top-1) over exactly the unmasked examples, or None
        when the iterator is empty.  top-1 is None under
        --report_accuracy_metrics false.  ``heartbeat``: beaten per
        batch so a long eval under the launcher supervisor stays
        visibly alive (the step loop — the usual beat site — is idle
        here)."""
        loss_sums, correct_sums, counts = [], [], []
        for batch in eval_iter:
            if heartbeat is not None:
                heartbeat.beat()
            if len(batch) == 2:
                images, labels = batch
                mask = np.ones((np.asarray(labels).shape[0],), np.float32)
            else:
                images, labels, mask = batch
            sharded = self.rt.shard_batch((images, labels, mask))
            ls, cs, n = self.eval_step(state, *sharded)
            loss_sums.append(ls)
            correct_sums.append(cs)
            counts.append(n)
        if not counts:
            return None
        total = float(np.sum(jax.device_get(counts)))
        if total == 0:
            return None
        loss = float(np.sum(jax.device_get(loss_sums))) / total
        if not self.cfg.report_accuracy_metrics:
            return (loss, None)
        return (loss,
                float(np.sum(jax.device_get(correct_sums))) / total)

    # ------------------------------------------------------------------
    def fit(self, state: TrainState, train_iter: Iterator,
            eval_iter_fn: Optional[Callable[[], Iterator]] = None,
            callbacks: Optional[list] = None):
        """Runs training; returns (state, stats-dict) where the stats dict
        is key-compatible with common.build_stats output."""
        cfg = self.cfg
        # dtflint: sync-point (one-time resume-position read, pre-loop)
        resumed_step = int(jax.device_get(state.step))
        time_cb = TimeHistory(self.global_batch, cfg.log_steps,
                              initial_global_step=resumed_step)
        # watchdogs (obs/watchdog): the NaN check reads the loss value
        # this loop already syncs at log cadence; the step-time guard
        # watches the same per-window wall time TimeHistory reports; the
        # heartbeat only exists when the launcher exported
        # DTF_HEARTBEAT_DIR.  All host-side, all off unless configured.
        nan_guard = NanLossWatchdog(enabled=getattr(cfg, "nan_guard", True))
        guard_factor = getattr(cfg, "step_time_guard_factor", 0.0) or 0.0
        step_guard = (StepTimeWatchdog(factor=guard_factor)
                      if guard_factor else None)
        heartbeat = Heartbeat.from_env(
            interval_s=getattr(cfg, "heartbeat_secs", 5.0))
        compile_pending = True
        window_t0 = time.monotonic()
        # calibration hook (dtf_tpu/plan): clean per-step wall times —
        # one sample per unskewed log window, so compile and epoch-
        # boundary work never contaminate the measurement the planner's
        # predicted-vs-measured ratio is computed against
        window_step_s: list = []
        # a skewed window covers non-step time (first-compile, or an
        # epoch boundary's eval/checkpoint) or fewer than log_steps
        # steps (post-boundary partial): emitting it would misreport
        # step_s and pollute the watchdog's rolling median — skip it
        window_skewed = True
        callbacks = [time_cb] + list(callbacks or [])
        acc_key = ("categorical_accuracy" if self.spec.one_hot
                   else "sparse_categorical_accuracy")
        history: dict = {"loss": [], acc_key: []}
        profile_range = _parse_profile_steps(cfg.profile_steps)
        profiling = False
        # ">= with a started flag" rather than "==": a resumed run whose
        # start step already passed profile_range[0] must still trace the
        # remaining in-range steps (--profile_steps contract under --resume).
        profile_started = False
        # profiler output goes to the TRACE dir when one is configured
        # — the XLA dump is observability artifact, not run state, and
        # mixing it into model_dir buries checkpoints under trace
        # protos (model_dir stays the fallback for untraced runs)
        profile_dir = (getattr(cfg, "trace_dir", "")
                       or os.environ.get("DTF_TRACE_DIR", "")
                       or cfg.model_dir)
        # MFU/cost ledger (obs/ledger.py): the train step registers its
        # XLA flop/byte counts at compile time — from the AOT
        # lower().compile() executable the loop then RUNS (no second
        # compile) — and every clean log window feeds its synced
        # per-step wall time.  DTF_LEDGER=0 is the kill switch (and
        # restores the pre-AOT jit dispatch path wholesale).
        from dtf_tpu.obs.ledger import Ledger
        ledger = Ledger()
        ledger_on = os.environ.get("DTF_LEDGER", "1") != "0"
        step_fn = self.train_step

        for cb in callbacks:
            _call(cb, "on_train_begin", None)
        eval_output = None
        metrics = None
        last_sharded = None
        global_step = resumed_step
        start_epoch = (global_step // self.steps_per_epoch
                       if self.steps_per_epoch else 0)
        # crash-exact mid-epoch resume: a run restored at step K of
        # epoch E continues at batch K%spe — it must neither re-train
        # the epoch prefix nor consume those batches from the (already
        # repositioned) data stream
        start_batch = (global_step % self.steps_per_epoch
                       if self.steps_per_epoch else 0)
        if global_step:
            log.info("resuming at step %d (epoch %d, batch %d)",
                     global_step, start_epoch, start_batch)
        t0 = time.time()
        try:
            for epoch in range(start_epoch, self.train_epochs):
                for cb in callbacks:
                    _call(cb, "on_epoch_begin", epoch, None)
                for batch_idx in range(
                        start_batch if epoch == start_epoch else 0,
                        self.steps_per_epoch):
                    for cb in callbacks:
                        _call(cb, "on_batch_begin", batch_idx, None)
                    if (profile_range and not profile_started
                            and global_step >= profile_range[0]
                            and global_step <= profile_range[1]):
                        jax.profiler.start_trace(profile_dir)
                        # surfaced by trace_main's summary: where the
                        # profiler dump for this run actually lives
                        trace.event("profiler_trace", path=profile_dir,
                                    start_step=global_step,
                                    stop_step=profile_range[1])
                        profiling = True
                        profile_started = True
                    images, labels = next(train_iter)
                    if hasattr(images, "device"):  # already sharded by prefetcher
                        sharded = (images, labels)
                    else:
                        sharded = self.rt.shard_batch((images, labels))
                    last_sharded = sharded
                    # NOTE: jit dispatch is async — a "step" span measures
                    # host-side dispatch (sub-ms once compiled), which is
                    # what makes it cheap enough to emit every step.  It
                    # exists for counting/attribution and host-stall
                    # detection; SYNCED wall-clock timing comes from the
                    # "log_window" spans below (and the "compile" span,
                    # whose first call blocks on trace+compile).
                    try:
                        if compile_pending:
                            compile_pending = False
                            with trace.span("compile", step=global_step):
                                if ledger_on:
                                    step_fn = self._compile_with_ledger(
                                        ledger, state, sharded)
                                with trace.span("step", step=global_step):
                                    state, metrics = step_fn(state,
                                                             *sharded)
                        else:
                            with trace.span("step", step=global_step):
                                state, metrics = step_fn(state, *sharded)
                    except Exception as e:  # noqa: BLE001 — classify,
                        # never swallow: only a recognized accelerator
                        # loss is translated; everything else keeps its
                        # ordinary crash path (traceback + crash budget)
                        from dtf_tpu.train import elastic as elastic_lib
                        if elastic_lib.is_device_loss(e):
                            trace.anomaly("device_lost", step=global_step,
                                          error=f"{type(e).__name__}: {e}")
                            raise elastic_lib.DeviceLost(global_step,
                                                         e) from e
                        raise
                    global_step += 1
                    if global_step % cfg.log_steps == 0:
                        # device_get (host copy): block_until_ready can
                        # return early on some remote platforms
                        # dtflint: sync-point (log-cadence host copy —
                        # the ledger's log_window wall time accounts it)
                        loss_val = jax.device_get(metrics["loss"])
                        nan_guard.check(global_step, float(loss_val))
                        # the loss trajectory record: Python floats
                        # round-trip JSON exactly, so the chaos suite's
                        # crash-exactness asserts compare these
                        # bit-identically across killed+resumed runs
                        trace.event("train_loss", step=global_step,
                                    loss=float(loss_val))
                        now = time.monotonic()
                        if not window_skewed:
                            # the one host-measured duration that spans a
                            # real device sync: log_steps steps of true
                            # wall time — the per-step timing signal
                            window_s = now - window_t0
                            trace.span_completed(
                                "log_window", window_s, step=global_step,
                                steps=cfg.log_steps,
                                step_s=window_s / cfg.log_steps)
                            window_step_s.append(window_s / cfg.log_steps)
                            # MFU ledger: the one per-step duration that
                            # spans a real device sync
                            ledger.observe("train_step",
                                           window_s / cfg.log_steps)
                            if step_guard is not None:
                                step_guard.observe(global_step, window_s)
                        window_t0 = now
                        window_skewed = False
                    if heartbeat is not None:
                        heartbeat.beat(step=global_step)
                    if profiling and global_step > profile_range[1]:
                        jax.profiler.stop_trace()
                        profiling = False
                    # interval checkpointing reads state/step from the
                    # logs dict (CheckpointCallback.every_steps)
                    for cb in callbacks:
                        _call(cb, "on_batch_end", batch_idx,
                              {"state": state, "step": global_step})
                    ckpt_every = getattr(cfg, "checkpoint_steps", 0) or 0
                    if ckpt_every and global_step % ckpt_every == 0:
                        # an interval save just ran inside this log
                        # window (synchronous seal — and under ZeRO
                        # the canonical param/opt gather): skip the
                        # window from the step-time signal like epoch
                        # boundaries are, or train_step_s, the
                        # step-time watchdog and the --zero_probe
                        # exposed-comm number all absorb checkpoint
                        # I/O as "step time"
                        window_skewed = True
                    # chaos probe AFTER the interval checkpoint sealed:
                    # crash@step:K with checkpoint_steps dividing K is
                    # the deterministic kill-after-durable-save
                    # experiment (tests/test_chaos.py)
                    chaos.step(global_step)
                    signum = preemption.triggered()
                    if signum is not None:
                        # preemption (SIGTERM/SIGINT): emergency
                        # checkpoint at this step boundary — save +
                        # wait + integrity manifest — then the distinct
                        # preempted exit the supervisor restarts
                        # without consuming the crash budget
                        for cb in callbacks:
                            _call(cb, "on_preempt",
                                  {"state": state, "step": global_step})
                        trace.event("preempted", step=global_step,
                                    signum=int(signum))
                        trace.flush()
                        raise preemption.Preempted(global_step, signum)
                # epoch end: materialize the last step's metrics (keras history
                # records per-epoch training metrics)
                # dtflint: sync-point (epoch-boundary metrics copy,
                # outside the step-time guard's measured window)
                m = jax.device_get(metrics)
                nan_guard.check(global_step, float(m["loss"]))
                trace.event("epoch_end", epoch=epoch, step=global_step,
                            loss=float(m["loss"]))
                history["loss"].append(float(m["loss"]))
                if "accuracy" in m:
                    history[acc_key].append(float(m["accuracy"]))
                for cb in callbacks:
                    _call(cb, "on_epoch_end", epoch,
                          {"state": state, "history": history})
                if heartbeat is not None:
                    # epoch-boundary work (checkpoint save above, eval
                    # below) runs outside the step loop's beat site — beat
                    # here so a slow save doesn't read as a dead rank
                    heartbeat.beat(step=global_step)
                if cfg.verbose and (jax.process_index() == 0):
                    log.info("epoch %d/%d: loss=%.4f top1=%s lr=%.5f",
                             epoch + 1, self.train_epochs, history["loss"][-1],
                             ("%.4f" % m["accuracy"]) if "accuracy" in m
                             else "n/a", float(m["learning_rate"]))
                run_eval = (not cfg.skip_eval and eval_iter_fn is not None and
                            ((epoch + 1) % cfg.epochs_between_evals == 0 or
                             epoch + 1 == self.train_epochs))
                if run_eval:
                    with trace.span("eval", epoch=epoch, step=global_step):
                        eval_output = self.evaluate(state, eval_iter_fn(),
                                                    heartbeat=heartbeat)
                    if eval_output and jax.process_index() == 0:
                        log.info("eval: loss=%.4f top1=%s", eval_output[0],
                                 ("%.4f" % eval_output[1])
                                 if eval_output[1] is not None else "n/a")
                    # --stop_threshold parity (model_helpers.past_stop_threshold
                    # via flags_core.define_base): end training once eval top-1
                    # reaches the threshold
                    if (eval_output and cfg.stop_threshold is not None
                            and eval_output[1] is not None
                            and eval_output[1] >= cfg.stop_threshold):
                        if jax.process_index() == 0:
                            log.info("stop_threshold %.4f reached (top1=%.4f) — "
                                     "stopping early at epoch %d",
                                     cfg.stop_threshold, eval_output[1], epoch + 1)
                        break
                # the epoch boundary just spent wall time on non-step work
                # (metrics sync, eval — incl. its one-time compile —
                # checkpoint-save callbacks): restart the step-time guard's
                # window here, or the next log window would measure that
                # work as a step-time regression on a healthy run
                window_t0 = time.monotonic()
                window_skewed = True  # next boundary closes a partial window
                if heartbeat is not None:
                    heartbeat.beat(step=global_step)
        finally:
            # one teardown for every exit — normal completion, the
            # stop_threshold break, and watchdog aborts
            # (TrainingAnomaly) alike: an in-flight profiler trace is
            # stopped and flushed, not orphaned mid-dump
            if profiling:
                jax.profiler.stop_trace()
        if (start_epoch >= self.train_epochs and not cfg.skip_eval
                and eval_iter_fn is not None):
            # resumed a fully-trained checkpoint: still honor the eval ask
            eval_output = self.evaluate(state, eval_iter_fn(),
                                        heartbeat=heartbeat)
            if eval_output and jax.process_index() == 0:
                log.info("eval (resumed, no further training): loss=%.4f "
                         "top1=%s", eval_output[0],
                         ("%.4f" % eval_output[1])
                         if eval_output[1] is not None else "n/a")
        for cb in callbacks:
            _call(cb, "on_train_end", {"state": state, "history": history})
        if metrics is not None:
            # host copy: the only reliable completion sync on platforms
            # where block_until_ready returns early
            # dtflint: sync-point (final completion barrier, post-loop)
            jax.device_get(metrics["loss"])
        log.info("train wall time: %.1fs (%d steps)",
                 time.time() - t0, global_step)
        trace.event("train_end", step=global_step,
                    wall_s=time.time() - t0)
        if (self.zero_stage >= 2 and getattr(cfg, "zero_probe", False)
                and window_step_s and last_sharded is not None):
            try:
                self._zero_overlap_probe(state, last_sharded, ledger,
                                         window_step_s)
            except Exception:  # noqa: BLE001 — a probe must not fail a run
                log.exception("zero_probe failed — overlap gauges skipped")
        ledger.emit_summary()
        trace.flush()
        # calibration gauges (dtf_tpu/plan reads these after a measured
        # smoke): the median clean-window step time, and the live
        # device bytes at train end — params + optimizer state + grads
        # + pipeline buffers, the persistent portion of the planner's
        # predicted peak.  One live_arrays walk per fit: negligible.
        from dtf_tpu.obs.registry import default_registry
        if window_step_s:
            mid = sorted(window_step_s)[len(window_step_s) // 2]
            default_registry().gauge("train_step_s", unit="s").set(mid)
        try:
            # PER-DEVICE bytes (the planner's predicted peak is
            # per-device): sum physical shard bytes on the local
            # devices, averaged over them — a.size alone counts the
            # global logical array, which overstates sharded tensors
            # by the shard count and misstates replicated ones
            live = 0
            for a in jax.live_arrays():
                shards = getattr(a, "addressable_shards", None)
                if shards:
                    live += sum(int(np.prod(s.data.shape))
                                * a.dtype.itemsize for s in shards)
                else:
                    live += a.size * a.dtype.itemsize
            live //= max(jax.local_device_count(), 1)
        except Exception:  # noqa: BLE001 — diagnostics must not fail a run
            live = 0
        if live:
            default_registry().gauge("train_live_bytes",
                                     unit="bytes").set(live)
        stats = build_stats(history, eval_output, time_cb)
        return state, stats


def _call(cb, name, *args):
    fn = getattr(cb, name, None)
    if fn is not None:
        fn(*args)


def _parse_profile_steps(profile_steps: Optional[str]):
    """--profile_steps "start,stop" parity (common.py:289-296)."""
    if not profile_steps:
        return None
    parts = [p.strip() for p in str(profile_steps).split(",")]
    if len(parts) != 2:
        raise ValueError("profile_steps must be 'start,stop'")
    return int(parts[0]), int(parts[1])
