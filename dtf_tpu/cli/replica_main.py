"""Replica serve process — one ServeEngine behind the router's wire.

The serving router (cli/router_main.py, serve/router.py) spawns N of
these.  Each builds the same model the same way (same ``--seed``, same
checkpoint), so greedy decode is replica-interchangeable: the router
can re-dispatch an in-flight request to a sibling — or to this
replica's own respawn — and get token-identical output.

Identity and rendezvous are environment + files, launcher-style:

  DTF_PROCESS_ID / --replica_id   which replica this is
  --rendezvous_dir                where to announce (replica_rank{K}
                                  .json: ephemeral port + pid) and
                                  where heartbeats go
  DTF_HEARTBEAT_DIR               exported by the router's spawner;
                                  the ENGINE LOOP rewrites
                                  heartbeat_rank{K}.json every
                                  iteration — the router's health
                                  probe (and launch.py's hang
                                  watchdog) read that, never the
                                  socket
  DTF_RESTART_GENERATION          respawn generation (stamped into the
                                  announce file)
  DTF_SERVE_CHECKPOINT            checkpoint override (a model_dir or
                                  export_dir path): serve THIS instead
                                  of the flag-configured checkpoint —
                                  the rollout controller's lever for
                                  restarting one replica at a time
                                  onto a new model (serve/rollout.py)
  --serve_host                    address to bind AND announce; a
                                  routable address + a shared
                                  rendezvous dir puts this replica
                                  behind a router on another host
  DTF_FAULT                       chaos passthrough: a
                                  slow_replica@replica<K> spec fires
                                  here when K == DTF_PROCESS_ID

SIGTERM drains: admissions shed with retry_after, in-flight finishes,
exit 0 — a drained replica is a clean exit the router's respawn budget
never sees.
"""

from __future__ import annotations

import logging
import os
import signal
import sys
import threading

from dtf_tpu.config import parse_flags

log = logging.getLogger("dtf_tpu")

REPLICA_DEFAULTS = dict(
    model="transformer_small",
    dataset="lm",
    skip_eval=True,
)


def run_replica(cfg, random_init: bool = False,
                ready_event: "threading.Event" = None) -> int:
    """Build the engine, serve the wire until SIGTERM.  Library entry
    (tests drive it in-process with ready_event)."""
    from dtf_tpu.cli.serve_main import build_serving_engine
    from dtf_tpu.serve.replica import ReplicaServer

    replica_id = cfg.replica_id
    if replica_id < 0:
        replica_id = int(os.environ.get("DTF_PROCESS_ID", "0"))
    if not cfg.rendezvous_dir:
        raise ValueError("--rendezvous_dir is required (the router's "
                         "announce/heartbeat rendezvous)")
    ckpt = os.environ.get("DTF_SERVE_CHECKPOINT", "")
    if ckpt:
        # rollout override: serve THIS checkpoint.  An export artifact
        # has a model/ subdir; anything else is a train model_dir
        if os.path.isdir(os.path.join(ckpt, "model")):
            cfg = cfg.replace(export_dir=ckpt, model_dir="")
        else:
            cfg = cfg.replace(model_dir=ckpt, export_dir="")
        random_init = False
        log.warning("replica %d: serving rollout checkpoint %s "
                    "(DTF_SERVE_CHECKPOINT)", replica_id, ckpt)
    _, engine = build_serving_engine(cfg, random_init=random_init,
                                     replica_rank=replica_id)
    # warm BEFORE announcing: the first request through a cold engine
    # pays XLA compile (seconds), during which the engine loop — and
    # therefore its heartbeat — stalls.  A replica that announces cold
    # reads as dead to the router's health probe the moment traffic
    # arrives; a replica that warms first serves its first real
    # request at steady-state latency.  (Chunk-shape variants still
    # compile lazily; the router's health timeout absorbs those
    # shorter stalls.)
    import numpy as np
    page = cfg.kv_page_size or 16
    warm = np.full((min(page, engine.max_seq_len - 2),), 1, np.int32)
    engine.submit(warm, max_new_tokens=2).result(timeout=600)
    log.info("replica %d: warm (compile done)", replica_id)
    server = ReplicaServer(engine, replica_id, cfg.rendezvous_dir,
                           host=cfg.serve_host)

    # --metrics_port: this replica's engine registry (queue depth,
    # prefix hits, decode-step MFU ledger gauges) as a live Prometheus
    # scrape + a /healthz probe (503 once draining).  Each replica is
    # its own process/port; router_main fans out base+1+K
    metrics_server = None
    if cfg.metrics_port:
        from dtf_tpu.obs.prom import MetricsServer
        metrics_server = MetricsServer(
            cfg.metrics_port, registry_fn=lambda: engine.metrics,
            health_fn=lambda: {"ok": not engine.draining,
                               "replica": replica_id,
                               "draining": engine.draining,
                               "outstanding": engine.outstanding})

    done = threading.Event()

    def _on_sigterm(signum, frame):
        # async-signal-minimal: one lock-free engine call + one event
        engine.begin_drain()
        done.set()
        os.write(2, b"replica: SIGTERM - draining\n")

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:   # not the main thread (in-process tests)
        pass

    server.start()
    if ready_event is not None:
        ready_event.set()
    log.info("replica %d: ready on port %d", replica_id, server.port)
    try:
        done.wait()
        # drain: wait out queued + in-flight work, then leave cleanly
        engine.stop(drain=True)
    finally:
        server.stop()
        if metrics_server is not None:
            metrics_server.shutdown()
    log.info("replica %d: drained — exiting 0", replica_id)
    return 0


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s")
    argv = list(argv if argv is not None else sys.argv[1:])
    random_init = "--serve_random_init" in argv
    if random_init:
        argv.remove("--serve_random_init")
    cfg = parse_flags(argv, defaults=REPLICA_DEFAULTS)
    from dtf_tpu import chaos
    from dtf_tpu.obs import trace
    trace.maybe_configure(cfg)
    chaos.maybe_configure(cfg)   # slow_replica / heartbeat_stall
    return run_replica(cfg, random_init=random_init)


if __name__ == "__main__":
    sys.exit(main())
