"""Parallelism-planner CLI — rank, check, and calibrate plans.

Rank the feasible plan lattice for a workload on a (possibly simulated)
mesh — plans for a 4-host × 4-device pod are computed on a CPU box:

  python -m dtf_tpu.cli.plan_main --model transformer_tpu --dataset lm \
      --seq_len 2048 --batch_size 256 --dtype bf16 --optimizer adamw \
      --plan_mesh 4x4 --top 10 --out plans.json

Verify that every plan the ranker calls feasible actually compiles
(one smoke train step per plan, on the live devices):

  python -m dtf_tpu.cli.plan_main --devices 8 --model transformer_small \
      --dataset lm --seq_len 64 --batch_size 8 --check --check_top 3

Calibration: run a short MEASURED smoke and record predicted-vs-measured
step time and live bytes into the obs registry (and, with
``--benchmark_log_dir``, into metric.log via
``BenchmarkFileLogger.log_registry``); exits nonzero when the ratio
leaves ``--calibrate_tolerance`` (the ci_check stage-6 contract):

  python -m dtf_tpu.cli.plan_main --model transformer_small --dataset lm \
      --seq_len 64 --batch_size 4 --optimizer adamw --calibrate

``--plan <file>`` evaluates that one plan instead of searching;
memory-infeasible plans are rejected loudly (exit 2).

All ordinary dtf flags (--model/--dataset/--batch_size/--seq_len/
--dtype/--optimizer/--plan_mesh/...) are accepted; the planner-only
options are --devices/--top/--out/--check/--check_top/--calibrate/
--calibrate_steps/--calibrate_tolerance.
"""

from __future__ import annotations

import os
import sys

# --devices N: virtual host-platform devices for --check smokes (the
# tests' 8-device CPU mesh).  Must land in XLA_FLAGS before the jax
# backend initializes — honored here, ahead of every other import.
if "--devices" in sys.argv:
    _n = int(sys.argv[sys.argv.index("--devices") + 1])
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + f" --xla_force_host_platform_device_count={_n}")

import json     # noqa: E402
import logging  # noqa: E402
import tempfile  # noqa: E402

from dtf_tpu.config import parse_flags  # noqa: E402

log = logging.getLogger("dtf_tpu")

_OWN_FLAGS = {
    # name: (takes_value, default)
    "devices": (True, None),
    "top": (True, 10),
    "out": (True, ""),
    "check": (False, False),
    "check_top": (True, 3),
    "calibrate": (False, False),
    "calibrate_steps": (True, 8),
    "calibrate_tolerance": (True, 2.0),
    # ZeRO-2/3 compute/comm overlap fraction the cost model credits.
    # Unset = AUTO: a prior --calibrate's measured fraction persisted
    # in --plan_cache for this (workload, mesh), else
    # cost_model.DEFAULT_OVERLAP_FRAC.  --calibrate emits the measured
    # run's IMPLIED fraction as plan_overlap_frac_implied and (with
    # --plan_cache) persists it, closing the loop without an operator;
    # an explicit value here always wins
    "overlap_frac": (True, None),
}

_FLOAT_FLAGS = ("calibrate_tolerance", "overlap_frac")


def _split_args(argv):
    """Extract plan_main-only options; the rest is ordinary dtf flags."""
    own = {k: v[1] for k, v in _OWN_FLAGS.items()}
    rest = []
    i = 0
    while i < len(argv):
        name = argv[i].lstrip("-")
        if argv[i].startswith("-") and name in _OWN_FLAGS:
            takes_value = _OWN_FLAGS[name][0]
            if takes_value:
                raw = argv[i + 1]
                own[name] = (float(raw) if name in _FLOAT_FLAGS
                             else raw if name == "out" else int(raw))
                i += 2
            else:
                own[name] = True
                i += 1
        else:
            rest.append(argv[i])
            i += 1
    return own, rest


def _smoke_config(cfg, train_steps: int, model_dir: str):
    """A measured/compile smoke derived from the workload config: tiny
    step count, synthetic-friendly, no checkpoint/eval side effects."""
    return cfg.replace(
        train_steps=train_steps, train_epochs=1, log_steps=1,
        model_dir=model_dir, skip_checkpoint=True, skip_eval=True,
        clean=False, resume=False, benchmark_log_dir="")


def _check(cfg, ranked, check_top: int) -> int:
    """Compile one smoke train step for each feasible-marked plan (top
    ``check_top``); nonzero exit when any of them fails — a plan the
    model calls feasible MUST compile, that is the contract."""
    import jax

    from dtf_tpu.cli.runner import run
    from dtf_tpu.plan import apply_plan

    live = jax.device_count()
    failures = 0
    # cap BEFORE the device-count test: checking a simulated mesh
    # larger than this box must report check_top clear failures, not
    # one "cannot check" line per feasible plan in the lattice
    to_check = [r for r in ranked if r.feasible][:check_top]
    for r in to_check:
        if r.plan.num_devices > live:
            print(f"plan {r.plan.describe()}: needs {r.plan.num_devices} "
                  f"devices, {live} attached — cannot check on this box",
                  file=sys.stderr)
            failures += 1
            continue
        with tempfile.TemporaryDirectory() as tmp:
            try:
                # apply_plan inside the guard: a hand-set plan-owned
                # flag (e.g. --check under a pinned --remat) reports
                # per-plan FAILED lines, not one uncaught traceback
                smoke = _smoke_config(apply_plan(cfg, r.plan), 1, tmp)
                run(smoke)
                print(f"check {r.plan.describe()}: OK")
            except Exception as e:  # noqa: BLE001 — report, keep checking
                failures += 1
                print(f"check {r.plan.describe()}: FAILED "
                      f"({type(e).__name__}: {e})", file=sys.stderr)
    if not to_check:
        print("check: no feasible plan to check", file=sys.stderr)
        return 1
    return 1 if failures else 0


def _calibrate(cfg, stats, mesh, plan, steps: int, tolerance: float,
               overlap_frac: float) -> int:
    """Measured smoke vs prediction.  Records, per the obs-registry
    contract: plan_predicted_step_s, plan_measured_step_s,
    plan_step_time_ratio, plan_predicted_peak_bytes,
    plan_measured_live_bytes, plan_live_bytes_ratio — exported through
    BenchmarkFileLogger.log_registry when --benchmark_log_dir is set."""
    import jax

    from dtf_tpu.cli.runner import run
    from dtf_tpu.obs.registry import default_registry
    from dtf_tpu.plan import apply_plan, predict
    from dtf_tpu.plan.mesh_spec import calibrate_device_flops

    # measured achievable FLOP/s replaces the preset's guess: the ratio
    # then compares the MODEL (traffic/FLOP accounting), not whether
    # the preset knew this box's matmul speed
    from dtf_tpu.plan.compile import PLAN_OWNED_FLAGS

    measured_flops = calibrate_device_flops()
    cost = predict(plan, stats, mesh, cfg.batch_size,
                   optimizer=cfg.optimizer, device_flops=measured_flops,
                   overlap_frac=overlap_frac)
    # calibrating a hand-flagged config: the plan was DERIVED from the
    # plan-owned flags (plan_from_config), so reset them to defaults
    # before apply_plan writes them back — otherwise its hand-set-flag
    # conflict guard rejects the very flags the plan encodes
    run_cfg = cfg.replace(plan="", **PLAN_OWNED_FLAGS)
    run_cfg = apply_plan(run_cfg, plan)
    benchmark_dir = cfg.benchmark_log_dir
    with tempfile.TemporaryDirectory() as tmp:
        stats_out = run(_smoke_config(run_cfg, steps, tmp))
    reg = default_registry()
    gauge = reg.get("train_step_s")
    if gauge is not None and gauge.value > 0:
        measured_step = float(gauge.value)
    elif stats_out.get("avg_exp_per_second"):
        measured_step = cfg.batch_size / stats_out["avg_exp_per_second"]
    else:
        print("calibrate: the smoke produced no step-time measurement "
              "(too few steps?)", file=sys.stderr)
        return 1
    live_gauge = reg.get("train_live_bytes")
    measured_live = float(live_gauge.value) if live_gauge else 0.0

    ratio = cost.step_time_s / measured_step
    reg.gauge("plan_predicted_step_s", unit="s").set(cost.step_time_s)
    reg.gauge("plan_measured_step_s", unit="s").set(measured_step)
    reg.gauge("plan_step_time_ratio").set(ratio)
    if plan.zero >= 2:
        # invert the overlap term against the measurement: the
        # overlap_frac that makes the model meet the measured step.
        # predict() defines hidden = min(t_grad, frac · compute), so
        # the implied fraction is measured-hidden / COMPUTE — same
        # denominator as the flag it feeds back into (--overlap_frac)
        t_grad = cost.breakdown.get("grad_sync_s", 0.0)
        hidden_pred = cost.breakdown.get("hidden_comm_s", 0.0)
        other = cost.comm_s - (t_grad - hidden_pred)
        if t_grad > 0 and cost.compute_s > 0:
            measured_exposed = max(0.0, measured_step - cost.compute_s
                                   - other)
            hidden_meas = min(max(t_grad - measured_exposed, 0.0),
                              t_grad)
            implied = min(hidden_meas / cost.compute_s, 1.0)
            reg.gauge("plan_overlap_frac_implied").set(implied)
            print(f"  overlap: modeled frac "
                  f"{cost.breakdown.get('overlap_frac', 0.0):.2f}, "
                  f"measured-implied {implied:.2f}")
            if cfg.plan_cache:
                # close the loop: persist the measured fraction per
                # (workload, mesh) so every later --plan auto resolve
                # and ranking against this cache uses it instead of
                # the model default — no operator in the loop
                from dtf_tpu.plan.cache import store_calibration
                store_calibration(cfg.plan_cache, stats, mesh, implied)
                print(f"  overlap: persisted to {cfg.plan_cache} — "
                      f"auto-applied by later rankings/resolves")
    reg.gauge("plan_predicted_peak_bytes", unit="bytes").set(
        cost.peak_bytes)
    if measured_live:
        reg.gauge("plan_measured_live_bytes", unit="bytes").set(
            measured_live)
        reg.gauge("plan_live_bytes_ratio").set(
            cost.peak_bytes / measured_live)
    print(f"calibration ({plan.describe()}, device_flops "
          f"{measured_flops:.3g}):")
    print(f"  step time: predicted {cost.step_time_s * 1e3:.2f} ms, "
          f"measured {measured_step * 1e3:.2f} ms  "
          f"(ratio {ratio:.2f})")
    if measured_live:
        print(f"  memory: predicted peak {cost.peak_bytes / 2**20:.1f} "
              f"MiB, measured live {measured_live / 2**20:.1f} MiB")
    if benchmark_dir and jax.process_index() == 0:
        from dtf_tpu.utils.benchmark_logger import BenchmarkFileLogger
        blog = BenchmarkFileLogger(benchmark_dir)
        blog.log_registry(reg)
        print(f"  registry exported to {benchmark_dir}/metric.log")
    if not (1.0 / tolerance <= ratio <= tolerance):
        print(f"calibrate: predicted/measured step-time ratio {ratio:.2f} "
              f"outside [{1 / tolerance:.2f}, {tolerance:.2f}] — the "
              f"cost model is off for this workload/box", file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s")
    own, rest = _split_args(list(sys.argv[1:] if argv is None else argv))
    cfg = parse_flags(rest)
    if not cfg.model or not cfg.dataset:
        print("plan_main needs --model and --dataset (the workload to "
              "plan)", file=sys.stderr)
        return 2

    from dtf_tpu.plan import (check_plan, load_plan_file, plan_from_config,
                              predict, search)
    from dtf_tpu.plan.compile import stats_for_config
    from dtf_tpu.plan.mesh_spec import mesh_spec
    from dtf_tpu.plan.search import RankedPlan, ranked_artifact

    from dtf_tpu.plan.cost_model import DEFAULT_OVERLAP_FRAC

    stats = stats_for_config(cfg)
    mesh = mesh_spec(cfg.plan_mesh)
    # effective overlap fraction: an explicit --overlap_frac wins;
    # else the plan cache's persisted --calibrate measurement for this
    # (workload, mesh) — the feedback loop closing without an operator
    # — else the model default
    overlap = (None if own["overlap_frac"] is None
               else float(own["overlap_frac"]))
    if overlap is None and cfg.plan_cache:
        from dtf_tpu.plan.cache import load_calibration
        overlap = load_calibration(cfg.plan_cache, stats, mesh)
        if overlap is not None:
            print(f"plan cache: using MEASURED overlap_frac "
                  f"{overlap:.2f} from a prior --calibrate "
                  f"(--overlap_frac overrides)")
    if overlap is None:
        overlap = DEFAULT_OVERLAP_FRAC

    if cfg.plan and cfg.plan != "auto":
        # evaluate ONE explicit plan (still printed as a 1-row ranking)
        plan = load_plan_file(cfg.plan)
        violations = tuple(check_plan(plan, stats, mesh, cfg.batch_size))
        cost = predict(plan, stats, mesh, cfg.batch_size,
                       optimizer=cfg.optimizer, overlap_frac=overlap)
        ranked = [RankedPlan(plan, cost, violations)]
    elif cfg.plan_cache:
        from dtf_tpu.plan.cache import cached_search
        ranked, hit = cached_search(cfg.plan_cache, stats, mesh,
                                    cfg.batch_size,
                                    optimizer=cfg.optimizer,
                                    overlap_frac=overlap)
        print(f"plan cache: {'HIT — search skipped' if hit else 'miss'} "
              f"({cfg.plan_cache})")
    else:
        ranked = search(stats, mesh, cfg.batch_size,
                        optimizer=cfg.optimizer, overlap_frac=overlap)

    feasible = sum(1 for r in ranked if r.feasible)
    print(f"{stats.model} ({stats.params / 1e6:.1f}M params"
          + (f", seq {stats.seq_len}" if stats.seq_len else "")
          + f") × batch {cfg.batch_size} on {mesh.name} "
          f"({mesh.num_hosts}×{mesh.devices_per_host} devices, "
          f"{mesh.hbm_bytes / 2**30:.0f} GiB HBM): "
          f"{feasible}/{len(ranked)} plans feasible")
    hdr = (f"{'rank':>4} {'plan':<34} {'step_ms':>9} {'peak_GiB':>9} "
           f"{'verdict':<10}")
    print(hdr)
    print("-" * len(hdr))
    for i, r in enumerate(ranked[:own["top"]], start=1):
        verdict = ("ok" if r.feasible
                   else ("invalid" if r.violations else "over-mem"))
        print(f"{i:>4} {r.plan.describe():<34} "
              f"{r.cost.step_time_s * 1e3:>9.2f} "
              f"{r.cost.peak_bytes / 2**30:>9.3f} {verdict:<10}")
        for v in r.violations:
            print(f"       ! {v}")

    if own["out"]:
        artifact = ranked_artifact(stats, mesh, cfg.batch_size, ranked,
                                   top=own["top"])
        with open(own["out"], "w") as f:
            json.dump(artifact, f, indent=1)
            f.write("\n")
        print(f"ranked artifact written to {own['out']}")

    rc = 0
    if cfg.plan == "auto" and not feasible:
        # the runner's best_plan rejects this loudly; the CLI must not
        # exit 0 on an all-infeasible lattice — and --calibrate below
        # must never pick (and run!) the least-over-budget plan
        near = min(ranked, key=lambda r: r.cost.peak_bytes, default=None)
        print(f"plan auto REJECTED: no feasible plan"
              + (f" — smallest predicted peak "
                 f"{near.cost.peak_bytes / 2**30:.2f} GiB "
                 f"({near.plan.describe()}) vs budget "
                 f"{near.cost.hbm_budget_bytes / 2**30:.2f} GiB"
                 if near else ""), file=sys.stderr)
        return 2
    if cfg.plan and cfg.plan != "auto":
        r = ranked[0]
        if r.violations:
            print(f"plan REJECTED (invalid): {'; '.join(r.violations)}",
                  file=sys.stderr)
            return 2
        if not r.cost.feasible:
            print(f"plan REJECTED (memory-infeasible): predicted peak "
                  f"{r.cost.peak_bytes / 2**30:.2f} GiB/device exceeds "
                  f"budget {r.cost.hbm_budget_bytes / 2**30:.2f} GiB",
                  file=sys.stderr)
            return 2
    if own["check"]:
        rc = rc or _check(cfg.replace(plan=""), ranked, own["check_top"])
    if own["calibrate"]:
        plan = (ranked[0].plan if cfg.plan
                else plan_from_config(cfg, mesh.num_devices))
        rc = rc or _calibrate(cfg, stats, mesh, plan,
                              own["calibrate_steps"],
                              own["calibrate_tolerance"], overlap)
    return rc


if __name__ == "__main__":
    sys.exit(main())
