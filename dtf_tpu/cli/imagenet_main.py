"""ImageNet ResNet-50 entry point.

TPU-native successor of reference resnet_imagenet_main.py (and the
_dist/_horovod variants plus all 16 ps_server/ per-rank copies —
SURVEY §2.1 rows 11-14, §7.9).  The flagship benchmark workload
(BASELINE.md): ResNet-50, 1 epoch, global batch = per-worker 192 × N.

Examples:
  python -m dtf_tpu.cli.imagenet_main --use_synthetic_data --train_steps 1 \
      --batch_size 4 --distribution_strategy off
  python -m dtf_tpu.cli.imagenet_main --data_dir /data/imagenet \
      --distribution_strategy tpu --dtype bf16 --batch_size 1024
"""

from __future__ import annotations

import logging
import sys

from dtf_tpu.config import parse_flags
from dtf_tpu.cli.runner import run

# parity with define_imagenet_keras_flags (resnet_imagenet_main.py:268-271:
# train_epochs=90) + the dtype/use_tensor_lr extras of that main
IMAGENET_DEFAULTS = dict(
    model="resnet50",
    dataset="imagenet",
    train_epochs=90,
    batch_size=256,
    epochs_between_evals=1,
)


def main(argv=None) -> dict:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s")
    cfg = parse_flags(argv if argv is not None else sys.argv[1:],
                      defaults=IMAGENET_DEFAULTS)
    # --trace_dir / DTF_TRACE_DIR tracing is configured by run() itself
    return run(cfg)


if __name__ == "__main__":
    main()
