"""The `run(flags_obj) -> stats` equivalent — shared body of every main.

Mirrors the canonical reference call stack (SURVEY §3.1):
session config → perf knobs → strategy → datasets → model →
compile → callbacks → fit/evaluate → build_stats.  Returns the stats
dict (logged as "Run stats:" like resnet_imagenet_main.py:278).
"""

from __future__ import annotations

import itertools
import logging
import os
import shutil

import jax

from dtf_tpu.config import Config
from dtf_tpu.data import DatasetSpec, get_dataset_spec, synthetic_input_fn
from dtf_tpu.data.pipeline import DevicePrefetcher
from dtf_tpu.models import build_model
from dtf_tpu.runtime import initialize, is_coordinator
from dtf_tpu.runtime.mesh import DATA_AXIS, MODEL_AXIS, SEQ_AXIS
from dtf_tpu.train import Trainer

log = logging.getLogger("dtf_tpu")


def effective_global_batch(cfg: Config, runtime) -> int:
    """Batch-size semantics across strategies (SURVEY §3.3/§3.4):
    mirrored/MWM treat --batch_size as global (Keras-fit semantics);
    horovod/parameter_server treat it as per-replica — each reference
    rank drove exactly one GPU with its own --batch_size, so the global
    batch is batch × hvd.size() ≡ batch × num_replicas.  Scaling by
    replicas (not processes) keeps the horovod LR rule consistent when
    one process drives several chips: LR ramps to 0.1 × num_replicas
    and the batch scales by the same factor."""
    if cfg.distribution_strategy in ("horovod", "parameter_server"):
        return cfg.batch_size * runtime.num_replicas
    return cfg.batch_size


def make_input_fns(cfg: Config, spec: DatasetSpec, global_batch: int):
    """Returns (train_iter_factory, eval_iter_factory).

    Each process produces its 1/process_count share of the global batch
    (the loop assembles the global array from process-local shards), so
    the per-host batch is global // process_count.
    """
    if global_batch % jax.process_count():
        raise ValueError(
            f"global batch_size {global_batch} must be divisible by the "
            f"process count ({jax.process_count()})")
    host_batch = global_batch // jax.process_count()
    # The TRAIN factory accepts start_step: crash-exact resume rebuilds
    # the stream positioned at the restored step (position-derived RNGs
    # make batch n a pure function of (seed, n) in every pipeline —
    # cifar/synthetic natively, imagenet via the sharded data service).
    if cfg.use_synthetic_data or not cfg.data_dir:
        fns = (
            lambda start_step=0: synthetic_input_fn(
                spec, True, host_batch, cfg.seed, start_step=start_step),
            lambda: synthetic_input_fn(spec, False, host_batch, cfg.seed + 1),
        )
    elif spec.name == "cifar10":
        from dtf_tpu.data.cifar import cifar_input_fn
        fns = (
            lambda start_step=0: cifar_input_fn(
                cfg.data_dir, True, host_batch, seed=cfg.seed,
                wire=cfg.input_wire, start_step=start_step),
            lambda: cifar_input_fn(cfg.data_dir, False, host_batch,
                                   drop_remainder=cfg.drop_remainder,
                                   wire=cfg.input_wire),
        )
    elif spec.name == "imagenet":
        from dtf_tpu.data.imagenet import imagenet_input_fn
        if cfg.input_service:
            # sharded deterministic multi-process service (the default):
            # batch n is a pure function of (seed, process, n), so
            # killed-at-K resume replays bit-exactly and decode scales
            # across worker PROCESSES.  Eval stays on the threaded
            # pipeline — one ordered unaugmented pass, nothing to make
            # deterministic.
            from dtf_tpu.data.service import service_input_fn
            train_fn = lambda start_step=0: service_input_fn(
                cfg.data_dir, host_batch, seed=cfg.seed,
                num_shards=cfg.input_num_shards,
                num_workers=cfg.input_workers,
                wire=cfg.input_wire, cache_dir=cfg.input_cache_dir,
                cache_limit_mb=cfg.input_cache_limit_mb,
                start_step=start_step)
        else:
            # legacy threaded pipeline: fused native decode, NOT
            # position-exact — a mid-stream resume refuses loudly
            # inside imagenet_input_fn
            train_fn = lambda start_step=0: imagenet_input_fn(
                cfg.data_dir, True, host_batch, seed=cfg.seed,
                num_threads=cfg.datasets_num_private_threads,
                fast_dct=cfg.input_fast_dct,
                scaled_decode=cfg.input_scaled_decode,
                wire=cfg.input_wire, start_step=start_step)
        fns = (
            train_fn,
            lambda: imagenet_input_fn(cfg.data_dir, False, host_batch,
                                      drop_remainder=cfg.drop_remainder,
                                      wire=cfg.input_wire),
        )
    else:
        raise ValueError(f"no input pipeline for dataset {spec.name!r}")
    if cfg.data_format == "channels_first" and not spec.is_sequence:
        # --data_format parity (resnet_cifar_main.py:94-98): batches flow
        # NCHW from here on; the compiled steps transpose back to NHWC
        fns = tuple(_channels_first_factory(fn) for fn in fns)
    return fns


def _channels_first_factory(fn):
    import numpy as np

    def wrapped(*args, **kw):
        for batch in fn(*args, **kw):
            images = np.ascontiguousarray(
                np.asarray(batch[0]).transpose(0, 3, 1, 2))
            yield (images,) + tuple(batch[1:])
    return wrapped


def run(cfg: Config) -> dict:
    """Entry wrapper: arms tracing/chaos, installs the preemption
    guard, and translates a graceful preemption (SIGTERM → emergency
    checkpoint at the step boundary) into the distinct EXIT_PREEMPTED
    exit code the launch.py supervisor restarts without consuming the
    crash budget."""
    from dtf_tpu import chaos
    from dtf_tpu.obs import trace
    from dtf_tpu.train import preemption
    trace.maybe_configure(cfg)
    # run-scoped trace id: the launcher mints one (DTF_TRACE_ID) so
    # every rank's records — steps, checkpoints, eval, data service,
    # PS — share it and `trace_main --request <id>` joins them into
    # one timeline; a standalone run mints its own
    trace.set_default_trace(os.environ.get("DTF_TRACE_ID")
                            or trace.new_trace_id())
    chaos.maybe_configure(cfg)
    preemption.install()
    poller = None
    if cfg.preemption_poll_s:
        # metadata-server preemption signal (GCE/TPU-VM): a pending
        # preemption visible on the metadata endpoint feeds the same
        # SIGTERM latch the guard just installed
        poller = preemption.MetadataPoller(cfg.preemption_poll_s).start()
    metrics_server = None
    if cfg.metrics_port and not (cfg.process_id or 0):
        # rank 0 only (cfg.process_id is None for single-process runs
        # and env-filled by the launcher otherwise — co-hosted ranks
        # must not race for one port); stdlib server, daemon threads
        from dtf_tpu.obs.prom import MetricsServer
        metrics_server = MetricsServer(cfg.metrics_port)
    try:
        return _run(cfg)
    except preemption.Preempted as p:
        log.warning("run preempted at step %d — emergency checkpoint "
                    "written; exiting %d", p.step, preemption.EXIT_PREEMPTED)
        trace.flush()
        raise SystemExit(preemption.EXIT_PREEMPTED)
    except Exception as e:  # noqa: BLE001 — device-loss classification
        from dtf_tpu.train import elastic
        if not (isinstance(e, elastic.DeviceLost)
                or elastic.is_device_loss(e)):
            raise
        step = getattr(e, "step", -1)
        log.warning("accelerators lost at step %d (%s) — exiting %d so "
                    "an --elastic supervisor reshards onto the "
                    "surviving topology", step, e,
                    elastic.EXIT_DEVICE_LOST)
        trace.flush()
        raise SystemExit(elastic.EXIT_DEVICE_LOST)
    finally:
        if metrics_server is not None:
            metrics_server.shutdown()
        if poller is not None:
            poller.stop()
        preemption.restore()


def _run(cfg: Config) -> dict:
    if cfg.plan:
        # --plan auto|<file>: compile the chosen plan into the ordinary
        # parallelism flags BEFORE anything reads them — from here on
        # the run is indistinguishable from the same flags set by hand
        # (bit-identical, tests/test_plan.py).  Infeasible plans die
        # here, loudly, not as an OOM mid-compile.
        # resolve_plan queries the live topology (mesh_spec("") and the
        # attached-device guard), which initializes the jax backend —
        # in a multi-process run the distributed rendezvous must come
        # first, or process_count() reports 1 and the later
        # jax.distributed.initialize refuses an initialized backend
        from dtf_tpu.runtime.mesh import _maybe_init_distributed
        _maybe_init_distributed(cfg)
        from dtf_tpu.plan import resolve_plan
        cfg = resolve_plan(cfg)
    # structured tracing: --trace_dir, or DTF_TRACE_DIR forwarded by the
    # launcher to every rank (idempotent when a main already configured)
    from dtf_tpu.obs import trace
    from dtf_tpu.obs.registry import default_registry
    # metric.log exports are per-run: a second run() in the same
    # process (tests, notebooks) must not inherit the previous run's
    # process-global counters (e.g. PS wire tallies)
    default_registry().reset()
    export_model = None
    if cfg.export_dir:
        # fail fast: don't discover a missing orbax install only after
        # training completes
        from dtf_tpu.train.checkpoint import export_model
    if cfg.clean and cfg.model_dir and os.path.isdir(cfg.model_dir):
        # model_helpers.apply_clean parity (resnet_imagenet_main.py:275)
        shutil.rmtree(cfg.model_dir, ignore_errors=True)
    if cfg.model_dir:
        os.makedirs(cfg.model_dir, exist_ok=True)
    if cfg.distribution_strategy == "parameter_server" and cfg.ps_mode == "async":
        # true-async push/pull against the C++ parameter store; no mesh,
        # no collective rendezvous — each worker steps independently
        # (SURVEY §3.4 semantics)
        from dtf_tpu.parallel import ps
        return ps.run_async(cfg)

    rt = initialize(cfg)
    spec = get_dataset_spec(cfg.dataset)
    import dataclasses
    if cfg.num_classes:
        spec = dataclasses.replace(spec, num_classes=cfg.num_classes)
    if cfg.seq_len and spec.is_sequence:
        spec = dataclasses.replace(spec, seq_len=cfg.seq_len)

    global_batch = effective_global_batch(cfg, rt)
    cfg = cfg.replace(batch_size=global_batch)

    rt.shard_seq = spec.is_sequence
    model_name = "trivial" if cfg.use_trivial_model else cfg.model
    is_moe = model_name.startswith("moe_transformer")
    is_pipeline = model_name.startswith("pipeline_transformer")
    seq_axis = (SEQ_AXIS if spec.is_sequence and cfg.seq_parallelism > 1
                else None)
    model_axis = (MODEL_AXIS if model_name.startswith("transformer")
                  and cfg.model_parallelism > 1 else None)
    # the 'model' axis doubles as the pipeline-stage axis for the
    # stacked-block family
    pipe_axis = (MODEL_AXIS if is_pipeline and cfg.model_parallelism > 1
                 else None)
    # experts ride the batch-splitting axis by default (classic
    # DeepSpeed-MoE/GShard placement — all_to_all token exchange);
    # --model_parallelism with a MoE family instead places them on the
    # 'model' axis (group size decoupled from dp; batch replicated
    # across it, partial-output psum — models/moe.py docstring)
    expert_axis = None
    expert_on_model = is_moe and cfg.model_parallelism > 1
    if is_moe:
        expert_axis = MODEL_AXIS if expert_on_model else DATA_AXIS
    if is_pipeline and cfg.seq_parallelism > 1:
        raise ValueError(
            "pipeline_transformer does not compose with seq_parallelism; "
            "use the plain transformer for ring attention")
    # None flags defer to the model preset's own defaults (the registry
    # partials, e.g. moe_transformer_small's 4 experts)
    model_kw = {}
    if is_moe:
        model_kw = {k: v for k, v in dict(
            num_experts=cfg.num_experts,
            capacity_factor=cfg.moe_capacity_factor,
            aux_weight=cfg.moe_aux_weight,
            router_top_k=cfg.moe_top_k).items() if v is not None}
        if expert_on_model:
            model_kw["expert_axis_along_batch"] = False
    elif is_pipeline:
        if cfg.pipeline_interleave > 1:
            if pipe_axis is None:
                raise ValueError(
                    "--pipeline_interleave > 1 needs pipeline stages: "
                    "set --model_parallelism > 1")
            model_kw["interleave"] = cfg.pipeline_interleave
        if cfg.num_microbatches is not None:
            model_kw = dict(model_kw, num_microbatches=cfg.num_microbatches)
        else:
            # auto-scale the GPipe schedule: bubble fraction is
            # (pp-1)/(M+pp-1), so target M = 4·pp (≤20% bubble) and
            # halve until it divides the per-shard batch
            pp = max(cfg.model_parallelism, 1)
            per_shard = global_batch // rt.num_replicas
            m = 4 * pp
            while m > 1 and per_shard % m:
                m //= 2
            model_kw = dict(model_kw, num_microbatches=max(m, 1))
    if cfg.remat or cfg.remat_policy:
        if model_name == "resnet50":
            # vision remat is the selective conv_out/bn_stats policy
            # (models/resnet.py RESNET_REMAT_POLICY) — there is no
            # full-remat or "dots" variant to select
            if cfg.remat_policy:
                raise ValueError(
                    "--remat_policy applies to the transformer families; "
                    "resnet50 takes plain --remat (selective "
                    "conv_out/bn_stats policy)")
            model_kw = dict(model_kw, remat=True)
        elif not model_name.startswith(
                ("transformer", "moe_transformer", "pipeline_transformer")):
            flag = "--remat" if cfg.remat else "--remat_policy"
            raise ValueError(
                f"{flag} is implemented for the transformer families and "
                f"resnet50, not {model_name!r}")
        else:
            model_kw = dict(model_kw, remat=True)
            if cfg.remat_policy:
                model_kw = dict(model_kw, remat_policy=cfg.remat_policy)
    shard_vocab = bool(cfg.shard_lm_head and model_axis is not None)
    if cfg.shard_lm_head and model_axis is None:
        raise ValueError(
            "--shard_lm_head needs the plain transformer family with "
            "--model_parallelism > 1")
    if shard_vocab:
        model_kw = dict(model_kw, shard_vocab=True)
    model, l2 = build_model(
        model_name, num_classes=spec.num_classes, dtype=cfg.compute_dtype,
        bn_axis=DATA_AXIS if cfg.sync_bn else None, seq_axis=seq_axis,
        model_axis=model_axis, expert_axis=expert_axis, pipe_axis=pipe_axis,
        **model_kw)

    import functools
    param_spec_fn = None
    if model_axis is not None:
        from dtf_tpu.models.transformer import param_partition_specs
        param_spec_fn = functools.partial(param_partition_specs,
                                          model_axis=model_axis,
                                          shard_vocab=shard_vocab)
    elif is_moe:
        from dtf_tpu.models.moe import moe_param_partition_specs
        param_spec_fn = functools.partial(moe_param_partition_specs,
                                          expert_axis=expert_axis)
    elif pipe_axis is not None:
        from dtf_tpu.models.pipeline_lm import pipeline_param_partition_specs
        param_spec_fn = functools.partial(pipeline_param_partition_specs,
                                          pipe_axis=pipe_axis)
    # uint8 wire: normalization runs inside the compiled step; the
    # wire→normalize decision is single-sourced in for_config (the
    # async-PS path calls the same function)
    from dtf_tpu.data.normalize import for_config
    trainer = Trainer(cfg, rt, model, l2, spec, param_spec_fn=param_spec_fn,
                      vocab_axis=MODEL_AXIS if shard_vocab else None,
                      normalize_fn=for_config(cfg, spec))
    train_fn, eval_fn = make_input_fns(cfg, spec, global_batch)

    train_iter = train_fn()
    first = next(train_iter)
    state = trainer.init_state(jax.random.key(cfg.seed), first)

    callbacks = []
    ckpt_mod = None
    ckpt_cb = None
    if (not cfg.skip_checkpoint or cfg.resume) and cfg.model_dir:
        try:
            from dtf_tpu.train import checkpoint as ckpt_mod
        except ImportError:
            if cfg.resume:
                raise ImportError(
                    "--resume needs orbax-checkpoint; install it or drop "
                    "the flag")
            log.warning("checkpointing disabled: orbax-checkpoint not "
                        "installed (pass --skip_checkpoint to silence)")
    resumed_step = 0
    if ckpt_mod is not None:
        # all processes participate (orbax coordinates the collective
        # write of the replicated state — the rank-0-write equivalent).
        # The manifest carries the host half of crash-exact resume:
        # data position + the seed that derives the pipeline RNGs.
        spe = max(trainer.steps_per_epoch, 1)
        # mirror make_input_fns' branch order: synthetic/no-data_dir runs
        # never touch the service, so their manifests must not claim its
        # host_state (or resume would enforce num_shards against a
        # stream that has no shards)
        service_on = (spec.name == "imagenet" and cfg.input_service
                      and bool(cfg.data_dir)
                      and not cfg.use_synthetic_data)

        def host_state_fn(step):
            data = {"scheme": "position-derived", "dataset": cfg.dataset,
                    "start_step": step}
            if service_on:
                # per-shard next-batch positions: derivable from the
                # step alone, carried so the manifest is self-describing
                # and the resume contract auditable — and num_shards,
                # which is part of the stream's IDENTITY (the merged
                # order depends on it), validated below on restore
                from dtf_tpu.data.service import shard_positions
                data["num_shards"] = cfg.input_num_shards
                data["shard_positions"] = shard_positions(
                    step, cfg.input_num_shards)
            return {"seed": cfg.seed, "global_step": step,
                    "epoch": step // spe, "step_in_epoch": step % spe,
                    # which mesh WROTE this step — informational for
                    # elastic post-mortems, never validated on restore
                    # (topology is exactly what an elastic resume may
                    # change; the canonical layout is topology-free)
                    "topology": {"devices": rt.num_devices,
                                 "replicas": rt.num_replicas,
                                 "processes": jax.process_count()},
                    "data": data}
        ckpt_cb = ckpt_mod.CheckpointCallback(
            cfg.model_dir, every_steps=cfg.checkpoint_steps,
            host_state_fn=host_state_fn, keep=cfg.checkpoint_keep,
            # ZeRO runs save the canonical stage-0 layout (full-shaped
            # params + optimizer state): the checkpoint is
            # stage-portable — restore into any --zero_stage, or into
            # serving via the bridge
            state_transform=(trainer.canonical_state if trainer.zero
                             else None))
        if cfg.resume:
            if trainer.zero:
                # ZeRO: checkpoints hold the canonical form; restore
                # against the stage-independent template, then place
                # into this run's stage layout (sliced params/opt
                # state with their shardings)
                restored = ckpt_cb.ckpt.restore(
                    trainer.canonical_template())
                if restored is None and ckpt_cb.ckpt.verified_steps():
                    # steps that VERIFY (sha256-intact) but restore
                    # into the canonical template for none of the
                    # candidates are a layout mismatch, not corruption
                    # — almost certainly a pre-canonical-format
                    # --optimizer_sharding run (sliced optimizer
                    # state on disk).  Restarting from scratch here
                    # would silently discard the whole run.
                    raise ValueError(
                        f"--resume: checkpoints under "
                        f"{cfg.model_dir}/checkpoints pass integrity "
                        f"verification but do not match the canonical "
                        f"ZeRO checkpoint layout (full-shaped params + "
                        f"optimizer state).  They likely predate the "
                        f"stage-portable format (older "
                        f"--optimizer_sharding runs saved sliced "
                        f"state).  Resume them with the code revision "
                        f"that wrote them, or restart without --resume")
                if restored is not None:
                    restored = trainer.staged_state(restored)
            else:
                # restore with the state's own per-leaf shardings
                # (TP/EP/PP states are not replicated — a blanket
                # replicated sharding would silently unshard them)
                state_shardings = jax.tree_util.tree_map(
                    lambda x: x.sharding, state)
                restored = ckpt_cb.ckpt.restore(state,
                                                sharding=state_shardings)
            if restored is not None:
                state = restored
                resumed_step = int(jax.device_get(state.step))
                host = ckpt_cb.ckpt.host_state(
                    ckpt_cb.ckpt.last_restored_step)
                if host and host.get("seed") is not None \
                        and host["seed"] != cfg.seed:
                    # a different seed re-derives a DIFFERENT data
                    # stream: the resumed run would silently train on
                    # other batches than the run it claims to continue
                    raise ValueError(
                        f"--resume seed mismatch: checkpoint was written "
                        f"with seed {host['seed']}, this run has "
                        f"--seed {cfg.seed}; crash-exact resume needs the "
                        f"same seed (pass --seed {host['seed']})")
                ckpt_shards = (host or {}).get("data", {}).get("num_shards")
                if service_on and ckpt_shards is not None \
                        and int(ckpt_shards) != cfg.input_num_shards:
                    # num_shards is part of the merged stream's identity
                    # (batch n = shard n%S, local batch n//S): resuming
                    # with a different count would silently continue on
                    # a DIFFERENT stream than the run it claims to be
                    raise ValueError(
                        f"--resume input_num_shards mismatch: checkpoint "
                        f"was written with {ckpt_shards} shard(s), this "
                        f"run has --input_num_shards "
                        f"{cfg.input_num_shards}; the merged batch order "
                        f"depends on the shard count (pass "
                        f"--input_num_shards {ckpt_shards}).  Worker "
                        f"count, by contrast, may change freely")
            elif cfg.eval_only:
                # evaluating random init as if it were a checkpoint would
                # silently report garbage — fail instead
                raise FileNotFoundError(
                    f"--eval_only --resume: no checkpoint found under "
                    f"{cfg.model_dir}/checkpoints; point --model_dir at a "
                    f"trained run")
            else:
                log.warning(
                    "--resume: no checkpoint found under %s/checkpoints — "
                    "training from scratch", cfg.model_dir)
        if not cfg.skip_checkpoint:
            callbacks.append(ckpt_cb)
    # elastic supervision (DTF_ELASTIC_DEVICES exported by launch.py
    # --elastic): verify the attached topology matches the
    # supervisor's surviving-capacity accounting, and stamp the resume
    # point + topology into the trace (no-op otherwise)
    from dtf_tpu.train import elastic
    elastic.note_elastic_resume(rt, resumed_step)
    if cfg.enable_tensorboard and cfg.model_dir and is_coordinator():
        from dtf_tpu.utils.tensorboard import TensorBoardCallback
        callbacks.append(TensorBoardCallback(cfg.model_dir))

    if cfg.eval_only:
        # before the prefetcher: no training batches are consumed, so
        # no background transfer thread should start
        from dtf_tpu.utils.logs import build_stats
        eval_output = trainer.evaluate(state, eval_fn())
        stats = build_stats({}, eval_output, None)
        log.info("Run stats (eval only): %s", stats)
        return stats

    if resumed_step > 0:
        # crash-exact resume: rebuild the stream POSITIONED at the
        # restored step (the probe iterator above consumed batch 0 of a
        # step-0 stream — close it so its worker threads/buffers don't
        # idle alongside the real pipeline for the whole run; the loop
        # starts at batch resumed_step and must see exactly that batch)
        if hasattr(train_iter, "close"):
            train_iter.close()
        first = None
        prefetched = DevicePrefetcher(train_fn(start_step=resumed_step),
                                      rt, buffer_size=2)
    else:
        prefetched = DevicePrefetcher(itertools.chain([first], train_iter),
                                      rt, buffer_size=2)

    # logger.benchmark_context parity (resnet_cifar_main.py:234)
    from dtf_tpu.utils.benchmark_logger import benchmark_context
    try:
        with benchmark_context(cfg) as bench_log:
            state, stats = trainer.fit(
                state, prefetched,
                eval_iter_fn=None if cfg.skip_eval else eval_fn,
                callbacks=callbacks)
            if bench_log is not None:
                step_now = int(jax.device_get(state.step))
                bench_log.log_stats(stats, global_step=step_now)
                # process-global obs registry (PS wire counters etc.)
                # rides the same metric.log; empty registries write
                # nothing
                bench_log.log_registry(default_registry(),
                                       global_step=step_now)
    finally:
        # EVERY exit — normal, watchdog TrainingAnomaly abort,
        # preemption — lands the in-flight async orbax save and seals
        # its manifest; an orphaned write is exactly the truncated
        # checkpoint the integrity fallback exists to catch
        if ckpt_cb is not None:
            ckpt_cb.ckpt.close()

    if export_model is not None:
        # --export_dir parity: final inference variables, written once
        # (replicated state ⇒ the collective write is coordinator-led);
        # ZeRO states export their canonical full-shaped params
        export_model(cfg.export_dir, trainer.canonical_state(state)
                     if trainer.zero else state)

    log.info("Run stats: %s",
             {k: v for k, v in stats.items() if k != "step_timestamp_log"})
    return stats
