"""Serving front-end: router + N replica serve processes.

The one-command replica-tier entry: spawns ``--router_replicas``
replica processes (cli/replica_main.py — each a full ServeEngine,
optionally TP-sharded via --serve_tp), stands up the health-checked
router over them (serve/router.py: prefix-affine placement, deadlines,
retry/failover, respawn budget), drives it with synthetic shared-
prefix traffic, and reports router + per-replica stats in the
BenchmarkMetric format.

Examples:
  # 2 replicas on fresh params (pipeline smoke; outputs are noise):
  python -m dtf_tpu.cli.router_main --serve_random_init \
      --model transformer_small --router_replicas 2 --serve_requests 16

  # 4 replicas over a trained checkpoint, chaos-killing replica 0 at
  # the 6th dispatch (the failover path, live):
  python -m dtf_tpu.cli.router_main --model_dir /tmp/lm_run \
      --router_replicas 4 --fault replica_kill@replica0:req:6

  # HA pair on shared storage: the leader journals + holds the lease,
  # the standby takes over (fencing epoch +1, zero replica respawns)
  # the moment the leader dies:
  python -m dtf_tpu.cli.router_main --serve_random_init --router_ha \
      --rendezvous_dir /shared/tier &
  python -m dtf_tpu.cli.router_main --serve_random_init \
      --router_standby --rendezvous_dir /shared/tier

SIGTERM drains the tier: the router sheds new submits, waits out
in-flight work, SIGTERMs the replicas (each drains + exits 0), then
exits 0 itself.
"""

from __future__ import annotations

import logging
import os
import signal
import sys
import tempfile
import time

import numpy as np

from dtf_tpu.config import parse_flags

log = logging.getLogger("dtf_tpu")

ROUTER_DEFAULTS = dict(
    model="transformer_small",
    dataset="lm",
    skip_eval=True,
)

# flags forwarded verbatim to every replica process (the engine-shape
# subset: every replica must build the same engine)
_FORWARD_FLAGS = (
    "model", "num_classes", "seed", "dtype", "model_dir", "export_dir",
    "serve_max_batch", "serve_max_seq_len", "serve_queue_size",
    "serve_max_delay_ms", "kv_page_size", "kv_pool_pages",
    "serve_prefill_chunk", "serve_prefix_sharing", "serve_tp",
    "heartbeat_secs", "rendezvous_dir", "serve_host",
)


def replica_command(cfg, random_init: bool) -> list:
    from dtf_tpu.config.flags import Config
    import dataclasses
    defaults = {f.name: f.default for f in dataclasses.fields(Config)}
    cmd = [sys.executable, "-m", "dtf_tpu.cli.replica_main"]
    for name in _FORWARD_FLAGS:
        val = getattr(cfg, name)
        if val is None or val == defaults.get(name):
            continue
        cmd += [f"--{name}", str(val)]
    if random_init:
        cmd.append("--serve_random_init")
    return cmd


def run_router(cfg, random_init: bool = False) -> dict:
    from dtf_tpu.serve import Backpressure, DeadlineExceeded, Router
    from dtf_tpu.serve.router import replica_spawner

    if cfg.router_health_timeout_s <= cfg.heartbeat_secs:
        # checked HERE, not in Config: only a router run pairs the two
        raise ValueError(
            f"--router_health_timeout_s ({cfg.router_health_timeout_s}) "
            f"must exceed --heartbeat_secs ({cfg.heartbeat_secs}) or "
            f"every healthy replica reads as dead between beats")
    rendezvous = cfg.rendezvous_dir or tempfile.mkdtemp(
        prefix="dtf_router_")
    cfg = cfg.replace(rendezvous_dir=rendezvous)

    # --- high availability (serve/ha.py + serve/journal.py) ---
    # leader: take the lease, journal every request, renew at ttl/3.
    # standby: wait out the leader's lease, then take over under the
    # next fencing epoch, adopting (never respawning) the live tier.
    ha_on = cfg.router_ha or cfg.router_standby
    ha_mod = lease = keeper = None
    journal_file = None
    epoch = 0
    if ha_on:
        from dtf_tpu.serve import ha as ha_mod
        from dtf_tpu.serve import journal as journal_mod
        journal_file = journal_mod.journal_path(rendezvous)
        lease = ha_mod.LeaderLease(rendezvous,
                                   ttl_s=cfg.router_lease_ttl_s)

    # /healthz must answer DURING the standby's wait (external probes
    # watch the takeover through it), so the payload source is swapped
    # once the router exists
    health_box = {"fn": lambda: {"ok": True, "role": "starting"}}
    metrics_server = None
    router_box: dict = {}
    if cfg.metrics_port:
        from dtf_tpu.obs.prom import MetricsServer
        from dtf_tpu.obs.registry import default_registry
        metrics_server = MetricsServer(
            cfg.metrics_port,
            registry_fn=lambda: (router_box["r"].metrics
                                 if "r" in router_box
                                 else default_registry()),
            health_fn=lambda: health_box["fn"]())

    if cfg.router_standby:
        health_box["fn"] = lambda: ha_mod.standby_health(lease)
        log.warning("standby: watching leader lease (ttl %.1fs) under "
                    "%s", cfg.router_lease_ttl_s, rendezvous)
        epoch = ha_mod.wait_for_takeover(lease)
        log.warning("standby: lease expired — taking over at epoch %d",
                    epoch)
    elif ha_on:
        epoch = lease.acquire()
        if epoch is None:
            if metrics_server is not None:
                metrics_server.shutdown()
            raise RuntimeError(
                "leader lease already held — start this router with "
                "--router_standby (or remove the stale "
                "router_lease.json)")

    env_extra = {}
    if cfg.trace_dir:
        env_extra["DTF_TRACE_DIR"] = os.path.abspath(cfg.trace_dir)
    if cfg.fault:
        env_extra["DTF_FAULT"] = cfg.fault
    # --metrics_port N makes the WHOLE tier scrapable from one flag:
    # the router serves its registry on N, replica K on N+1+K (each is
    # a separate process — one port each), every endpoint with a
    # /healthz probe
    extra_flags = None
    if cfg.metrics_port:
        extra_flags = (lambda rid:
                       ["--metrics_port", str(cfg.metrics_port + 1 + rid)])
    # per-replica checkpoint overrides, shared BY REFERENCE between
    # the router (the rollout controller writes it) and the spawner
    # (reads it at spawn time → DTF_SERVE_CHECKPOINT)
    ckpt_map: dict = {}
    # the standby never owns replica processes: the (dead) leader
    # spawned them, and a takeover that respawned the tier would turn
    # a router blip into N cold-starts
    spawn = None
    if not cfg.router_standby:
        spawn = replica_spawner(replica_command(cfg, random_init),
                                rendezvous, env_extra=env_extra,
                                extra_flags=extra_flags,
                                checkpoint_map=ckpt_map)
    router = Router(
        cfg.router_replicas, rendezvous, spawn=spawn,
        checkpoint_map=ckpt_map,
        journal_path=journal_file,
        journal_fsync_s=cfg.router_journal_fsync_s,
        epoch=epoch or 0,
        role="leader",   # by construction: it holds the lease (HA) or
                         # is the only router (HA off)
        page_size=cfg.kv_page_size or 16,
        placement=cfg.router_placement,
        deadline_s=cfg.router_deadline_s,
        admission_limit=cfg.router_admission,
        probe_interval_s=cfg.router_probe_s,
        health_timeout_s=cfg.router_health_timeout_s,
        replica_inflight=(cfg.router_replica_inflight
                          or cfg.serve_queue_size),
        max_respawns=cfg.router_max_respawns,
        respawn_window_s=cfg.router_respawn_window_s,
        respawn_backoff_s=cfg.router_respawn_backoff_s,
        hedge_s=cfg.router_hedge_s,
        prefill_replicas=cfg.router_prefill_replicas,
        seed=cfg.seed)

    def _on_sigterm(signum, frame):
        router.begin_drain()
        os.write(2, b"router: SIGTERM - draining tier\n")

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:
        pass

    router_box["r"] = router
    health_box["fn"] = router.health
    if ha_on:
        # the renewal heartbeat: a lease lost (stall, partition,
        # operator force-take) fences this router on the spot
        keeper = ha_mod.LeaseKeeper(lease, on_fenced=router.fence)
        keeper.start()

    log.info("router: %s %d replicas (rendezvous %s)",
             "adopting" if cfg.router_standby else "spawning",
             cfg.router_replicas, rendezvous)
    # first-compile on a CPU replica can take minutes; the wait only
    # ends early when every replica heartbeats + announces.  From here
    # on the tier must come down with us — a traffic-loop exception
    # must not leave N serve processes running
    try:
        router.start(wait_s=600.0, adopt=cfg.router_standby)
        adopt_summary = None
        if cfg.router_standby:
            adopt_summary = ha_mod.take_over(
                router, rollout_state_path=cfg.rollout_state)
            log.warning("standby: takeover complete — %s", {
                k: v for k, v in adopt_summary.items()
                if k != "handles"})
        out = _drive_traffic(cfg, router)
        if adopt_summary is not None:
            out["takeover_epoch"] = router.epoch
            out["readopted"] = adopt_summary["readopted"]
            out["redispatched"] = adopt_summary["redispatched"]
        return out
    except BaseException:
        router.stop(drain=False)
        raise
    finally:
        if keeper is not None:
            keeper.stop()
        if lease is not None:
            lease.release()
        if metrics_server is not None:
            metrics_server.shutdown()


def _drive_traffic(cfg, router) -> dict:
    from dtf_tpu.serve import Backpressure, DeadlineExceeded

    rng = np.random.default_rng(cfg.seed)
    vocab = cfg.num_classes or 32_768
    ps = cfg.kv_page_size or 16
    # shared-prefix traffic: a few "system prompts" (whole pages) with
    # per-request tails — the shape prefix-affine placement exists for
    n_groups = max(1, min(4, cfg.router_replicas))
    sys_prompts = [rng.integers(0, vocab, (2 * ps,)).astype(np.int32)
                   for _ in range(n_groups)]

    def make_prompt(i):
        tail = rng.integers(
            0, vocab, (int(rng.integers(1, cfg.serve_prompt_len + 1)),)
        ).astype(np.int32)
        return np.concatenate([sys_prompts[i % n_groups], tail])

    def resolve(handles, outcomes):
        tokens = 0
        for h in handles:
            try:
                r = h.result(timeout=cfg.router_deadline_s + 30)
                tokens += len(r.tokens)
                outcomes["ok"] += 1
            except Backpressure:
                outcomes["backpressure"] += 1
            except DeadlineExceeded:
                outcomes["deadline"] += 1
        return tokens

    t0 = time.time()
    handles = []
    outcomes = {"ok": 0, "backpressure": 0, "deadline": 0}
    for i in range(cfg.serve_requests):
        try:
            handles.append(router.submit(
                make_prompt(i), max_new_tokens=cfg.serve_max_new_tokens,
                temperature=cfg.serve_temperature))
        except Backpressure:
            outcomes["backpressure"] += 1
    tokens = resolve(handles, outcomes)

    # --rollout_checkpoint: a live mid-traffic rollout — the control-
    # surface op, driven while waves of traffic keep flowing (the
    # canary gate compares MIRRORED LIVE requests, so the rollout
    # needs traffic to judge the new model against)
    rollout_state = None
    if cfg.rollout_checkpoint:
        import threading

        box = {}

        def _roll():
            try:
                box["state"] = router.rollout(
                    cfg.rollout_checkpoint,
                    state_path=cfg.rollout_state,
                    canary_requests=cfg.rollout_canary_requests,
                    mirror_fraction=cfg.rollout_mirror_fraction,
                    max_divergence=cfg.rollout_max_divergence,
                    warm_timeout_s=cfg.rollout_warm_timeout_s)
            except Exception as e:  # noqa: BLE001 — surfaced below
                box["error"] = e

        rt = threading.Thread(target=_roll, name="rollout", daemon=True)
        rt.start()
        wave = 0
        while rt.is_alive():
            hs = []
            for i in range(4):
                try:
                    hs.append(router.submit(
                        make_prompt(wave * 4 + i),
                        max_new_tokens=cfg.serve_max_new_tokens,
                        temperature=cfg.serve_temperature))
                except Backpressure:
                    outcomes["backpressure"] += 1
            tokens += resolve(hs, outcomes)
            wave += 1
            rt.join(timeout=0.25)
        if "error" in box:
            raise box["error"]
        rollout_state = box.get("state")
        log.warning("rollout finished: %s",
                    rollout_state.phase if rollout_state else "?")
    wall = time.time() - t0

    out = {
        "requests": cfg.serve_requests,
        "completed": outcomes["ok"],
        "backpressure": outcomes["backpressure"],
        "deadline_exceeded": outcomes["deadline"],
        "tokens_per_second": tokens / wall if wall > 0 else 0.0,
        "replicas": cfg.router_replicas,
        "failovers": router.metrics.get("router_failover_total").value,
        "affinity_hits": router.metrics.get(
            "router_affinity_hits_total").value,
        "per_replica_completed": [
            router.replica_completed(i)
            for i in range(cfg.router_replicas)],
    }
    if rollout_state is not None:
        out["rollout_phase"] = rollout_state.phase
        out["rollout_reason"] = rollout_state.reason
        out["canary_compared"] = rollout_state.compared
        out["canary_diverged"] = rollout_state.diverged
    if cfg.benchmark_log_dir:
        from dtf_tpu.utils.benchmark_logger import BenchmarkFileLogger
        blog = BenchmarkFileLogger(cfg.benchmark_log_dir)
        blog.log_run_info(cfg.model, cfg.dataset, cfg.to_dict(),
                          test_id=cfg.benchmark_test_id)
        blog.log_registry(router.metrics)
    router.stop(drain=True)
    log.info("Router stats: %s", out)
    return out


def main(argv=None) -> dict:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s")
    argv = list(argv if argv is not None else sys.argv[1:])
    random_init = "--serve_random_init" in argv
    if random_init:
        argv.remove("--serve_random_init")
    cfg = parse_flags(argv, defaults=ROUTER_DEFAULTS)
    from dtf_tpu import chaos
    from dtf_tpu.obs import trace
    if cfg.trace_dir:
        # the router is a NAMED stream: trace_router.jsonl next to the
        # replicas' trace_rank{K}.jsonl — trace_main --merge interleaves
        trace.configure(cfg.trace_dir, stream="router")
    chaos.maybe_configure(cfg)   # replica_kill / net_partition fire here
    return run_router(cfg, random_init=random_init)


if __name__ == "__main__":
    main()
