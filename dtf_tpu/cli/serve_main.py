"""Serving entry point — checkpoint → KV-cache decode → batched traffic.

The training mains end at a checkpoint; this main is its consumer: it
loads train-format (or --export_dir-format) variables through the
serve bridge, stands up the dynamic batching engine, drives it with
synthetic traffic — every request consumed through its token STREAM —
and reports latency percentiles + tokens/s in the BenchmarkMetric
format (--benchmark_log_dir writes metric.log).

`--serve_tp N` serves tensor-parallel: an N-chip 'model' mesh, params
restored DIRECTLY into the Megatron layout (no replicated
intermediate) and the KV page pool sharded on its head dim — a model
that trains sharded never has to fit on one chip to serve.
`--serve_prefix_sharing` (default on, paged cache) makes a shared
system prompt cost one physical page copy across the batch.

Examples:
  # serve a trained LM checkpoint:
  python -m dtf_tpu.cli.serve_main --model_dir /tmp/lm_run \
      --model transformer_small --serve_requests 32

  # no checkpoint yet?  --serve_random_init stands up the engine on
  # fresh params (pipeline smoke test; answers are noise):
  python -m dtf_tpu.cli.serve_main --serve_random_init \
      --model transformer_small --serve_requests 8
"""

from __future__ import annotations

import logging
import os
import signal
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from dtf_tpu.config import parse_flags

log = logging.getLogger("dtf_tpu")

SERVE_DEFAULTS = dict(
    model="transformer_small",
    dataset="lm",
    skip_eval=True,
)


def build_serving_engine(cfg, random_init: bool = False,
                         replica_rank=None):
    """Model + params + ServeEngine from a Config — shared by this
    main and the replica-tier entry (cli/replica_main.py).

    The engine gets an obs HEARTBEAT when the launcher (or the serving
    router) exported DTF_HEARTBEAT_DIR: the engine loop rewrites
    ``heartbeat_rank{N}.json`` once per iteration, so launch.py's hang
    watchdog — and the router's health probe — cover serving exactly
    like they cover train ranks."""
    from dtf_tpu.models import build_model
    from dtf_tpu.obs.watchdog import Heartbeat
    from dtf_tpu.serve import (ServeEngine, load_for_serving,
                               serving_memory_plan, serving_mesh)
    from dtf_tpu.serve.bridge import place_for_serving

    if not cfg.model.startswith("transformer"):
        raise ValueError(
            f"serving is implemented for the plain transformer LM family, "
            f"not {cfg.model!r}")
    model, _ = build_model(cfg.model, num_classes=cfg.num_classes,
                           dtype=cfg.compute_dtype)
    max_seq = cfg.serve_max_seq_len or model.max_seq_len
    # --serve_tp N: an N-chip 'model'-axis mesh; the bridge restores
    # DIRECTLY into the Megatron layout (no replicated intermediate)
    # and the engine's Decoder runs every step under shard_map
    mesh = serving_mesh(cfg.serve_tp) if cfg.serve_tp > 1 else None
    if random_init:
        log.warning("--serve_random_init: serving FRESH parameters — "
                    "pipeline smoke test only, outputs are noise")
        variables = {"params": model.init(
            jax.random.key(cfg.seed),
            jnp.zeros((1, max_seq), jnp.int32))["params"]}
        variables = place_for_serving(variables, mesh=mesh,
                                      model_parallelism=cfg.serve_tp)
    else:
        variables = load_for_serving(model_dir=cfg.model_dir,
                                     export_dir=cfg.export_dir, mesh=mesh,
                                     model_parallelism=cfg.serve_tp)

    # paged KV cache by default (--kv_page_size 0 restores the
    # contiguous per-slot layout); the memory plan makes pool sizing a
    # logged decision
    serving_memory_plan(model, num_slots=cfg.serve_max_batch,
                        max_seq_len=max_seq,
                        kv_page_size=cfg.kv_page_size,
                        kv_pool_pages=cfg.kv_pool_pages,
                        model_parallelism=cfg.serve_tp)
    engine = ServeEngine(
        model, variables["params"],
        max_batch=cfg.serve_max_batch, max_seq_len=max_seq,
        max_delay_s=cfg.serve_max_delay_ms / 1000.0,
        queue_size=cfg.serve_queue_size, seed=cfg.seed,
        kv_page_size=cfg.kv_page_size or None,
        kv_pool_pages=cfg.kv_pool_pages or None,
        # Config.validate guarantees serve_prefill_chunk is None when
        # the paged cache is off, so this never trips the engine's
        # contradiction check
        prefill_chunk=cfg.serve_prefill_chunk,
        prefix_sharing=cfg.serve_prefix_sharing and bool(cfg.kv_page_size),
        mesh=mesh,
        heartbeat=Heartbeat.from_env(rank=replica_rank,
                                     interval_s=cfg.heartbeat_secs))
    return model, engine


def serve(cfg, random_init: bool = False) -> dict:
    """Build model + params + engine from a Config; run the synthetic
    traffic demo; return the stats dict.  Library entry for tests."""
    from dtf_tpu.serve import collect_stats

    model, engine = build_serving_engine(cfg, random_init=random_init)

    # --metrics_port: the engine registry (queue depth, prefix hits,
    # decode-step MFU ledger gauges) live over Prometheus + /healthz
    metrics_server = None
    if cfg.metrics_port:
        from dtf_tpu.obs.prom import MetricsServer
        metrics_server = MetricsServer(
            cfg.metrics_port, registry_fn=lambda: engine.metrics,
            health_fn=lambda: {"ok": not engine.draining,
                               "draining": engine.draining,
                               "outstanding": engine.outstanding})

    # serve drain: SIGTERM (the preemption signal) stops admissions —
    # new submits shed with retry_after — finishes in-flight decodes,
    # and the process exits 0 (a drained replica is a clean exit the
    # supervisor does not classify as a crash).  The handler body is
    # async-signal-minimal: one lock-free engine call + one os.write.
    drained = {"signaled": False}

    def _on_sigterm(signum, frame):
        drained["signaled"] = True
        engine.begin_drain()
        os.write(2, b"serve: SIGTERM - draining (admissions shed, "
                    b"in-flight finishing)\n")

    old_handler = None
    try:
        old_handler = signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:  # not the main thread (library/test use)
        pass

    from dtf_tpu.serve.engine import Backpressure
    rng = np.random.default_rng(cfg.seed)
    vocab = model.vocab_size
    handles = []
    shed_by_drain = 0
    streamed_tokens = 0
    t0 = time.time()

    def _consume(handle):
        # the streaming client shape: render each token as its decode
        # step retires (first-token latency, not full-retire latency).
        # Tokens counted here flowed through the per-token path; the
        # engine's serve_stream_lag_s histogram records consumer lag
        n = 0
        for _ in handle.stream(timeout=600):
            n += 1
        return n

    try:
        import concurrent.futures as cf

        # synthetic traffic: varied-length prompts, all submitted up
        # front (a burst — the shape that exercises batching + queue),
        # each consumed through its token STREAM by a client thread
        with cf.ThreadPoolExecutor(max_workers=8) as ex:
            consumers = []
            for _ in range(cfg.serve_requests):
                plen = int(rng.integers(1, cfg.serve_prompt_len + 1))
                prompt = rng.integers(0, vocab, (plen,)).astype(np.int32)
                try:
                    h = engine.submit(
                        prompt, max_new_tokens=cfg.serve_max_new_tokens,
                        temperature=cfg.serve_temperature)
                except Backpressure:
                    # drain (or a genuinely full queue): the request is
                    # the client's to retry elsewhere
                    shed_by_drain += 1
                    continue
                handles.append(h)
                consumers.append(ex.submit(_consume, h))
            streamed_tokens = sum(c.result() for c in consumers)
        for h in handles:
            h.result(timeout=600)
        wall = time.time() - t0
        engine.stop()  # drain=True: waits out queued + in-flight work
    finally:
        if old_handler is not None:
            signal.signal(signal.SIGTERM, old_handler)
        if metrics_server is not None:
            metrics_server.shutdown()
    if drained["signaled"]:
        log.info("serve: drained after SIGTERM (%d in-flight finished, "
                 "%d shed) — exiting 0", len(handles), shed_by_drain)

    stats = collect_stats(engine.completed, engine.shed_count,
                          wall_time_s=wall)
    if cfg.benchmark_log_dir:
        from dtf_tpu.utils.benchmark_logger import BenchmarkFileLogger
        blog = BenchmarkFileLogger(cfg.benchmark_log_dir)
        blog.log_run_info(cfg.model, cfg.dataset, cfg.to_dict(),
                          test_id=cfg.benchmark_test_id)
        blog.log_serving_stats(stats)
        # live engine registry (queue depth, sheds, slot occupancy,
        # latency histogram) in the same metric.log format
        blog.log_registry(engine.metrics)
    out = {
        "requests": stats.num_requests,
        "shed": stats.num_shed,
        "tokens_per_second": stats.tokens_per_s,
        "latency_p50_s": stats.latency_p50_s,
        "latency_p99_s": stats.latency_p99_s,
        "ttft_p50_s": stats.ttft_p50_s,
        "streamed_tokens": streamed_tokens,
        "tp": cfg.serve_tp,
    }
    log.info("Serve stats: %s", out)
    return out


def main(argv=None) -> dict:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s")
    argv = list(argv if argv is not None else sys.argv[1:])
    # one serving-only switch, kept out of Config: random-init serving
    # is a smoke-test posture, not a run configuration
    random_init = "--serve_random_init" in argv
    if random_init:
        argv.remove("--serve_random_init")
    cfg = parse_flags(argv, defaults=SERVE_DEFAULTS)
    # --trace_dir: serve batch-form/decode spans + shed anomalies
    from dtf_tpu.obs import trace
    trace.maybe_configure(cfg)
    return serve(cfg, random_init=random_init)


if __name__ == "__main__":
    main()
