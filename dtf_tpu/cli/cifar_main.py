"""CIFAR-10 ResNet-56 entry point.

TPU-native successor of reference resnet_cifar_main.py (and its
_dist/_dist_1/_ps_*/_horovod variants — the per-rank file copies
collapse into flags/env because per-process identity is config, not
code; SURVEY §7.9).

Examples:
  python -m dtf_tpu.cli.cifar_main --use_synthetic_data --train_steps 1 \
      --batch_size 4 --distribution_strategy off
  python -m dtf_tpu.cli.cifar_main --data_dir /data/cifar-10-batches-bin \
      --distribution_strategy mirrored
"""

from __future__ import annotations

import logging
import sys

from dtf_tpu.config import parse_flags
from dtf_tpu.cli.runner import run

# per-dataset defaults — parity with define_cifar_flags + set_defaults
# (resnet_cifar_main.py:223-230: epochs 182, batch 128)
CIFAR_DEFAULTS = dict(
    model="resnet56",
    dataset="cifar10",
    train_epochs=182,
    batch_size=128,
    epochs_between_evals=10,
)


def main(argv=None) -> dict:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s")
    cfg = parse_flags(argv if argv is not None else sys.argv[1:],
                      defaults=CIFAR_DEFAULTS)
    # --trace_dir / DTF_TRACE_DIR tracing is configured by run() itself
    return run(cfg)


if __name__ == "__main__":
    main()
