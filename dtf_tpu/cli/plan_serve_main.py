"""Serving-capacity planner CLI — replay, rank, and calibrate fleet
configs (the serving sibling of plan_main).

Answer capacity what-ifs from a RECORDED trace (a traced bench_serve /
router run — ``--trace`` accepts the trace dir; service times come
from the run's own ledger/span records):

  python -m dtf_tpu.cli.plan_serve_main --trace /tmp/run_trace \
      --target_rps 40 --slo_p99 2.0            # replicas needed
  python -m dtf_tpu.cli.plan_serve_main --trace /tmp/run_trace \
      --chips 8                                # TP vs replicas split
  python -m dtf_tpu.cli.plan_serve_main --trace /tmp/run_trace \
      --pool_sweep 32,64,128,256               # pool size vs shed rate

or from a SYNTHETIC arrival process (extrapolation beyond recorded
load; service times then come from ``--decode_step_ms`` /
``--prefill_chunk_ms`` or a ``--trace`` given purely as the profile
source):

  python -m dtf_tpu.cli.plan_serve_main --rate 80 --duration 60 \
      --process burst --decode_step_ms 12 --prefill_chunk_ms 9 \
      --chips 16

Calibration (the ci_check stage-11 contract, PR-5 ``--calibrate``
shape): record a LIVE traced engine run, reconstruct the workload and
service profile from that trace alone, replay it through the
simulator, and compare predicted tokens/s and p99 latency against the
measured run — gauges (plan_serve_tokens_ratio, plan_serve_p99_ratio)
land in the obs registry (exported to metric.log with
``--benchmark_log_dir``), and the exit is nonzero outside
``--calibrate_tolerance`` (default 2×):

  python -m dtf_tpu.cli.plan_serve_main --calibrate

``--out FILE`` writes everything the run computed (workload summary,
profile, predictions, what-if answers) as one JSON artifact.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import sys
import tempfile
import time

log = logging.getLogger("dtf_tpu")


def _build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m dtf_tpu.cli.plan_serve_main",
        description="Trace-driven serving-capacity simulator: replay "
                    "recorded or synthetic traffic through an analytic "
                    "fleet model; rank configs; calibrate vs a live run.")
    # workload
    ap.add_argument("--trace", nargs="*", default=[],
                    help="trace dir(s)/file(s) of a recorded serving "
                         "run (workload + service profile source)")
    ap.add_argument("--rate", type=float, default=8.0,
                    help="synthetic arrival rate, req/s")
    ap.add_argument("--duration", type=float, default=30.0,
                    help="synthetic window, seconds")
    ap.add_argument("--process", default="poisson",
                    choices=("poisson", "burst"))
    ap.add_argument("--burst_factor", type=float, default=4.0)
    ap.add_argument("--prompt_tokens", default="8:64",
                    help="synthetic prompt-length range lo:hi")
    ap.add_argument("--decode_tokens", type=int, default=32)
    ap.add_argument("--shared_fraction", type=float, default=0.0)
    ap.add_argument("--shared_groups", type=int, default=2)
    ap.add_argument("--shared_prefix_tokens", type=int, default=128)
    ap.add_argument("--seed", type=int, default=0)
    # service profile (overrides; required when no --trace carries them)
    ap.add_argument("--decode_step_ms", type=float, default=0.0,
                    help="decode-step service time (overrides the "
                         "trace's measured median)")
    ap.add_argument("--prefill_chunk_ms", type=float, default=0.0)
    ap.add_argument("--chunk_tokens", type=int, default=0)
    ap.add_argument("--page_size", type=int, default=16)
    ap.add_argument("--tp_comm_frac", type=float, default=0.15,
                    help="non-scaling fraction of a step under TP "
                         "(Amdahl split; documented default)")
    # fleet base config
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--pool_pages", type=int, default=128,
                    help="usable KV pages per replica at tp=1")
    ap.add_argument("--queue_size", type=int, default=64)
    ap.add_argument("--admission_limit", type=int, default=128)
    ap.add_argument("--deadline_s", type=float, default=120.0)
    ap.add_argument("--replica_inflight", type=int, default=16)
    ap.add_argument("--placement", default="affinity",
                    choices=("affinity", "least_loaded"))
    # what-ifs
    ap.add_argument("--target_rps", type=float, default=0.0,
                    help="with --slo_p99: replicas needed for this rate")
    ap.add_argument("--slo_p99", type=float, default=0.0,
                    help="p99 latency SLO, seconds")
    ap.add_argument("--max_replicas", type=int, default=64)
    ap.add_argument("--chips", type=int, default=0,
                    help="rank tp × replicas splits of this chip budget")
    ap.add_argument("--pool_sweep", default="",
                    help="comma-separated usable pool sizes to sweep "
                         "against shed rate")
    ap.add_argument("--loss_bar", type=float, default=0.01,
                    help="max shed+deadline fraction a config may lose")
    ap.add_argument("--chip_cost_per_hour", type=float, default=0.0,
                    help="with --chips and --slo_p99: rank the tp × "
                         "replicas splits by $/Mtoken AT the SLO "
                         "(fleet rate = chips × this, throughput from "
                         "the simulator); 0 = off")
    ap.add_argument("--pool_split", action="store_true",
                    help="with --chips: rank prefill:decode replica "
                         "splits against colocated — the decode pool "
                         "pays KV-page migration over the wire "
                         "instead of prefill (see --migrate_*)")
    ap.add_argument("--migrate_page_bytes", type=int, default=1 << 20,
                    help="wire bytes per migrated KV page")
    ap.add_argument("--migrate_wire_gbps", type=float, default=10.0,
                    help="fabric bandwidth for KV-page migration, "
                         "decimal Gbit/s")
    ap.add_argument("--migrate_latency_ms", type=float, default=2.0,
                    help="per-window round-trip latency of the "
                         "page_fetch migration protocol, ms")
    # calibration
    ap.add_argument("--calibrate", action="store_true",
                    help="record a live traced engine run, replay it, "
                         "compare predicted vs measured (nonzero exit "
                         "outside the tolerance)")
    ap.add_argument("--measure_tp_comm", action="store_true",
                    help="measure tp_comm_frac from two live traced "
                         "runs (tp=1 vs tp=2 over virtual host "
                         "devices) instead of trusting the "
                         "--tp_comm_frac default; exports the "
                         "plan_serve_tp_comm_frac gauge and feeds the "
                         "measured value to every what-if in this run")
    ap.add_argument("--calibrate_tolerance", type=float, default=2.0)
    ap.add_argument("--calibrate_requests", type=int, default=12)
    ap.add_argument("--calibrate_budget", type=int, default=24,
                    help="max_new_tokens per calibration request")
    ap.add_argument("--model", default="transformer_small",
                    help="calibration model (registry name)")
    ap.add_argument("--seq", type=int, default=128,
                    help="calibration engine max_seq_len")
    ap.add_argument("--calibrate_slots", type=int, default=4)
    ap.add_argument("--benchmark_log_dir", default="",
                    help="export the calibration gauges to metric.log "
                         "here (BenchmarkFileLogger.log_registry)")
    ap.add_argument("--out", default="",
                    help="write the full result artifact (JSON)")
    return ap


def _profile_overrides(args) -> dict:
    over = {"page_size": int(args.page_size),
            "tp_comm_frac": float(args.tp_comm_frac)}
    if args.decode_step_ms > 0:
        over["decode_step_s"] = args.decode_step_ms / 1e3
    if args.prefill_chunk_ms > 0:
        over["prefill_chunk_s"] = args.prefill_chunk_ms / 1e3
    if args.chunk_tokens > 0:
        over["chunk_tokens"] = int(args.chunk_tokens)
    return over


def _fleet_config(args):
    from dtf_tpu.plan.serve_model import FleetConfig
    return FleetConfig(
        replicas=args.replicas, tp=args.tp, slots=args.slots,
        pool_pages=args.pool_pages, queue_size=args.queue_size,
        admission_limit=args.admission_limit, deadline_s=args.deadline_s,
        replica_inflight=args.replica_inflight, placement=args.placement)


def _fmt_pred(pred) -> str:
    return (f"{pred.tokens_per_s:8.1f} tok/s  "
            f"p50 {pred.latency_p50_s * 1e3:7.1f} ms  "
            f"p99 {pred.latency_p99_s * 1e3:7.1f} ms  "
            f"loss {pred.loss_rate:5.1%}  "
            f"util {pred.replica_utilization:5.1%}")


def _whatifs(args, workload, profile, base, artifact) -> None:
    """The three documented capacity questions, each gated on its own
    flags; results printed and folded into the artifact."""
    from dtf_tpu.plan import serve_model as sm

    if args.target_rps > 0 and args.slo_p99 > 0:
        n, evaluated = sm.replicas_for(
            workload, profile, base, args.target_rps, args.slo_p99,
            max_replicas=args.max_replicas, loss_bar=args.loss_bar)
        print(f"\nwhat-if: replicas for {args.target_rps:g} req/s at "
              f"p99 <= {args.slo_p99:g}s (loss <= {args.loss_bar:.0%})")
        for r, pred in evaluated:
            mark = " <-- first to meet the SLO" if r == n else ""
            print(f"  {r:>3} replica(s): {_fmt_pred(pred)}{mark}")
        if n is None:
            print(f"  NO config up to {args.max_replicas} replicas "
                  f"meets the SLO — the workload needs a different "
                  f"lever (TP, pool, chunking)")
        artifact["replicas_for"] = {
            "target_rps": args.target_rps, "slo_p99_s": args.slo_p99,
            "answer": n,
            "evaluated": [{"replicas": r, **p.to_dict()}
                          for r, p in evaluated]}

    tp_ranked = None
    if args.chips > 0:
        ranked = sm.rank_tp_vs_replicas(workload, profile, base,
                                        args.chips,
                                        loss_bar=args.loss_bar)
        tp_ranked = ranked
        print(f"\nwhat-if: tp × replicas at {args.chips} chips")
        for i, (cfg, pred) in enumerate(ranked, start=1):
            print(f"  #{i} {cfg.describe():<40} {_fmt_pred(pred)}")
        artifact["tp_vs_replicas"] = {
            "chips": args.chips,
            "ranked": [{"config": c.to_dict(), **p.to_dict()}
                       for c, p in ranked]}

    if args.chip_cost_per_hour > 0:
        if not (args.chips > 0 and args.slo_p99 > 0):
            raise SystemExit(
                "--chip_cost_per_hour needs --chips (the budget to "
                "split) and --slo_p99 (the SLO the $/token ranking "
                "holds configs to)")
        # reuse the tp × replicas simulations above — same splits,
        # no second trace replay
        rows = sm.rank_cost_per_token(
            workload, profile, base, args.chips,
            args.chip_cost_per_hour, args.slo_p99,
            loss_bar=args.loss_bar, evaluated=tp_ranked)
        print(f"\nwhat-if: $/Mtoken at {args.chips} chips × "
              f"${args.chip_cost_per_hour:g}/chip-hr, p99 <= "
              f"{args.slo_p99:g}s")
        for i, row in enumerate(rows, start=1):
            verdict = ("ok" if row.meets_slo else "MISSES SLO")
            cost = ("inf" if row.usd_per_mtoken == float("inf")
                    else f"{row.usd_per_mtoken:8.2f}")
            print(f"  #{i} {row.config.describe():<40} "
                  f"${cost}/Mtok  {_fmt_pred(row.prediction)}  "
                  f"[{verdict}]")
        artifact["cost_per_token"] = {
            "chips": args.chips,
            "chip_cost_per_hour": args.chip_cost_per_hour,
            "slo_p99_s": args.slo_p99,
            "ranked": [r.to_dict() for r in rows]}

    if args.pool_split:
        if not args.chips > 0:
            raise SystemExit("--pool_split needs --chips (the budget "
                             "the prefill:decode split carves up)")
        best, rows = sm.pool_split(
            workload, profile, base, args.chips,
            page_bytes=args.migrate_page_bytes,
            wire_gbps=args.migrate_wire_gbps,
            wire_latency_s=args.migrate_latency_ms / 1e3,
            loss_bar=args.loss_bar)
        print(f"\nwhat-if: prefill:decode split at {args.chips} chips "
              f"(page {args.migrate_page_bytes}B over "
              f"{args.migrate_wire_gbps:g} Gbit/s + "
              f"{args.migrate_latency_ms:g} ms/window)")
        for row in rows:
            mark = ""
            if best is not None and row is best:
                mark = " <-- best split (beats colocated p99)"
            pre = ("" if row.prefill is None
                   else f"  [prefill pool: {_fmt_pred(row.prefill)}]")
            print(f"  {row.describe():<24} {_fmt_pred(row.decode)}"
                  f"{mark}{pre}")
        if best is None:
            print("  colocated wins at this budget — migration wire "
                  "cost eats the split's head-of-line win")
        artifact["pool_split"] = {
            "chips": args.chips,
            "page_bytes": args.migrate_page_bytes,
            "wire_gbps": args.migrate_wire_gbps,
            "wire_latency_s": args.migrate_latency_ms / 1e3,
            "answer": (best.to_dict() if best is not None else None),
            "rows": [r.to_dict() for r in rows]}

    if args.pool_sweep:
        sizes = [int(s) for s in args.pool_sweep.split(",") if s.strip()]
        best, rows = sm.pool_vs_shed(workload, profile, base, sizes,
                                     loss_bar=args.loss_bar)
        print(f"\nwhat-if: page-pool size vs shed rate "
              f"(loss bar {args.loss_bar:.0%})")
        for pages, pred in rows:
            mark = " <-- smallest under the bar" if pages == best else ""
            print(f"  {pages:>6} pages: {_fmt_pred(pred)}{mark}")
        if best is None:
            print("  NO swept pool size stays under the loss bar")
        artifact["pool_vs_shed"] = {
            "sizes": sizes, "answer": best,
            "rows": [{"pool_pages": pg, **p.to_dict()}
                     for pg, p in rows]}


# ---------------------------------------------------------------------------
# calibration: record a live run, replay it, compare
# ---------------------------------------------------------------------------

def _record_calibration_run(args, trace_dir: str, *, tp: int = 1
                            ) -> dict:
    """A short traced in-process engine run — the measured side of the
    calibration.  Returns the engine geometry the simulator must
    mirror.  Prompts are sized to ONE chunk shape so warmup compiles
    every executable the measured burst runs.  ``tp`` > 1 runs the
    same burst tensor-parallel over virtual host devices (the
    --measure_tp_comm pair)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dtf_tpu.models import build_model
    from dtf_tpu.obs import trace
    from dtf_tpu.serve import ServeEngine

    ps = int(args.page_size)
    chunk = int(args.chunk_tokens) if args.chunk_tokens > 0 else 4 * ps
    slots = int(args.calibrate_slots)
    budget = int(args.calibrate_budget)
    # pool sized to the contiguous-equivalent reservation: calibration
    # measures the MODEL, not page starvation (pool what-ifs are the
    # simulator's job once calibrated)
    pool_usable = slots * (-(-int(args.seq) // ps))
    trace.configure(trace_dir, rank=0)
    model, _ = build_model(args.model, dtype=jnp.bfloat16)
    params = jax.jit(model.init)(
        jax.random.key(0), jnp.zeros((1, args.seq), jnp.int32))["params"]
    mesh = None
    if tp > 1:
        from dtf_tpu.serve import serving_mesh
        mesh = serving_mesh(tp)
    eng = ServeEngine(model, params, max_batch=slots,
                      max_seq_len=int(args.seq), max_delay_s=0.0,
                      queue_size=max(64, 4 * args.calibrate_requests),
                      kv_page_size=ps, kv_pool_pages=pool_usable + 1,
                      prefill_chunk=chunk, mesh=mesh)
    rng = np.random.default_rng(args.seed)

    def prompt():
        return rng.integers(0, model.vocab_size,
                            (int(rng.integers(4, ps + 1)),)).astype(
            np.int32)

    # warmup: compile the (single) prefill-chunk shape + decode step —
    # the parsed workload drops these two requests below
    warm = [eng.submit(prompt(), max_new_tokens=2) for _ in range(2)]
    for h in warm:
        h.result(timeout=600)
    # measured burst: half up front, the rest trickling in — queueing
    # AND steady-state decode both appear in the record
    handles = []
    n = int(args.calibrate_requests)
    for i in range(n):
        handles.append(eng.submit(prompt(), max_new_tokens=budget))
        if i >= n // 2:
            time.sleep(0.05)
    for h in handles:
        h.result(timeout=600)
    eng.stop()          # flushes the ledger summary into the trace
    trace.flush()
    trace.disable()     # close the file so the parser reads it all
    return {"slots": slots, "pool_usable": pool_usable, "page_size": ps,
            "chunk_tokens": chunk, "queue_size": max(
                64, 4 * args.calibrate_requests),
            "warmup_requests": 2}


def _ensure_host_devices(n: int) -> None:
    """The tp=2 measurement run needs >= 2 devices; on a CPU box they
    are virtual (XLA's host platform device count).  The flag is read
    at BACKEND INIT (first device query), not at jax import — so
    setting it here still works even though the package already
    imported jax.  If the backend initialized earlier with fewer
    devices, serving_mesh raises its own loud error below."""
    import os
    cur = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in cur:
        return
    os.environ["XLA_FLAGS"] = (
        cur + f" --xla_force_host_platform_device_count={n}").strip()


def _measure_tp_comm(args, artifact) -> float:
    """Satellite of the Amdahl TP model: measure ``tp_comm_frac``
    instead of trusting the documented 0.15 default.  Two identical
    traced bursts — tp=1 and tp=2 — give two median decode-step times;
    the Amdahl split solves for the non-scaling fraction
    (:func:`~dtf_tpu.plan.serve_model.measured_tp_comm_frac`).  The
    result lands in the ``plan_serve_tp_comm_frac`` gauge and replaces
    ``--tp_comm_frac`` for every what-if in this run."""
    import dtf_tpu.plan.serve_model as sm
    from dtf_tpu.cli.trace_main import discover, merge_records
    from dtf_tpu.obs.registry import default_registry, percentile

    medians = {}
    for tp in (1, 2):
        with tempfile.TemporaryDirectory(
                prefix=f"dtf_tpcomm{tp}_") as tmp:
            _record_calibration_run(args, tmp, tp=tp)
            merged = merge_records(discover([tmp]))
        durs = sorted(float(r.get("dur_s", 0.0)) for r in merged
                      if r.get("kind") == "span"
                      and r.get("name") == "serve_decode")
        # drop the compile-tainted head the same way from_records
        # does: medians, not means
        if not durs:
            raise SystemExit(f"measure_tp_comm: the tp={tp} run traced "
                             f"no serve_decode spans — nothing to "
                             f"solve the Amdahl split from")
        medians[tp] = percentile(durs, 50.0)
    frac = sm.measured_tp_comm_frac(medians[1], medians[2])
    default_registry().gauge("plan_serve_tp_comm_frac").set(frac)
    print(f"measured tp_comm_frac: {frac:.3f}  (decode step "
          f"{medians[1] * 1e3:.2f} ms @ tp=1 -> "
          f"{medians[2] * 1e3:.2f} ms @ tp=2; "
          f"--tp_comm_frac {args.tp_comm_frac:g} overridden)")
    artifact["tp_comm_measurement"] = {
        "decode_step_s_tp1": medians[1],
        "decode_step_s_tp2": medians[2],
        "tp_comm_frac": frac,
        "default_overridden": float(args.tp_comm_frac)}
    return frac


def _calibrate(args, artifact) -> int:
    import dtf_tpu.plan.serve_model as sm
    from dtf_tpu.obs.registry import default_registry
    from dtf_tpu.plan.serve_trace import (Workload, measured_stats,
                                          parse_workload)

    with tempfile.TemporaryDirectory(prefix="dtf_plan_serve_") as tmp:
        geom = _record_calibration_run(args, tmp)
        workload = parse_workload([tmp])
        from dtf_tpu.cli.trace_main import discover, merge_records
        merged = merge_records(discover([tmp]))
    # drop the warmup requests (their latency is XLA compile, not
    # serving) and rebase the window to the measured burst
    reqs = workload.requests[geom["warmup_requests"]:]
    if not reqs:
        print("calibrate: the recorded run produced no measurable "
              "requests", file=sys.stderr)
        return 1
    t0 = min(r.arrival_s for r in reqs)
    reqs = [dataclasses.replace(r, arrival_s=r.arrival_s - t0)
            for r in reqs]
    workload = Workload(
        reqs, max(r.arrival_s + r.latency_s for r in reqs) + 1e-9,
        workload.source, workload.skipped_no_trace)

    profile = sm.ServeProfile.from_records(
        merged, page_size=geom["page_size"],
        chunk_tokens=geom["chunk_tokens"],
        tp_comm_frac=float(args.tp_comm_frac))
    config = sm.FleetConfig(
        replicas=1, tp=1, slots=geom["slots"],
        pool_pages=geom["pool_usable"], queue_size=geom["queue_size"],
        admission_limit=max(128, 4 * len(reqs)),
        deadline_s=600.0, replica_inflight=max(64, 4 * len(reqs)),
        placement="least_loaded")
    measured = measured_stats(workload)
    pred = sm.simulate(workload, profile, config)
    ratios = sm.calibration_ratios(measured, pred)

    print(f"calibration ({len(reqs)} measured requests, decode step "
          f"{profile.decode_step_s * 1e3:.2f} ms, chunk "
          f"{profile.prefill_chunk_s * 1e3:.2f} ms):")
    print(f"  tokens/s: predicted {pred.tokens_per_s:.1f}, measured "
          f"{measured['tokens_per_s']:.1f}  "
          f"(ratio {ratios['tokens_ratio']:.2f})")
    print(f"  p99 latency: predicted {pred.latency_p99_s * 1e3:.1f} ms, "
          f"measured {measured['latency_p99_s'] * 1e3:.1f} ms  "
          f"(ratio {ratios['p99_ratio']:.2f})")
    artifact["calibration"] = {
        "profile": profile.to_dict(), "config": config.to_dict(),
        "measured": measured, "predicted": pred.to_dict(),
        "ratios": ratios, "tolerance": args.calibrate_tolerance}
    if args.benchmark_log_dir:
        from dtf_tpu.utils.benchmark_logger import BenchmarkFileLogger
        blog = BenchmarkFileLogger(args.benchmark_log_dir)
        blog.log_registry(default_registry())
        print(f"  registry exported to "
              f"{args.benchmark_log_dir}/metric.log")
    if not sm.ratios_within(ratios, args.calibrate_tolerance):
        tol = args.calibrate_tolerance
        print(f"calibrate: ratio(s) outside [{1 / tol:.2f}, {tol:.2f}] "
              f"— the fleet model is off for this workload/box "
              f"({ratios})", file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s")
    args = _build_parser().parse_args(argv)
    artifact: dict = {"argv": list(sys.argv[1:] if argv is None
                                   else argv)}
    rc = 0
    if args.measure_tp_comm:
        _ensure_host_devices(2)
        args.tp_comm_frac = _measure_tp_comm(args, artifact)
    if args.calibrate:
        rc = _calibrate(args, artifact)
    else:
        from dtf_tpu.plan import serve_model as sm
        from dtf_tpu.plan.serve_trace import (measured_stats,
                                              parse_workload,
                                              synthetic_workload)
        overrides = _profile_overrides(args)
        if args.trace:
            try:
                workload = parse_workload(args.trace)
            except FileNotFoundError as e:
                print(f"plan_serve: {e}", file=sys.stderr)
                return 2
            from dtf_tpu.cli.trace_main import discover, merge_records
            merged = merge_records(discover(list(args.trace)))
            try:
                profile = sm.ServeProfile.from_records(merged,
                                                       **overrides)
            except ValueError as e:
                print(f"plan_serve: {e}", file=sys.stderr)
                return 2
            if not workload.requests:
                print(f"plan_serve: no per-request records under "
                      f"{args.trace} (need a traced serving run)",
                      file=sys.stderr)
                return 2
            artifact["measured"] = measured_stats(workload)
        else:
            lo, _, hi = args.prompt_tokens.partition(":")
            try:
                workload = synthetic_workload(
                    rate_rps=args.rate, duration_s=args.duration,
                    seed=args.seed, process=args.process,
                    burst_factor=args.burst_factor,
                    prompt_tokens=(int(lo), int(hi or lo)),
                    decode_tokens=args.decode_tokens,
                    shared_fraction=args.shared_fraction,
                    shared_groups=args.shared_groups,
                    shared_prefix_tokens=args.shared_prefix_tokens)
                profile = sm.ServeProfile(**overrides)
            except (TypeError, ValueError) as e:
                print(f"plan_serve: {e} (synthetic workloads need "
                      f"--decode_step_ms and --prefill_chunk_ms, or a "
                      f"--trace to profile from)", file=sys.stderr)
                return 2
        base = _fleet_config(args)
        print(f"workload: {workload.summary()}")
        print(f"profile: decode step "
              f"{profile.decode_step_s * 1e3:.2f} ms, chunk "
              f"{profile.prefill_chunk_s * 1e3:.2f} ms × "
              f"{profile.chunk_tokens} tok, page {profile.page_size}")
        baseline = sm.simulate(workload, profile, base)
        print(f"baseline {base.describe()}: {_fmt_pred(baseline)}")
        artifact.update(workload=workload.summary(),
                        profile=profile.to_dict(),
                        base_config=base.to_dict(),
                        baseline=baseline.to_dict())
        _whatifs(args, workload, profile, base, artifact)

    if args.out:
        with open(args.out, "w") as f:
            json.dump(artifact, f, indent=1, default=str)
            f.write("\n")
        print(f"artifact written to {args.out}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
