"""Trace summarizer — turn per-rank JSONL traces into a step report.

Reads the ``trace_rank*.jsonl`` files a traced run wrote (train, PS,
or serve — any subsystem emitting through dtf_tpu.obs.trace), and
prints per-span-name timing aggregates (count, total, mean, p50/p99,
max), event counts, and every anomaly record.

Usage:
  python -m dtf_tpu.cli.trace_main <trace_dir | trace.jsonl> [...]
      [--check] [--allow <kind>]... [--json] [--merge]
      [--request <trace_id>] [--ledger]

``--request <trace_id>`` reconstructs ONE request's (or one run's)
cross-process timeline: every record whose ``trace`` is the id — or
whose batch-span ``traces`` list contains it — from every rank and
named stream, time-ordered with relative offsets.  The view that
answers "where did request X spend its time?": router queue wait →
dispatch → replica prefill chunks → decode steps → failover
re-dispatch → stream delivery → completion, each line rank-tagged.
Composes with ``--merge`` (emit the filtered records as raw JSONL
instead of the rendered timeline).  Exits 2 when the id appears in no
record.

``--ledger`` renders the MFU/cost ledger (obs/ledger.py) from the
trace stream's ``ledger_exec``/``ledger_summary`` events: one row per
(rank, executable) with XLA FLOPs/bytes, measured mean wall time,
achieved TFLOP/s, MFU, and HBM-bandwidth fraction.  With ``--json``
the same rows come out as one JSON object (``{"ledger": [...]}``) —
the machine-readable join the capacity simulator's calibration
(``plan_serve_main``) consumes instead of scraping the table.

``--merge`` emits ONE time-ordered cross-rank stream (JSONL on stdout)
instead of the aggregate table: every record from every
``trace_rank{N}.jsonl`` — and every NAMED stream like the serving
router's ``trace_router.jsonl`` — sorted by timestamp, rank-tagged
(named streams tag their name).  The view that answers "what was rank
2 doing when rank 0 stalled?" and "what did the router see when
replica 1 died?".  Spans sort by their START time (``ts``), so a long
span appears where it began, interleaved with what ran under it.
Composes with ``--check``.

``--check`` is the CI/bench contract: exit 0 only when the trace
contains NO anomaly records (nan_loss, step_time_regression, ...), so a
bench script can assert a run was clean with one command.

``--allow <kind>`` (repeatable) declares EXPECTED anomalies: a chaos
run asserts "the injected fault fired and nothing else broke" with
``--check --allow injected_fault``.  Allowed kinds are still printed
(flagged ALLOWED) but no longer fail the check; every anomaly of any
other kind still does.

``--json`` emits the summary as one JSON object instead of the table
(machine consumers).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from collections import Counter as CCounter
from typing import Dict, List

from dtf_tpu.obs.registry import Histogram
from dtf_tpu.obs.trace import read_records
# the trace vocabulary is single-sourced in obs/vocab.py: this CLI's
# --allow typo check and the dtflint closure rule (trace-unregistered /
# trace-unemitted) validate against ONE registry.  Re-exported here for
# callers that historically imported the tuples from trace_main.
from dtf_tpu.obs.vocab import (KNOWN_ANOMALY_KINDS,  # noqa: F401
                               KNOWN_EVENT_KINDS, allowable_kinds)


def discover(paths: List[str]) -> List[str]:
    """Expand directories to their trace files: per-rank
    ``trace_rank*.jsonl`` plus named streams (``trace_router*.jsonl``,
    the serving router's tier)."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            found = sorted(
                glob.glob(os.path.join(p, "trace_rank*.jsonl"))
                + glob.glob(os.path.join(p, "trace_router*.jsonl")))
            if not found:
                raise FileNotFoundError(
                    f"no trace_rank*.jsonl files under {p!r}")
            files.extend(found)
        else:
            files.append(p)
    return files


def _rank_from_path(path: str):
    # the writer's naming contract, not "any digits": a rotated
    # trace_rank2.jsonl.1 or a v4_trace_rank2.jsonl prefix must still
    # resolve rank 2; named streams resolve to their name
    base = os.path.basename(path)
    m = re.search(r"trace_rank(\d+)", base)
    if m:
        return int(m.group(1))
    m = re.search(r"trace_([A-Za-z]\w*)", base)
    return m.group(1) if m else 0


def merge_records(files: List[str]) -> List[dict]:
    """All records from all per-rank files as one stream, sorted by
    timestamp (ties broken by rank for a stable order).  Every record
    is rank-tagged — the writer stamps ``rank``; records from an older
    trace without it inherit the rank from the filename."""
    merged: List[dict] = []
    for path in files:
        fallback = _rank_from_path(path)
        for rec in read_records(path):
            rec.setdefault("rank", fallback)
            merged.append(rec)
    # ties break by rank-as-string: int ranks and named streams
    # ("router") share one timeline
    merged.sort(key=lambda r: (float(r.get("ts", 0.0)),
                               str(r.get("rank", 0))))
    return merged


def request_records(merged: List[dict], trace_id: str) -> List[dict]:
    """The subset of a merged stream belonging to one trace id —
    records tagged directly (``trace``) or via a batch span's
    ``traces`` list (one decode step serves many requests)."""
    out = []
    for rec in merged:
        if rec.get("trace") == trace_id:
            out.append(rec)
        else:
            traces = rec.get("traces")
            if traces and trace_id in traces:
                out.append(rec)
    return out


#: timeline rendering: drop the plumbing keys, keep the payload
_TIMELINE_HIDE = ("kind", "name", "ts", "rank", "trace", "traces",
                  "dur_s", "span_id", "parent_span", "parent")


def print_request_timeline(trace_id: str, recs: List[dict]) -> None:
    """One request's cross-process life, time-ordered with offsets
    relative to its first record."""
    t0 = min(float(r.get("ts", 0.0)) for r in recs)
    t1 = max(float(r.get("ts", 0.0)) + float(r.get("dur_s", 0.0))
             for r in recs)
    ranks = sorted({str(r.get("rank", "?")) for r in recs})
    print(f"trace {trace_id}: {len(recs)} records across ranks "
          f"{ranks}, {t1 - t0:.3f}s end to end")
    for r in recs:
        rel = float(r.get("ts", 0.0)) - t0
        kind = r.get("kind", "?")
        name = r.get("name", "?")
        dur = (f" ({float(r['dur_s']) * 1e3:.1f}ms)"
               if kind == "span" and "dur_s" in r else "")
        detail = {k: v for k, v in r.items() if k not in _TIMELINE_HIDE}
        tag = "ANOMALY " if kind == "anomaly" else ""
        print(f"  +{rel:8.3f}s [{str(r.get('rank', '?')):>6}] "
              f"{tag}{name}{dur} {detail if detail else ''}")


def ledger_rows(merged: List[dict]) -> List[dict]:
    """The MFU/cost ledger as machine-readable rows from
    ledger_exec/ledger_summary events — latest record per (rank,
    executable) wins (a re-compile or a later summary supersedes).
    One dict per (rank, exec): flops/bytes/count/mean_s/
    achieved_tflops/mfu/hbm_frac (missing fields None).  This is the
    join surface the capacity simulator's calibration reads — the
    human table in :func:`print_ledger` renders the same rows."""
    rows: Dict[tuple, dict] = {}
    for rec in merged:
        if rec.get("name") == "ledger_exec":
            key = (str(rec.get("rank", "?")), rec.get("exec", "?"))
            rows.setdefault(key, {}).update(
                flops=rec.get("flops"), bytes=rec.get("bytes"))
        elif rec.get("name") == "ledger_summary":
            key = (str(rec.get("rank", "?")), rec.get("exec", "?"))
            rows.setdefault(key, {}).update(
                count=rec.get("count"), mean_s=rec.get("mean_s"),
                achieved_tflops=rec.get("achieved_tflops"),
                mfu=rec.get("mfu"), hbm_frac=rec.get("hbm_frac"))
    return [{"rank": rank, "exec": name, **r}
            for (rank, name), r in sorted(rows.items())]


def print_ledger(merged: List[dict]) -> bool:
    """Render :func:`ledger_rows` as the human table.  Returns False
    when the stream carries no ledger records at all."""
    rows = ledger_rows(merged)
    if not rows:
        return False

    def fmt(v, spec):
        return format(v, spec) if isinstance(v, (int, float)) else "-"

    hdr = (f"{'rank':<7}{'executable':<28}{'gflops':>9}{'calls':>7}"
           f"{'mean_ms':>9}{'tflop/s':>9}{'mfu':>7}{'hbm':>7}")
    print(hdr)
    print("-" * len(hdr))
    for r in rows:
        print(f"{r['rank']:<7}{r['exec']:<28}"
              f"{fmt((r.get('flops') or 0) / 1e9, '9.1f'):>9}"
              f"{fmt(r.get('count'), 'd'):>7}"
              f"{fmt((r.get('mean_s') or 0) * 1e3, '9.2f'):>9}"
              f"{fmt(r.get('achieved_tflops'), '.2f'):>9}"
              f"{fmt(r.get('mfu'), '.3f'):>7}"
              f"{fmt(r.get('hbm_frac'), '.3f'):>7}")
    return True


def summarize(files: List[str]) -> dict:
    spans: Dict[str, Histogram] = {}
    events: CCounter = CCounter()
    anomalies: List[dict] = []
    ranks = set()
    steps = set()
    profiler_traces: List[str] = []
    for path in files:
        for rec in read_records(path):
            ranks.add(rec.get("rank", 0))
            kind = rec.get("kind")
            if kind == "span":
                name = rec.get("name", "?")
                h = spans.get(name)
                if h is None:
                    h = spans[name] = Histogram(name, unit="s")
                h.observe(float(rec.get("dur_s", 0.0)))
                if name == "step" and "step" in rec:
                    steps.add((rec.get("rank", 0), rec["step"]))
            elif kind == "event":
                events[rec.get("name", "?")] += 1
                if rec.get("name") == "profiler_trace":
                    # --profile_steps dumped an XLA trace: surface where
                    path_ = str(rec.get("path", ""))
                    if path_ and path_ not in profiler_traces:
                        profiler_traces.append(path_)
            elif kind == "anomaly":
                anomalies.append(rec)
    span_rows = {}
    for name, h in sorted(spans.items()):
        s = h.snapshot()
        span_rows[name] = {
            "count": s["count"], "total_s": s["count"] * s["mean"],
            "mean_s": s["mean"], "p50_s": s["p50"], "p99_s": s["p99"],
            "max_s": s["max"],
        }
    return {
        "files": files,
        "ranks": sorted(ranks, key=str),
        "step_spans": len(steps) if steps else (
            span_rows.get("step", {}).get("count", 0)),
        "spans": span_rows,
        "events": dict(sorted(events.items())),
        "anomalies": anomalies,
        "profiler_traces": profiler_traces,
    }


def print_summary(summary: dict, allowed=()) -> None:
    allowed = set(allowed)
    print(f"trace files: {len(summary['files'])}  "
          f"ranks: {summary['ranks']}  "
          f"step spans: {summary['step_spans']}")
    if summary["spans"]:
        hdr = (f"{'span':<24}{'count':>8}{'total_s':>10}{'mean_s':>10}"
               f"{'p50_s':>10}{'p99_s':>10}{'max_s':>10}")
        print(hdr)
        print("-" * len(hdr))
        for name, r in summary["spans"].items():
            print(f"{name:<24}{r['count']:>8}{r['total_s']:>10.3f}"
                  f"{r['mean_s']:>10.4f}{r['p50_s']:>10.4f}"
                  f"{r['p99_s']:>10.4f}{r['max_s']:>10.4f}")
    if summary["events"]:
        print("events: " + ", ".join(f"{k}×{v}"
                                     for k, v in summary["events"].items()))
    for path in summary.get("profiler_traces", ()):
        print(f"profiler trace: {path}")
    for a in summary["anomalies"]:
        detail = {k: v for k, v in a.items()
                  if k not in ("kind", "name", "ts")}
        tag = ("ALLOWED ANOMALY" if a.get("name") in allowed
               else "ANOMALY")
        print(f"{tag}: {a.get('name', '?')} {detail}")
    if not summary["anomalies"]:
        print("anomalies: none")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dtf_tpu.cli.trace_main",
        description="Summarize dtf_tpu JSONL traces.")
    ap.add_argument("paths", nargs="+",
                    help="trace dir(s) or trace_rank*.jsonl file(s)")
    ap.add_argument("--check", action="store_true",
                    help="exit nonzero when any anomaly record is present")
    ap.add_argument("--allow", action="append", default=[], metavar="KIND",
                    help="anomaly kind --check tolerates (repeatable): "
                         "chaos runs pass --allow injected_fault to "
                         "assert 'only the injected fault'")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as JSON instead of a table")
    ap.add_argument("--merge", action="store_true",
                    help="emit one time-ordered cross-rank JSONL stream "
                         "(rank-tagged records) instead of the summary")
    ap.add_argument("--request", default="", metavar="TRACE_ID",
                    help="reconstruct one trace id's cross-process "
                         "timeline (with --merge: emit its records as "
                         "raw JSONL); exits 2 when the id is unknown")
    ap.add_argument("--ledger", action="store_true",
                    help="render the MFU/cost ledger table from the "
                         "stream's ledger_exec/ledger_summary events")
    args = ap.parse_args(argv)

    files = discover(args.paths)
    allowed = set(args.allow)
    for kind in sorted(allowed - allowable_kinds()):
        # warn, don't fail: new subsystems may emit kinds this registry
        # hasn't learned — but a typo'd --allow silently tolerating
        # nothing is exactly the bug an expected-anomaly list invites
        print(f"warning: --allow {kind!r} is not a known anomaly kind "
              f"(known: {', '.join(KNOWN_ANOMALY_KINDS)})",
              file=sys.stderr)
    if args.request:
        merged = merge_records(files)
        recs = request_records(merged, args.request)
        if not recs:
            print(f"trace id {args.request!r} appears in no record "
                  f"under {args.paths}", file=sys.stderr)
            return 2
        if args.merge:
            for rec in recs:
                print(json.dumps(rec, default=str))
        else:
            print_request_timeline(args.request, recs)
        # --check still scans the WHOLE stream: a clean request inside
        # a dirty run is not a clean run
        anomalies = [r for r in merged if r.get("kind") == "anomaly"]
    elif args.ledger:
        merged = merge_records(files)
        if args.json:
            # machine-readable join surface (the capacity simulator's
            # calibration consumes this instead of scraping the table)
            rows = ledger_rows(merged)
            if not rows:
                print("no ledger records in this trace", file=sys.stderr)
                return 2
            print(json.dumps({"ledger": rows}, indent=2, default=str))
        elif not print_ledger(merged):
            print("no ledger records in this trace (ledger_exec/"
                  "ledger_summary events are emitted by instrumented "
                  "train/serve runs)", file=sys.stderr)
            return 2
        anomalies = [r for r in merged if r.get("kind") == "anomaly"]
    elif args.merge:
        # one pass over the files: the merged stream also feeds the
        # --check anomaly scan (no summarize — the aggregate view is
        # never printed in merge mode)
        merged = merge_records(files)
        for rec in merged:
            print(json.dumps(rec, default=str))
        anomalies = [r for r in merged if r.get("kind") == "anomaly"]
    else:
        summary = summarize(files)
        if args.json:
            print(json.dumps(summary, indent=2, default=str))
        else:
            print_summary(summary, allowed=allowed)
        anomalies = summary["anomalies"]
    if args.check:
        blocked = [a for a in anomalies
                   if a.get("name") not in allowed]
        if blocked:
            tolerated = len(anomalies) - len(blocked)
            print(f"--check: {len(blocked)} anomaly record(s)"
                  + (f" ({tolerated} allowed)" if tolerated else "")
                  + " — run was NOT clean", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
