"""Trace summarizer — turn per-rank JSONL traces into a step report.

Reads the ``trace_rank*.jsonl`` files a traced run wrote (train, PS,
or serve — any subsystem emitting through dtf_tpu.obs.trace), and
prints per-span-name timing aggregates (count, total, mean, p50/p99,
max), event counts, and every anomaly record.

Usage:
  python -m dtf_tpu.cli.trace_main <trace_dir | trace.jsonl> [...]
      [--check] [--allow <kind>]... [--json] [--merge]

``--merge`` emits ONE time-ordered cross-rank stream (JSONL on stdout)
instead of the aggregate table: every record from every
``trace_rank{N}.jsonl`` — and every NAMED stream like the serving
router's ``trace_router.jsonl`` — sorted by timestamp, rank-tagged
(named streams tag their name).  The view that answers "what was rank
2 doing when rank 0 stalled?" and "what did the router see when
replica 1 died?".  Spans sort by their START time (``ts``), so a long
span appears where it began, interleaved with what ran under it.
Composes with ``--check``.

``--check`` is the CI/bench contract: exit 0 only when the trace
contains NO anomaly records (nan_loss, step_time_regression, ...), so a
bench script can assert a run was clean with one command.

``--allow <kind>`` (repeatable) declares EXPECTED anomalies: a chaos
run asserts "the injected fault fired and nothing else broke" with
``--check --allow injected_fault``.  Allowed kinds are still printed
(flagged ALLOWED) but no longer fail the check; every anomaly of any
other kind still does.

``--json`` emits the summary as one JSON object instead of the table
(machine consumers).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from collections import Counter as CCounter
from typing import Dict, List

from dtf_tpu.obs.registry import Histogram
from dtf_tpu.obs.trace import read_records


#: anomaly kinds the subsystems emit (docs for --allow; unknown kinds
#: only warn — forward compatibility beats a stale registry)
KNOWN_ANOMALY_KINDS = (
    "nan_loss", "step_time_regression", "reader_lag", "serve_shed",
    "ckpt_integrity", "injected_fault",
    # serving replica tier (dtf_tpu/serve/router.py)
    "router_shed", "replica_lost", "replica_give_up",
    "redispatch_divergence", "router_deadline",
    # raw chaos kinds (the fault_kind attr of injected_fault records;
    # accepted so `--allow replica_kill`-style typos warn, not pass)
    "replica_kill", "net_partition", "slow_replica",
)


def discover(paths: List[str]) -> List[str]:
    """Expand directories to their trace files: per-rank
    ``trace_rank*.jsonl`` plus named streams (``trace_router*.jsonl``,
    the serving router's tier)."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            found = sorted(
                glob.glob(os.path.join(p, "trace_rank*.jsonl"))
                + glob.glob(os.path.join(p, "trace_router*.jsonl")))
            if not found:
                raise FileNotFoundError(
                    f"no trace_rank*.jsonl files under {p!r}")
            files.extend(found)
        else:
            files.append(p)
    return files


def _rank_from_path(path: str):
    # the writer's naming contract, not "any digits": a rotated
    # trace_rank2.jsonl.1 or a v4_trace_rank2.jsonl prefix must still
    # resolve rank 2; named streams resolve to their name
    base = os.path.basename(path)
    m = re.search(r"trace_rank(\d+)", base)
    if m:
        return int(m.group(1))
    m = re.search(r"trace_([A-Za-z]\w*)", base)
    return m.group(1) if m else 0


def merge_records(files: List[str]) -> List[dict]:
    """All records from all per-rank files as one stream, sorted by
    timestamp (ties broken by rank for a stable order).  Every record
    is rank-tagged — the writer stamps ``rank``; records from an older
    trace without it inherit the rank from the filename."""
    merged: List[dict] = []
    for path in files:
        fallback = _rank_from_path(path)
        for rec in read_records(path):
            rec.setdefault("rank", fallback)
            merged.append(rec)
    # ties break by rank-as-string: int ranks and named streams
    # ("router") share one timeline
    merged.sort(key=lambda r: (float(r.get("ts", 0.0)),
                               str(r.get("rank", 0))))
    return merged


def summarize(files: List[str]) -> dict:
    spans: Dict[str, Histogram] = {}
    events: CCounter = CCounter()
    anomalies: List[dict] = []
    ranks = set()
    steps = set()
    for path in files:
        for rec in read_records(path):
            ranks.add(rec.get("rank", 0))
            kind = rec.get("kind")
            if kind == "span":
                name = rec.get("name", "?")
                h = spans.get(name)
                if h is None:
                    h = spans[name] = Histogram(name, unit="s")
                h.observe(float(rec.get("dur_s", 0.0)))
                if name == "step" and "step" in rec:
                    steps.add((rec.get("rank", 0), rec["step"]))
            elif kind == "event":
                events[rec.get("name", "?")] += 1
            elif kind == "anomaly":
                anomalies.append(rec)
    span_rows = {}
    for name, h in sorted(spans.items()):
        s = h.snapshot()
        span_rows[name] = {
            "count": s["count"], "total_s": s["count"] * s["mean"],
            "mean_s": s["mean"], "p50_s": s["p50"], "p99_s": s["p99"],
            "max_s": s["max"],
        }
    return {
        "files": files,
        "ranks": sorted(ranks, key=str),
        "step_spans": len(steps) if steps else (
            span_rows.get("step", {}).get("count", 0)),
        "spans": span_rows,
        "events": dict(sorted(events.items())),
        "anomalies": anomalies,
    }


def print_summary(summary: dict, allowed=()) -> None:
    allowed = set(allowed)
    print(f"trace files: {len(summary['files'])}  "
          f"ranks: {summary['ranks']}  "
          f"step spans: {summary['step_spans']}")
    if summary["spans"]:
        hdr = (f"{'span':<24}{'count':>8}{'total_s':>10}{'mean_s':>10}"
               f"{'p50_s':>10}{'p99_s':>10}{'max_s':>10}")
        print(hdr)
        print("-" * len(hdr))
        for name, r in summary["spans"].items():
            print(f"{name:<24}{r['count']:>8}{r['total_s']:>10.3f}"
                  f"{r['mean_s']:>10.4f}{r['p50_s']:>10.4f}"
                  f"{r['p99_s']:>10.4f}{r['max_s']:>10.4f}")
    if summary["events"]:
        print("events: " + ", ".join(f"{k}×{v}"
                                     for k, v in summary["events"].items()))
    for a in summary["anomalies"]:
        detail = {k: v for k, v in a.items()
                  if k not in ("kind", "name", "ts")}
        tag = ("ALLOWED ANOMALY" if a.get("name") in allowed
               else "ANOMALY")
        print(f"{tag}: {a.get('name', '?')} {detail}")
    if not summary["anomalies"]:
        print("anomalies: none")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dtf_tpu.cli.trace_main",
        description="Summarize dtf_tpu JSONL traces.")
    ap.add_argument("paths", nargs="+",
                    help="trace dir(s) or trace_rank*.jsonl file(s)")
    ap.add_argument("--check", action="store_true",
                    help="exit nonzero when any anomaly record is present")
    ap.add_argument("--allow", action="append", default=[], metavar="KIND",
                    help="anomaly kind --check tolerates (repeatable): "
                         "chaos runs pass --allow injected_fault to "
                         "assert 'only the injected fault'")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as JSON instead of a table")
    ap.add_argument("--merge", action="store_true",
                    help="emit one time-ordered cross-rank JSONL stream "
                         "(rank-tagged records) instead of the summary")
    args = ap.parse_args(argv)

    files = discover(args.paths)
    allowed = set(args.allow)
    for kind in sorted(allowed - set(KNOWN_ANOMALY_KINDS)):
        # warn, don't fail: new subsystems may emit kinds this registry
        # hasn't learned — but a typo'd --allow silently tolerating
        # nothing is exactly the bug an expected-anomaly list invites
        print(f"warning: --allow {kind!r} is not a known anomaly kind "
              f"(known: {', '.join(KNOWN_ANOMALY_KINDS)})",
              file=sys.stderr)
    if args.merge:
        # one pass over the files: the merged stream also feeds the
        # --check anomaly scan (no summarize — the aggregate view is
        # never printed in merge mode)
        merged = merge_records(files)
        for rec in merged:
            print(json.dumps(rec, default=str))
        anomalies = [r for r in merged if r.get("kind") == "anomaly"]
    else:
        summary = summarize(files)
        if args.json:
            print(json.dumps(summary, indent=2, default=str))
        else:
            print_summary(summary, allowed=allowed)
        anomalies = summary["anomalies"]
    if args.check:
        blocked = [a for a in anomalies
                   if a.get("name") not in allowed]
        if blocked:
            tolerated = len(anomalies) - len(blocked)
            print(f"--check: {len(blocked)} anomaly record(s)"
                  + (f" ({tolerated} allowed)" if tolerated else "")
                  + " — run was NOT clean", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
