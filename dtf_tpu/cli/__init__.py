from dtf_tpu.cli.runner import run  # noqa: F401
