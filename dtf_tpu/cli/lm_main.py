"""Language-model entry point — the long-context workload.

No reference counterpart (the reference is vision-only, SURVEY §5.7);
this main exposes the framework's long-context machinery end-to-end:
flash-attention on-chip, ring attention across the 'seq' mesh axis.

Examples:
  # single chip, flash attention:
  python -m dtf_tpu.cli.lm_main --use_synthetic_data --train_steps 3 \
      --batch_size 4 --model transformer_small

  # 8-device mesh, 2-way data x 4-way sequence parallel ring attention:
  python -m dtf_tpu.cli.lm_main --use_synthetic_data --train_steps 3 \
      --batch_size 4 --seq_parallelism 4 --dtype bf16
"""

from __future__ import annotations

import logging
import sys

from dtf_tpu.cli.runner import run
from dtf_tpu.config import parse_flags

LM_DEFAULTS = dict(
    model="transformer",
    dataset="lm",
    train_epochs=1,
    batch_size=8,
    dtype="bf16",
    optimizer="adamw",     # warmup+cosine LM recipe (schedules.lm_schedule)
    skip_eval=True,
)


def main(argv=None) -> dict:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s")
    cfg = parse_flags(argv if argv is not None else sys.argv[1:],
                      defaults=LM_DEFAULTS)
    # --trace_dir / DTF_TRACE_DIR tracing is configured by run() itself
    return run(cfg)


if __name__ == "__main__":
    main()
