"""Multi-process launcher — the one-command replacement for the
reference's deployment story.

The reference needed 16 near-identical per-rank script copies plus an
ssh fan-out loop (`ps_server/run.sh`: ssh root@host python …_ps_$i.py
2>log$i.log &, 1 s stagger) and a pkill teardown (`kill.sh`), because
each rank's TF_CONFIG had to be hardcoded (SURVEY §3.4, §7.9).  Here
per-process identity is env config, so one parameterized command does
it all:

Local fan-out (all processes on this host — multi-chip hosts, or CPU
mesh testing):

    python -m dtf_tpu.cli.launch --num_processes 4 -- \
        python -m dtf_tpu.cli.cifar_main --distribution_strategy \
        multi_worker_mirrored ...

Cluster fan-out (prints — or runs with --execute via ssh — one command
per host; horovodrun -H parity):

    python -m dtf_tpu.cli.launch --hosts h1,h2,h3,h4 -- \
        python -m dtf_tpu.cli.imagenet_main ...

Per-rank stderr/stdout goes to <log_dir>/log{rank}.log (run.sh parity).
On any rank failing, all ranks are torn down (kill.sh parity) and the
launcher exits non-zero.
"""

from __future__ import annotations

import json
import os
import shlex
import signal
import subprocess
import sys
import time
from typing import List, Optional

# Heartbeat-file contract, duplicated from dtf_tpu/obs/watchdog.py ON
# PURPOSE: the supervisor's own logic stays stdlib-only — the process
# that kills and restarts broken ML ranks should not depend on the obs
# package it supervises (the unavoidable cost of `-m dtf_tpu.cli.launch`
# is the package-init shard_map shim's jax import, a fixed ~3 s).
# tests/test_obs.py asserts the two sides agree on the contract.
HEARTBEAT_DIR_ENV = "DTF_HEARTBEAT_DIR"


def heartbeat_path(directory: str, rank: int) -> str:
    return os.path.join(directory, f"heartbeat_rank{rank}.json")


def read_heartbeat(path: str):
    """Parse a heartbeat file; None when missing/torn (treated as 'no
    heartbeat signal', not as death — log growth still counts)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def build_env(rank: int, world: int, coordinator: str,
              devices_per_process: Optional[int] = None,
              heartbeat_dir: Optional[str] = None) -> dict:
    env = dict(os.environ)
    env["DTF_COORDINATOR"] = coordinator
    env["DTF_PROCESS_ID"] = str(rank)
    env["DTF_PROCESS_COUNT"] = str(world)
    if heartbeat_dir:
        # ranks running dtf_tpu mains rewrite
        # <log_dir>/heartbeat_rank{N}.json at a bounded interval
        # (obs/watchdog.Heartbeat) — the supervisor's structured
        # liveness signal, replacing stdout-size scraping
        env[HEARTBEAT_DIR_ENV] = os.path.abspath(heartbeat_dir)
    if devices_per_process:
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            f" --xla_force_host_platform_device_count="
                            f"{devices_per_process}")
    return env


def _run_once(cmd: List[str], num_processes: int, coordinator: str,
              log_dir: str, devices_per_process: Optional[int],
              stagger_s: float = 0.0,
              heartbeat_timeout: Optional[float] = None,
              attempt: int = 0, startup_grace: float = 300.0) -> int:
    os.makedirs(log_dir, exist_ok=True)
    procs = []  # (rank, Popen)
    logs = []
    rc = 0
    # hang watchdog state: last time each rank showed life — via its
    # heartbeat file (structured, preferred) or its log growing
    # (fallback ONLY for ranks that have never emitted a heartbeat: once
    # a rank has beaten, log growth stops counting, so a rank whose log
    # grows from a side thread while its training thread is deadlocked
    # is still caught)
    sizes = [0] * num_processes
    hb_ts = [None] * num_processes   # last heartbeat payload ts seen
    hb_mtime = [None] * num_processes  # stat gate: parse only on change
    last_beat = [0.0] * num_processes
    spawned = [0.0] * num_processes
    # restart attempts keep earlier logs (the first failure is usually
    # the informative one): log0.log, then log0.retry1.log, ...
    suffix = f".retry{attempt}" if attempt else ""
    log_path = lambda rank: os.path.join(log_dir, f"log{rank}{suffix}.log")
    try:
        for rank in range(num_processes):
            # a heartbeat file surviving a previous attempt must not
            # masquerade as this attempt's first beat
            try:
                os.unlink(heartbeat_path(log_dir, rank))
            except OSError:
                pass
            f = open(log_path(rank), "wb")
            logs.append(f)
            p = subprocess.Popen(
                cmd, env=build_env(rank, num_processes, coordinator,
                                   devices_per_process,
                                   heartbeat_dir=log_dir),
                stdout=f, stderr=subprocess.STDOUT)
            procs.append((rank, p))
            last_beat[rank] = spawned[rank] = time.monotonic()
            if stagger_s:
                time.sleep(stagger_s)  # run.sh's 1 s stagger, now optional
        while procs:
            for rank, p in list(procs):
                ret = p.poll()
                if ret is None:
                    if heartbeat_timeout:
                        # liveness: the rank's heartbeat file advanced
                        # (obs/watchdog beats at a bounded interval even
                        # when nothing logs — e.g. mid-epoch with a long
                        # --log_steps); ranks that never beat fall back
                        # to log growth.  Quiet past the timeout means a
                        # hung collective or deadlock — the failure mode
                        # the reference could only resolve by hand with
                        # kill.sh
                        now = time.monotonic()
                        # mtime gate: beats land every heartbeat_secs at
                        # most, so one stat per poll replaces an
                        # open+parse per poll
                        try:
                            mt = os.stat(
                                heartbeat_path(log_dir, rank)).st_mtime
                        except OSError:
                            mt = hb_mtime[rank]
                        if mt != hb_mtime[rank]:
                            hb_mtime[rank] = mt
                            hb = read_heartbeat(
                                heartbeat_path(log_dir, rank))
                            if (hb is not None
                                    and hb.get("ts") != hb_ts[rank]):
                                hb_ts[rank] = hb.get("ts")
                                last_beat[rank] = now
                        try:
                            sz = os.path.getsize(log_path(rank))
                        except OSError:
                            sz = sizes[rank]
                        if sz != sizes[rank]:
                            sizes[rank] = sz
                            # log growth is liveness only until the
                            # first heartbeat: after that, a growing log
                            # with a stale heartbeat is the deadlocked-
                            # but-chatty signature, not life
                            if hb_ts[rank] is None:
                                last_beat[rank] = now
                        if (now - last_beat[rank] > heartbeat_timeout
                                # a rank in first XLA compile /
                                # checkpoint restore legitimately logs
                                # nothing for minutes — give every rank
                                # a startup grace before the heartbeat
                                # rule applies
                                and now - spawned[rank] > startup_grace):
                            print(f"rank {rank} heartbeat lost "
                                  f"({heartbeat_timeout:.0f}s without "
                                  f"{'a heartbeat' if hb_ts[rank] is not None else 'log output'}"
                                  f"); killing", file=sys.stderr)
                            p.kill()
                    continue
                procs.remove((rank, p))
                if ret != 0:
                    if rc == 0:  # keep the FIRST failure's code
                        rc = ret
                    print(f"rank {rank} exited {ret} (see "
                          f"{log_path(rank)}); tearing down",
                          file=sys.stderr)
                    for _, q in procs:  # kill.sh parity
                        q.send_signal(signal.SIGTERM)
            time.sleep(0.2)
    finally:
        for _, q in procs:
            q.kill()
        for f in logs:
            f.close()
    return rc


def launch_local(cmd: List[str], num_processes: int, coordinator: str,
                 log_dir: str, devices_per_process: Optional[int],
                 stagger_s: float = 0.0, max_restarts: int = 0,
                 heartbeat_timeout: Optional[float] = None,
                 startup_grace: float = 300.0) -> int:
    """Run the job, optionally supervising it.

    ``max_restarts``: on any rank failing (or hanging, with
    ``heartbeat_timeout``), tear down and relaunch ALL ranks — the
    sync-SPMD recovery unit is the whole job, with progress carried by
    checkpoints (pair the training command with ``--resume``).  The
    reference's recovery story was manual: per-epoch checkpoints plus
    an operator running kill.sh and re-running run.sh (SURVEY §5.3).
    """
    attempt = 0
    while True:
        rc = _run_once(cmd, num_processes, coordinator, log_dir,
                       devices_per_process, stagger_s, heartbeat_timeout,
                       attempt=attempt, startup_grace=startup_grace)
        if rc == 0 or attempt >= max_restarts:
            return rc
        attempt += 1
        print(f"relaunching all {num_processes} ranks (restart "
              f"{attempt}/{max_restarts})", file=sys.stderr)


def cluster_commands(cmd: List[str], hosts: List[str], coordinator: str,
                     log_dir: str, background: bool = True) -> List[str]:
    """One ssh line per host — the run.sh loop, generated.

    `background` appends `&` for manual copy-paste use; --execute mode
    passes False so ssh blocks until the remote rank exits and its
    status is observable."""
    world = len(hosts)
    quoted = " ".join(shlex.quote(c) for c in cmd)
    lines = []
    for rank, host in enumerate(hosts):
        envs = (f"DTF_COORDINATOR={coordinator} DTF_PROCESS_ID={rank} "
                f"DTF_PROCESS_COUNT={world}")
        logfile = shlex.quote(f"{log_dir}/log{rank}.log")
        remote = (f"mkdir -p {shlex.quote(log_dir)} && {envs} {quoted} "
                  f"> {logfile} 2>&1")
        if background:
            remote += " &"
        lines.append(f"ssh {host} {shlex.quote(remote)}")
    return lines


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--" not in argv:
        print(__doc__)
        return 2
    split = argv.index("--")
    opts, cmd = argv[:split], argv[split + 1:]

    num_processes, coordinator = 1, "localhost:12346"
    hosts: List[str] = []
    log_dir = "./ranklogs"
    devices_per_process: Optional[int] = None
    execute = False
    max_restarts = 0
    heartbeat_timeout: Optional[float] = None
    startup_grace: Optional[float] = None  # None → default 300 (local mode)
    i = 0
    while i < len(opts):
        o = opts[i]
        if o == "--num_processes":
            num_processes = int(opts[i + 1]); i += 2
        elif o == "--coordinator":
            coordinator = opts[i + 1]; i += 2
        elif o == "--hosts":
            hosts = [h.strip() for h in opts[i + 1].split(",") if h.strip()]
            i += 2
        elif o == "--log_dir":
            log_dir = opts[i + 1]; i += 2
        elif o == "--devices_per_process":
            devices_per_process = int(opts[i + 1]); i += 2
        elif o == "--execute":
            execute = True; i += 1
        elif o == "--max_restarts":
            max_restarts = int(opts[i + 1]); i += 2
        elif o == "--heartbeat_timeout":
            heartbeat_timeout = float(opts[i + 1]); i += 2
        elif o == "--startup_grace":
            startup_grace = float(opts[i + 1]); i += 2
        else:
            raise ValueError(f"unknown launcher option {o}")

    if hosts:
        if num_processes != 1 or devices_per_process:
            raise ValueError(
                "--hosts runs one rank per host; --num_processes/"
                "--devices_per_process are not supported with it")
        if max_restarts or heartbeat_timeout or startup_grace is not None:
            raise ValueError(
                "--max_restarts/--heartbeat_timeout/--startup_grace "
                "supervise local fan-out; for --hosts runs, supervise "
                "on each host")
        if coordinator == "localhost:12346":
            coordinator = f"{hosts[0]}:12346"
        lines = cluster_commands(cmd, hosts, coordinator, log_dir,
                                 background=not execute)
        if not execute:
            print("\n".join(lines))
            return 0
        # blocking ssh per rank: failures are observable and propagated
        running = [subprocess.Popen(line, shell=True) for line in lines]
        rc = 0
        for rank, p in enumerate(running):
            ret = p.wait()
            if ret:
                print(f"host rank {rank} exited {ret}", file=sys.stderr)
                if rc == 0:
                    rc = ret
        return rc
    # startup_grace default: 300 s covers first-compile stalls, but an
    # operator who explicitly set a SHORTER --heartbeat_timeout wants
    # hangs caught on that clock from the start — so the unset-grace
    # default follows the explicit timeout downward (never upward: a
    # long steady-state timeout must not weaken startup detection).
    if startup_grace is None:
        startup_grace = (min(heartbeat_timeout, 300.0)
                         if heartbeat_timeout else 300.0)
    return launch_local(cmd, num_processes, coordinator, log_dir,
                        devices_per_process, max_restarts=max_restarts,
                        heartbeat_timeout=heartbeat_timeout,
                        startup_grace=startup_grace)


if __name__ == "__main__":
    sys.exit(main())
