"""Multi-process launcher — the one-command replacement for the
reference's deployment story.

The reference needed 16 near-identical per-rank script copies plus an
ssh fan-out loop (`ps_server/run.sh`: ssh root@host python …_ps_$i.py
2>log$i.log &, 1 s stagger) and a pkill teardown (`kill.sh`), because
each rank's TF_CONFIG had to be hardcoded (SURVEY §3.4, §7.9).  Here
per-process identity is env config, so one parameterized command does
it all:

Local fan-out (all processes on this host — multi-chip hosts, or CPU
mesh testing):

    python -m dtf_tpu.cli.launch --num_processes 4 -- \
        python -m dtf_tpu.cli.cifar_main --distribution_strategy \
        multi_worker_mirrored ...

Cluster fan-out (prints — or runs with --execute via ssh — one command
per host; horovodrun -H parity):

    python -m dtf_tpu.cli.launch --hosts h1,h2,h3,h4 -- \
        python -m dtf_tpu.cli.imagenet_main ...

Per-rank stderr/stdout goes to <log_dir>/log{rank}.log (run.sh parity).
On any rank failing, all ranks are torn down (kill.sh parity) and the
launcher exits non-zero.
"""

from __future__ import annotations

import collections
import json
import os
import shlex
import signal
import subprocess
import sys
import time
from typing import List, Optional

# Heartbeat-file contract, duplicated from dtf_tpu/obs/watchdog.py ON
# PURPOSE: the supervisor's own logic stays stdlib-only — the process
# that kills and restarts broken ML ranks should not depend on the obs
# package it supervises (the unavoidable cost of `-m dtf_tpu.cli.launch`
# is the package-init shard_map shim's jax import, a fixed ~3 s).
# tests/test_obs.py asserts the two sides agree on the contract.
HEARTBEAT_DIR_ENV = "DTF_HEARTBEAT_DIR"

# Exit-code contract with dtf_tpu/train/preemption.py, dtf_tpu/chaos
# and dtf_tpu/train/elastic.py — duplicated here for the same
# stdlib-only reason (parity is pinned by tests/test_chaos.py and
# tests/test_elastic.py).  A rank exiting EXIT_PREEMPTED performed a
# graceful preemption checkpoint: the supervisor restarts it WITHOUT
# consuming the crash-restart budget and without backoff (the work is
# durable; waiting helps nobody).  A rank exiting EXIT_DEVICE_LOST saw
# its accelerators vanish while the host survived: under --elastic the
# supervisor RESHARDS (relaunch on the surviving topology) instead of
# burning the crash budget on a fault no restart-at-size can fix.  Any
# other nonzero exit (including death by signal — negative Popen
# returncodes) is a crash: budgeted, with exponential backoff — except
# an UNPROMPTED SIGKILL (one this supervisor did not send), which is
# the host-loss rank-exit pattern: the OOM-killer or the host going
# away, never a python crash.
EXIT_PREEMPTED = 75
EXIT_DEVICE_LOST = 76

# Env var + rendezvous-file contract with dtf_tpu/train/elastic.py
# (canonical constants live there; parity test-pinned).  The supervisor
# exports the surviving device total so a relaunched rank can verify
# the topology it actually attached matches the supervisor's
# accounting; a healed host's agent (or the elastic smoke) re-announces
# capacity by writing {"devices": N} into <log_dir>/elastic_rejoin.json
# — the grow-back probe consumes it at the next checkpoint boundary.
ELASTIC_DEVICES_ENV = "DTF_ELASTIC_DEVICES"
REJOIN_FILE = "elastic_rejoin.json"


def classify_exit(rc: int) -> str:
    if rc == 0:
        return "ok"
    if rc == EXIT_PREEMPTED:
        return "preempted"
    if rc == EXIT_DEVICE_LOST:
        return "device_loss"
    return "crash"


def read_rejoin(log_dir: str):
    """Announced rejoin capacity (device count), or None when absent,
    torn, or malformed — ANY unreadable announce reads as 'not yet',
    never as a grow (and never as a supervisor crash: this runs inside
    the monitor loop)."""
    try:
        with open(os.path.join(log_dir, REJOIN_FILE)) as f:
            doc = json.load(f)
        if not isinstance(doc, dict):
            return None
        return int(doc.get("devices", 0))
    except (OSError, ValueError, TypeError):
        return None


class SupervisorEventLog:
    """Append-only ``supervisor_events.jsonl`` in the log dir: one JSON
    record per supervision decision (rank exits with classification,
    heartbeat kills, restarts with backoff + budget state, give-ups) —
    post-mortems read this instead of scraping log{N}.retry{M}.log
    filenames.  Best-effort: a full disk must not take down the
    supervisor with the job."""

    def __init__(self, log_dir: str):
        self.path = os.path.join(log_dir, "supervisor_events.jsonl")

    def emit(self, event: str, **attrs) -> None:
        rec = {"ts": time.time(), "event": event}
        rec.update(attrs)
        try:
            with open(self.path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError:
            pass


def heartbeat_path(directory: str, rank: int) -> str:
    return os.path.join(directory, f"heartbeat_rank{rank}.json")


def read_heartbeat(path: str):
    """Parse a heartbeat file; None when missing/torn (treated as 'no
    heartbeat signal', not as death — log growth still counts)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def build_env(rank: int, world: int, coordinator: str,
              devices_per_process: Optional[int] = None,
              heartbeat_dir: Optional[str] = None,
              generation: int = 0,
              trace_id: Optional[str] = None,
              elastic_devices: Optional[int] = None) -> dict:
    env = dict(os.environ)
    env["DTF_COORDINATOR"] = coordinator
    env["DTF_PROCESS_ID"] = str(rank)
    env["DTF_PROCESS_COUNT"] = str(world)
    if trace_id:
        # run-scoped trace id: every rank (and every restart attempt)
        # of one supervised job shares it, so their trace records join
        # one timeline (`trace_main --request <id>`).  The runner
        # installs it as the process default trace
        # (obs/trace.set_default_trace).  Unconditional: the per-job id
        # is authoritative here — operator intent (an exported
        # DTF_TRACE_ID) was already folded in when the job minted it,
        # and a stale var lingering in os.environ must not fuse two
        # jobs' timelines.
        env["DTF_TRACE_ID"] = trace_id
    # restart generation (= supervisor attempt): the async-PS snapshot
    # tags its done_count with this, so a whole-job restart discards
    # the stale generation's DONE tally instead of double-counting it
    # (dtf_tpu/parallel/ps.py GENERATION_ENV — duplicated string for
    # the same stdlib-only reason as the contracts above; parity is
    # pinned by tests/test_ps.py)
    env["DTF_RESTART_GENERATION"] = str(generation)
    if heartbeat_dir:
        # ranks running dtf_tpu mains rewrite
        # <log_dir>/heartbeat_rank{N}.json at a bounded interval
        # (obs/watchdog.Heartbeat) — the supervisor's structured
        # liveness signal, replacing stdout-size scraping
        env[HEARTBEAT_DIR_ENV] = os.path.abspath(heartbeat_dir)
    if elastic_devices:
        # elastic supervision: the surviving device TOTAL this attempt
        # was sized for — the runner verifies its attached topology
        # against it (train/elastic.note_elastic_resume) so a relaunch
        # that silently got a different mesh than the supervisor
        # accounted for fails loudly instead of training mis-sharded
        env[ELASTIC_DEVICES_ENV] = str(elastic_devices)
    if devices_per_process:
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            f" --xla_force_host_platform_device_count="
                            f"{devices_per_process}")
    return env


def _run_once(cmd: List[str], num_processes: int, coordinator: str,
              log_dir: str, devices_per_process: Optional[int],
              stagger_s: float = 0.0,
              heartbeat_timeout: Optional[float] = None,
              attempt: int = 0, startup_grace: float = 300.0,
              events: Optional[SupervisorEventLog] = None,
              teardown_grace: float = 60.0,
              trace_id: Optional[str] = None,
              grow_check=None,
              elastic_devices: Optional[int] = None):
    """One supervised attempt.  Returns ``(rc, classification, grew)``:
    the first failing rank's exit code and REFINED classification
    (heartbeat-lost kills and unprompted SIGKILLs read as host loss,
    EXIT_DEVICE_LOST as device loss), and whether ``grow_check`` fired
    — in which case the attempt was deliberately drained (SIGTERM ⇒
    emergency checkpoints ⇒ the preempted exit) so the caller can
    relaunch at the restored topology."""
    os.makedirs(log_dir, exist_ok=True)
    if events is None:
        events = SupervisorEventLog(log_dir)
    events.emit("attempt_start", attempt=attempt, ranks=num_processes,
                devices_per_process=devices_per_process)
    # teardown escalation state: once a failure SIGTERMs the survivors,
    # they get `teardown_grace` seconds to emergency-checkpoint and
    # exit; a rank wedged in a dead collective (or ignoring SIGTERM)
    # is then hard-killed — without this the monitor loop would wait
    # on it forever (the finally's kill only runs after the loop ends)
    term_at: Optional[float] = None
    procs = []  # (rank, Popen)
    logs = []
    rc = 0
    first_cls = "ok"
    grew = False
    # kill attribution for host-loss classification: ranks THIS
    # supervisor SIGKILLed (heartbeat loss, teardown escalation) vs an
    # unprompted SIGKILL from outside (OOM-killer, the host vanishing)
    hb_killed: set = set()
    td_killed: set = set()
    # hang watchdog state: last time each rank showed life — via its
    # heartbeat file (structured, preferred) or its log growing
    # (fallback ONLY for ranks that have never emitted a heartbeat: once
    # a rank has beaten, log growth stops counting, so a rank whose log
    # grows from a side thread while its training thread is deadlocked
    # is still caught)
    sizes = [0] * num_processes
    hb_ts = [None] * num_processes   # last heartbeat payload ts seen
    hb_mtime = [None] * num_processes  # stat gate: parse only on change
    last_beat = [0.0] * num_processes
    spawned = [0.0] * num_processes
    # restart attempts keep earlier logs (the first failure is usually
    # the informative one): log0.log, then log0.retry1.log, ...
    suffix = f".retry{attempt}" if attempt else ""
    log_path = lambda rank: os.path.join(log_dir, f"log{rank}{suffix}.log")
    try:
        for rank in range(num_processes):
            # a heartbeat file surviving a previous attempt must not
            # masquerade as this attempt's first beat
            try:
                os.unlink(heartbeat_path(log_dir, rank))
            except OSError:
                pass
            f = open(log_path(rank), "wb")
            logs.append(f)
            p = subprocess.Popen(
                cmd, env=build_env(rank, num_processes, coordinator,
                                   devices_per_process,
                                   heartbeat_dir=log_dir,
                                   generation=attempt,
                                   trace_id=trace_id,
                                   elastic_devices=elastic_devices),
                stdout=f, stderr=subprocess.STDOUT)
            procs.append((rank, p))
            last_beat[rank] = spawned[rank] = time.monotonic()
            if stagger_s:
                time.sleep(stagger_s)  # run.sh's 1 s stagger, now optional
        while procs:
            for rank, p in list(procs):
                ret = p.poll()
                if ret is None:
                    if heartbeat_timeout:
                        # liveness: the rank's heartbeat file advanced
                        # (obs/watchdog beats at a bounded interval even
                        # when nothing logs — e.g. mid-epoch with a long
                        # --log_steps); ranks that never beat fall back
                        # to log growth.  Quiet past the timeout means a
                        # hung collective or deadlock — the failure mode
                        # the reference could only resolve by hand with
                        # kill.sh
                        now = time.monotonic()
                        # mtime gate: beats land every heartbeat_secs at
                        # most, so one stat per poll replaces an
                        # open+parse per poll
                        try:
                            mt = os.stat(
                                heartbeat_path(log_dir, rank)).st_mtime
                        except OSError:
                            mt = hb_mtime[rank]
                        if mt != hb_mtime[rank]:
                            hb_mtime[rank] = mt
                            hb = read_heartbeat(
                                heartbeat_path(log_dir, rank))
                            if (hb is not None
                                    and hb.get("ts") != hb_ts[rank]):
                                hb_ts[rank] = hb.get("ts")
                                last_beat[rank] = now
                        try:
                            sz = os.path.getsize(log_path(rank))
                        except OSError:
                            sz = sizes[rank]
                        if sz != sizes[rank]:
                            sizes[rank] = sz
                            # log growth is liveness only until the
                            # first heartbeat: after that, a growing log
                            # with a stale heartbeat is the deadlocked-
                            # but-chatty signature, not life
                            if hb_ts[rank] is None:
                                last_beat[rank] = now
                        if (now - last_beat[rank] > heartbeat_timeout
                                # a rank in first XLA compile /
                                # checkpoint restore legitimately logs
                                # nothing for minutes — give every rank
                                # a startup grace before the heartbeat
                                # rule applies
                                and now - spawned[rank] > startup_grace):
                            print(f"rank {rank} heartbeat lost "
                                  f"({heartbeat_timeout:.0f}s without "
                                  f"{'a heartbeat' if hb_ts[rank] is not None else 'log output'}"
                                  f"); killing", file=sys.stderr)
                            events.emit("heartbeat_lost", attempt=attempt,
                                        rank=rank,
                                        timeout_s=heartbeat_timeout)
                            # heartbeat silence is the host-loss
                            # signature (a dead host stops beating long
                            # before any exit code arrives) — remember
                            # the kill so the exit classifies as
                            # host_loss, not as our own SIGKILL
                            hb_killed.add(rank)
                            p.kill()
                    continue
                procs.remove((rank, p))
                cls = classify_exit(ret)
                if rank in hb_killed:
                    cls = "host_loss"
                elif (ret < 0 and -ret == signal.SIGKILL
                        and rank not in td_killed):
                    # an unprompted SIGKILL: this supervisor did not
                    # send it, and a python crash cannot exit via
                    # SIGKILL on its own — the OOM-killer or the host
                    # going away, i.e. host loss
                    cls = "host_loss"
                events.emit("rank_exit", attempt=attempt, rank=rank,
                            code=ret, classification=cls,
                            log=log_path(rank))
                if ret != 0:
                    if rc == 0:  # keep the FIRST failure's code + class
                        rc = ret
                        first_cls = cls
                    print(f"rank {rank} exited {ret} "
                          f"({cls}; see "
                          f"{log_path(rank)}); tearing down",
                          file=sys.stderr)
                    for _, q in procs:  # kill.sh parity — SIGTERM first
                        # so dtf mains can emergency-checkpoint (the
                        # preemption path); hard kill after
                        # teardown_grace below
                        q.send_signal(signal.SIGTERM)
                    if term_at is None:
                        term_at = time.monotonic()
            if (term_at is not None and procs
                    and time.monotonic() - term_at > teardown_grace):
                for r2, q in procs:
                    print(f"rank {r2} still alive {teardown_grace:.0f}s "
                          f"after teardown SIGTERM; killing",
                          file=sys.stderr)
                    events.emit("teardown_kill", attempt=attempt, rank=r2,
                                grace_s=teardown_grace)
                    td_killed.add(r2)
                    q.kill()
                term_at = None  # killed; the loop reaps their exits
            if (grow_check is not None and not grew and term_at is None
                    and procs and grow_check()):
                # capacity re-announced while running shrunken: drain
                # the job at a CHECKPOINT BOUNDARY (SIGTERM ⇒ the
                # preemption path's emergency sealed checkpoint at the
                # next step boundary ⇒ exit 75) and let the caller
                # relaunch at the restored topology
                grew = True
                events.emit("grow_triggered", attempt=attempt)
                print("elastic: capacity re-announced — draining for a "
                      "grow-back relaunch at the next checkpoint "
                      "boundary", file=sys.stderr)
                for _, q in procs:
                    q.send_signal(signal.SIGTERM)
                term_at = time.monotonic()
            time.sleep(0.2)
    finally:
        for _, q in procs:
            q.kill()
        for f in logs:
            f.close()
    return rc, first_cls, grew


def launch_local(cmd: List[str], num_processes: int, coordinator: str,
                 log_dir: str, devices_per_process: Optional[int],
                 stagger_s: float = 0.0, max_restarts: int = 0,
                 heartbeat_timeout: Optional[float] = None,
                 startup_grace: float = 300.0,
                 restart_window_s: float = 3600.0,
                 restart_backoff_s: float = 1.0,
                 max_preemptions: int = 100,
                 teardown_grace: float = 60.0,
                 elastic: bool = False, min_devices: int = 1,
                 max_elastic: int = 16) -> int:
    """Run the job, supervising it.

    On any rank failing (or hanging, with ``heartbeat_timeout``), tear
    down and relaunch ALL ranks — the sync-SPMD recovery unit is the
    whole job, with progress carried by checkpoints (pair the training
    command with ``--resume``).  The reference's recovery story was
    manual: per-epoch checkpoints plus an operator running kill.sh and
    re-running run.sh (SURVEY §5.3).

    Exit-code classification drives the restart policy:

      preempted (EXIT_PREEMPTED, 75) — the rank wrote a durable
          emergency checkpoint before exiting: relaunch immediately,
          WITHOUT consuming the crash budget (capped only by
          ``max_preemptions``, a runaway-loop backstop).  Only when
          supervision was actually requested (``max_restarts`` > 0 or a
          ``heartbeat_timeout``): an unsupervised launch whose operator
          SIGTERMs it must STOP, not resurrect itself 100 times.
      device_loss (EXIT_DEVICE_LOST, 76) / host_loss (heartbeat-lost
          kill, or an UNPROMPTED SIGKILL — the OOM-killer / the host
          vanishing) — with ``elastic`` set, these are TOPOLOGY losses,
          not crashes: restarting at the same size would fail the same
          way, so the supervisor SHRINKS instead (host loss drops the
          lost host's worth of ranks; device loss halves the local
          device count — the finest granularity an emulated topology
          can report), relaunches on the surviving mesh at the last
          checkpoint, and refuses LOUDLY when the result would fall
          below ``min_devices``.  The training command resolves its own
          parallelization against whatever it attaches (``--plan auto``
          re-plans; mirrored re-meshes), so the GLOBAL batch and step
          semantics are invariant across the shrink.  Capped by
          ``max_elastic`` (a flapping-fabric backstop), never by the
          crash budget.  While shrunken, the supervisor probes
          ``<log_dir>/elastic_rejoin.json`` (a healed host's agent — or
          an operator — re-announces capacity there): once the
          announced device count covers the full topology again, the
          job is DRAINED at a checkpoint boundary (SIGTERM ⇒ emergency
          sealed checkpoint ⇒ exit 75) and relaunched at full size —
          preemption becomes a throughput dip, not an outage.  Without
          ``elastic`` both classifications fall back to the budgeted
          crash policy (the label still lands in the event log).
      crash (any other nonzero, incl. death by signal) — budgeted:
          ``max_restarts`` crashes per sliding ``restart_window_s``
          window (a long healthy run earns its budget back — unlike
          the old lifetime counter, where a week of uptime and a
          crash-loop looked the same), with exponential backoff
          ``restart_backoff_s × 2^(n-1)`` between relaunches.

    Every decision lands in ``<log_dir>/supervisor_events.jsonl``.
    """
    os.makedirs(log_dir, exist_ok=True)
    # run-scoped trace id, minted ONCE for the whole supervised job and
    # handed to every rank (and every restart attempt) through
    # build_env — all ranks' trace records share it, so `trace_main
    # --request <id>` joins the cross-rank timeline.  An
    # operator-exported DTF_TRACE_ID wins (correlate with an outer
    # orchestrator); otherwise a local variable, not os.environ — an
    # in-process caller launching several jobs (tests) must not have
    # them share one id.  Stdlib-only (os.urandom), matching
    # obs/trace.new_trace_id().
    run_trace_id = os.environ.get("DTF_TRACE_ID") or os.urandom(8).hex()
    events = SupervisorEventLog(log_dir)
    supervising = (bool(max_restarts) or heartbeat_timeout is not None
                   or elastic)
    if elastic and not devices_per_process and num_processes <= 1:
        raise ValueError(
            "--elastic needs a topology the supervisor can shrink: "
            "--devices_per_process (local/virtual device count) or "
            "--num_processes > 1")
    # elastic topology state: the full (launch-time) topology and the
    # current surviving one.  dpp=None means "whatever is attached" —
    # it counts as 1 for totals so the multi-process host-loss lever
    # still works without a device count.
    dpp1 = lambda d: d if d else 1
    full_procs, full_dpp = num_processes, devices_per_process
    cur_procs, cur_dpp = num_processes, devices_per_process
    full_total = full_procs * dpp1(full_dpp)
    losses = 0
    if elastic:
        # a rejoin announce surviving a PREVIOUS job must not trigger
        # an instant spurious grow
        try:
            os.unlink(os.path.join(log_dir, REJOIN_FILE))
        except OSError:
            pass
    attempt = 0
    preemptions = 0
    crash_times: collections.deque = collections.deque()
    while True:
        cur_total = cur_procs * dpp1(cur_dpp)
        grow_check = None
        if elastic and cur_total < full_total:
            grow_check = (lambda need=full_total:
                          (read_rejoin(log_dir) or 0) >= need)
        rc, cls, grew = _run_once(
            cmd, cur_procs, coordinator, log_dir,
            cur_dpp, stagger_s, heartbeat_timeout,
            attempt=attempt, startup_grace=startup_grace,
            events=events, teardown_grace=teardown_grace,
            trace_id=run_trace_id, grow_check=grow_check,
            # only exported when the supervisor actually KNOWS the
            # device total (devices_per_process set): in multi-process
            # mode without it, cur_total counts ranks, not devices,
            # and the runner's topology verification against it would
            # wrongly refuse any rank attaching more than one device
            elastic_devices=(cur_total if elastic and cur_dpp
                             else None))
        if grew and rc != 0:
            # deliberately drained for growth (the expected exits are
            # 75 after the emergency checkpoint): restore the full
            # topology, consume the announce, relaunch outside the
            # crash budget.  A rank that died DIRTY during the drain
            # (anything but preempted) is recorded honestly — the
            # relaunch still resumes from the last SEALED checkpoint,
            # losing at most the boundary save, and the loop is
            # bounded because each grow needs a fresh shrink, which
            # max_elastic caps.
            try:
                os.unlink(os.path.join(log_dir, REJOIN_FILE))
            except OSError:
                pass
            cur_procs, cur_dpp = full_procs, full_dpp
            attempt += 1
            events.emit("elastic_grow", restart=attempt, procs=cur_procs,
                        devices_per_process=cur_dpp,
                        total_devices=full_total,
                        drain_classification=cls)
            if cls != "preempted":
                print(f"elastic: grow-back drain exited DIRTY "
                      f"({cls}, rc {rc}) — the boundary checkpoint may "
                      f"be missing; resuming from the last sealed one",
                      file=sys.stderr)
            print(f"elastic: growing back to {full_total} device(s) "
                  f"({cur_procs} rank(s)) — restart {attempt}",
                  file=sys.stderr)
            continue
        if cls == "ok" or rc == 0:
            events.emit("job_done", attempts=attempt)
            return 0
        if elastic and cls in ("device_loss", "host_loss"):
            losses += 1
            if losses > max_elastic:
                events.emit("give_up", code=rc, classification=cls,
                            losses=losses, max_elastic=max_elastic)
                print(f"giving up: {losses} topology losses exceed "
                      f"--max_elastic {max_elastic} (flapping fabric?)",
                      file=sys.stderr)
                return rc
            if cls == "host_loss" and cur_procs > 1:
                # the lost host's ranks are gone; its devices with it
                new_procs, new_dpp = cur_procs - 1, cur_dpp
            elif dpp1(cur_dpp) > 1:
                # device loss (or a single-process host emulation):
                # halve the local device count — the finest surviving-
                # capacity granularity an exit code can report
                new_procs, new_dpp = cur_procs, dpp1(cur_dpp) // 2
            else:
                new_procs, new_dpp = cur_procs - 1, cur_dpp
            new_total = new_procs * dpp1(new_dpp)
            if new_procs < 1 or new_total < min_devices:
                events.emit("give_up", code=rc, classification=cls,
                            reason="min_devices",
                            surviving_devices=new_total,
                            min_devices=min_devices)
                print(f"giving up: {cls} would shrink the job to "
                      f"{new_total} device(s), below the --min_devices "
                      f"floor ({min_devices}) — refusing to resume "
                      f"that small; waiting for capacity is the "
                      f"operator's call", file=sys.stderr)
                return rc
            cur_procs, cur_dpp = new_procs, new_dpp
            attempt += 1
            events.emit("elastic_shrink", classification=cls,
                        restart=attempt, procs=cur_procs,
                        devices_per_process=cur_dpp,
                        total_devices=new_total, losses=losses,
                        max_elastic=max_elastic)
            print(f"elastic: {cls} — resuming smaller on {new_total} "
                  f"device(s) ({cur_procs} rank(s)) at the last "
                  f"checkpoint (restart {attempt}; crash budget "
                  f"untouched)", file=sys.stderr)
            continue
        if cls == "preempted":
            if not supervising:
                events.emit("give_up", code=rc, classification=cls,
                            reason="unsupervised")
                print("job preempted; not supervising (no --max_restarts/"
                      "--heartbeat_timeout) — exiting", file=sys.stderr)
                return rc
            preemptions += 1
            if preemptions > max_preemptions:
                events.emit("give_up", code=rc, classification=cls,
                            preemptions=preemptions,
                            max_preemptions=max_preemptions)
                print(f"giving up: {preemptions} preemptions exceed "
                      f"--max_preemptions {max_preemptions}",
                      file=sys.stderr)
                return rc
            attempt += 1
            events.emit("restart", classification=cls, restart=attempt,
                        backoff_s=0.0, preemptions=preemptions,
                        crashes_in_window=len(crash_times),
                        budget=max_restarts)
            print(f"relaunching all {cur_procs} ranks after "
                  f"preemption (restart {attempt}; crash budget "
                  f"untouched)", file=sys.stderr)
            continue
        # crash — including device/host loss WITHOUT --elastic (the
        # honest label still landed in the event log, but the policy
        # without an elastic mandate is the plain budgeted restart):
        # sliding-window budget + exponential backoff
        now = time.monotonic()
        while crash_times and now - crash_times[0] > restart_window_s:
            crash_times.popleft()
        if len(crash_times) >= max_restarts:
            events.emit("give_up", code=rc, classification=cls,
                        crashes_in_window=len(crash_times),
                        window_s=restart_window_s, budget=max_restarts)
            return rc
        crash_times.append(now)
        backoff = restart_backoff_s * (2.0 ** (len(crash_times) - 1))
        attempt += 1
        events.emit("restart", classification=cls, restart=attempt,
                    backoff_s=backoff, crashes_in_window=len(crash_times),
                    window_s=restart_window_s, budget=max_restarts)
        print(f"relaunching all {cur_procs} ranks (crash "
              f"{len(crash_times)}/{max_restarts} in window; backoff "
              f"{backoff:.1f}s)", file=sys.stderr)
        if backoff > 0:
            time.sleep(backoff)


def cluster_commands(cmd: List[str], hosts: List[str], coordinator: str,
                     log_dir: str, background: bool = True) -> List[str]:
    """One ssh line per host — the run.sh loop, generated.

    `background` appends `&` for manual copy-paste use; --execute mode
    passes False so ssh blocks until the remote rank exits and its
    status is observable."""
    world = len(hosts)
    quoted = " ".join(shlex.quote(c) for c in cmd)
    # one run-scoped trace id for the WHOLE cluster job (same contract
    # as launch_local): every host's rank inherits it, so their trace
    # records join one timeline.  An operator-exported DTF_TRACE_ID
    # wins — correlate with an outer orchestrator by exporting it.
    trace_id = os.environ.get("DTF_TRACE_ID") or os.urandom(8).hex()
    lines = []
    for rank, host in enumerate(hosts):
        envs = (f"DTF_COORDINATOR={coordinator} DTF_PROCESS_ID={rank} "
                f"DTF_PROCESS_COUNT={world} DTF_TRACE_ID={trace_id}")
        logfile = shlex.quote(f"{log_dir}/log{rank}.log")
        remote = (f"mkdir -p {shlex.quote(log_dir)} && {envs} {quoted} "
                  f"> {logfile} 2>&1")
        if background:
            remote += " &"
        lines.append(f"ssh {host} {shlex.quote(remote)}")
    return lines


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--" not in argv:
        print(__doc__)
        return 2
    split = argv.index("--")
    opts, cmd = argv[:split], argv[split + 1:]

    num_processes, coordinator = 1, "localhost:12346"
    hosts: List[str] = []
    log_dir = "./ranklogs"
    devices_per_process: Optional[int] = None
    execute = False
    max_restarts = 0
    heartbeat_timeout: Optional[float] = None
    startup_grace: Optional[float] = None  # None → default 300 (local mode)
    restart_window_s = 3600.0
    restart_backoff_s = 1.0
    max_preemptions = 100
    teardown_grace = 60.0
    elastic = False
    min_devices = 1
    max_elastic = 16
    supervise_flags_set = False
    i = 0
    while i < len(opts):
        o = opts[i]
        if o == "--num_processes":
            num_processes = int(opts[i + 1]); i += 2
        elif o == "--coordinator":
            coordinator = opts[i + 1]; i += 2
        elif o == "--hosts":
            hosts = [h.strip() for h in opts[i + 1].split(",") if h.strip()]
            i += 2
        elif o == "--log_dir":
            log_dir = opts[i + 1]; i += 2
        elif o == "--devices_per_process":
            devices_per_process = int(opts[i + 1]); i += 2
        elif o == "--execute":
            execute = True; i += 1
        elif o == "--max_restarts":
            max_restarts = int(opts[i + 1]); i += 2
        elif o == "--heartbeat_timeout":
            heartbeat_timeout = float(opts[i + 1]); i += 2
        elif o == "--startup_grace":
            startup_grace = float(opts[i + 1]); i += 2
        elif o == "--restart_window":
            restart_window_s = float(opts[i + 1])
            supervise_flags_set = True; i += 2
        elif o == "--restart_backoff":
            restart_backoff_s = float(opts[i + 1])
            supervise_flags_set = True; i += 2
        elif o == "--max_preemptions":
            max_preemptions = int(opts[i + 1])
            supervise_flags_set = True; i += 2
        elif o == "--teardown_grace":
            teardown_grace = float(opts[i + 1])
            supervise_flags_set = True; i += 2
        elif o == "--elastic":
            elastic = True
            supervise_flags_set = True; i += 1
        elif o == "--min_devices":
            min_devices = int(opts[i + 1])
            supervise_flags_set = True; i += 2
        elif o == "--max_elastic":
            max_elastic = int(opts[i + 1])
            supervise_flags_set = True; i += 2
        else:
            raise ValueError(f"unknown launcher option {o}")

    if hosts:
        if num_processes != 1 or devices_per_process:
            raise ValueError(
                "--hosts runs one rank per host; --num_processes/"
                "--devices_per_process are not supported with it")
        if (max_restarts or heartbeat_timeout or startup_grace is not None
                or supervise_flags_set):
            raise ValueError(
                "--max_restarts/--heartbeat_timeout/--startup_grace/"
                "--restart_window/--restart_backoff/--max_preemptions/"
                "--teardown_grace/--elastic/--min_devices/--max_elastic "
                "supervise local fan-out; for --hosts runs, supervise "
                "on each host")
        if coordinator == "localhost:12346":
            coordinator = f"{hosts[0]}:12346"
        lines = cluster_commands(cmd, hosts, coordinator, log_dir,
                                 background=not execute)
        if not execute:
            print("\n".join(lines))
            return 0
        # blocking ssh per rank: failures are observable and propagated
        running = [subprocess.Popen(line, shell=True) for line in lines]
        rc = 0
        for rank, p in enumerate(running):
            ret = p.wait()
            if ret:
                print(f"host rank {rank} exited {ret}", file=sys.stderr)
                if rc == 0:
                    rc = ret
        return rc
    # startup_grace default: 300 s covers first-compile stalls, but an
    # operator who explicitly set a SHORTER --heartbeat_timeout wants
    # hangs caught on that clock from the start — so the unset-grace
    # default follows the explicit timeout downward (never upward: a
    # long steady-state timeout must not weaken startup detection).
    if startup_grace is None:
        startup_grace = (min(heartbeat_timeout, 300.0)
                         if heartbeat_timeout else 300.0)
    return launch_local(cmd, num_processes, coordinator, log_dir,
                        devices_per_process, max_restarts=max_restarts,
                        heartbeat_timeout=heartbeat_timeout,
                        startup_grace=startup_grace,
                        restart_window_s=restart_window_s,
                        restart_backoff_s=restart_backoff_s,
                        max_preemptions=max_preemptions,
                        teardown_grace=teardown_grace,
                        elastic=elastic, min_devices=min_devices,
                        max_elastic=max_elastic)


if __name__ == "__main__":
    sys.exit(main())
