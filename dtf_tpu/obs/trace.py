"""Structured JSONL tracing.

One record per line, one file per rank (``trace_rank{N}.jsonl``), three
record kinds:

  span    — a timed region: {"kind":"span","name":...,"ts":<start>,
            "dur_s":...,"rank":...,"parent":...,  ...attrs}
            (written when the region EXITS, so a crash mid-span leaves
            the enclosing spans visible up to the crash point)
  event   — a point-in-time marker: {"kind":"event","name":...,
            "ts":..., ...attrs} (e.g. "heartbeat")
  anomaly — an event that means the run is unhealthy: same shape with
            kind="anomaly" ("nan_loss", "step_time_regression", ...).
            `trace_main --check` exits nonzero when any is present.

Design constraints, in order:

  1. disabled == free: every public entry point hits a module-level
     None check and returns a shared no-op object.  No locks, no
     allocation, no time syscalls.
  2. enabled but off the step critical path: records are appended to an
     in-memory list and flushed to disk every ``flush_every`` records
     (and at close/atexit), so a per-step span costs two clock reads,
     one small dict, and an amortized write.
  3. crash-robust enough to debug the crash: the flush interval bounds
     the loss window, and abort paths (watchdog) flush explicitly.

The tracer is configured once per process — from ``--trace_dir`` via
:func:`maybe_configure`, or from the ``DTF_TRACE_DIR`` environment
variable that the launcher forwards to every rank.  Rank identity comes
from config/env (``DTF_PROCESS_ID``), NOT from jax — importing this
module must never initialize a backend.

SPAN CONTEXT (request-scoped distributed tracing): every record can
carry a ``trace`` id that survives process boundaries, so one request's
life — router queue, dispatch, replica prefill/decode, failover,
completion — is reconstructable across N trace files
(``trace_main --request <id>``).  Three propagation layers:

  - explicit attrs win: ``trace.event("x", trace=tid)`` — the serving
    tier tags per-request records this way (one engine iteration
    serves MANY requests, so ambient context can't express it; batch
    spans carry a ``traces`` list instead).
  - thread-local :func:`context` — ``with trace.context(tid, parent):``
    stamps every record emitted under it.
  - process-wide :func:`set_default_trace` — the RUN-scoped id the
    launcher mints once (``DTF_TRACE_ID``) and every rank inherits, so
    train steps, checkpoint saves, eval and data-service records join
    one timeline without per-call plumbing.

Spans additionally get a process-unique ``span_id`` (rank-qualified
counter — no syscalls) and a ``parent_span`` id when nested; a parent
id crossing a process boundary (the router's per-request span id,
carried over the replica wire) lands via ``parent_span`` too, which is
what makes the context *propagatable* rather than merely ambient.
"""

from __future__ import annotations

import atexit
import contextlib
import itertools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

_tracer: Optional["Tracer"] = None
_lock = threading.Lock()
_local = threading.local()
_default_trace: Optional[str] = None


def new_trace_id() -> str:
    """A fresh 16-hex trace id (collision-safe across processes)."""
    return os.urandom(8).hex()


def new_span_id() -> str:
    """A fresh 8-hex span id for callers that need one BEFORE any span
    opens (the router mints one per request and sends it over the wire
    as the replica-side records' ``parent_span``)."""
    return os.urandom(4).hex()


def set_default_trace(trace_id: Optional[str]) -> None:
    """Install the process-wide run-scoped trace id (None clears it).
    Stamped on every record that carries no explicit/contextual
    trace — the train-side 'everything in this run joins up' layer."""
    global _default_trace
    _default_trace = trace_id or None


def default_trace() -> Optional[str]:
    return _default_trace


@contextlib.contextmanager
def context(trace_id: Optional[str], parent: Optional[str] = None):
    """Thread-local span context: records emitted under it default
    their ``trace`` (and ``parent_span``) to these ids.  Nests; inner
    contexts shadow outer ones; explicit attrs always win."""
    prev = getattr(_local, "ctx", None)
    _local.ctx = (trace_id, parent)
    try:
        yield
    finally:
        _local.ctx = prev


def current_context():
    """(trace_id, parent_span) of the active :func:`context`, or
    None."""
    return getattr(_local, "ctx", None)


class _NullSpan:
    """Shared do-nothing context manager for the disabled fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "name", "attrs", "t0", "span_id")

    def __init__(self, tracer: "Tracer", name: str, attrs: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        self.span_id = self._tracer._next_span_id()
        self._tracer._stack().append((self.name, self.span_id))
        self.t0 = time.time()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.time() - self.t0
        stack = self._tracer._stack()
        stack.pop()
        rec = {"kind": "span", "name": self.name, "ts": self.t0,
               "dur_s": dur, "span_id": self.span_id}
        if stack:
            # parent name kept for the summarizer's nesting view;
            # parent_span is the id link the request timeline follows
            rec["parent"] = stack[-1][0]
            rec["parent_span"] = stack[-1][1]
        if exc_type is not None:
            rec["error"] = exc_type.__name__
        if self.attrs:
            rec.update(self.attrs)
        self._tracer.emit(rec)
        return False


class Tracer:
    """Buffered JSONL writer; thread-safe; one instance per process."""

    def __init__(self, path: str, rank=0, flush_every: int = 256):
        self.path = os.path.abspath(path)
        # rank is an int for launcher ranks; NAMED streams (the serving
        # router) tag records with their stream name instead, so a
        # merged timeline reads "router" next to 0..N-1
        self.rank = rank if isinstance(rank, str) else int(rank)
        self.flush_every = max(int(flush_every), 1)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._file = open(self.path, "a", buffering=1024 * 64)
        self._buf: List[str] = []
        self._mu = threading.Lock()
        self._local = threading.local()
        self._span_ids = itertools.count(1)
        self.emit({"kind": "event", "name": "trace_start", "ts": time.time(),
                   "pid": os.getpid()})

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _next_span_id(self) -> str:
        # rank-qualified counter: unique across a run's processes with
        # no per-span syscall (os.urandom per step would be real cost)
        return f"{self.rank}.{next(self._span_ids)}"

    # -- record emission ----------------------------------------------
    def emit(self, record: Dict[str, Any]) -> None:
        record.setdefault("rank", self.rank)
        # span context: explicit attrs > thread-local context() >
        # process default (the run-scoped trace id) — setdefault keeps
        # the precedence without ever overwriting a caller's tag
        ctx = getattr(_local, "ctx", None)
        if ctx is not None:
            if ctx[0] is not None:
                record.setdefault("trace", ctx[0])
            if ctx[1] is not None:
                record.setdefault("parent_span", ctx[1])
        elif _default_trace is not None:
            record.setdefault("trace", _default_trace)
        line = json.dumps(record, default=str)
        with self._mu:
            self._buf.append(line)
            if len(self._buf) >= self.flush_every:
                self._flush_locked()

    def span(self, name: str, **attrs) -> _Span:
        return _Span(self, name, attrs)

    def event(self, name: str, **attrs) -> None:
        rec = {"kind": "event", "name": name, "ts": time.time()}
        rec.update(attrs)
        self.emit(rec)

    def anomaly(self, name: str, **attrs) -> None:
        rec = {"kind": "anomaly", "name": name, "ts": time.time()}
        rec.update(attrs)
        self.emit(rec)
        self.flush()  # anomalies must survive the crash they predict

    # -- lifecycle -----------------------------------------------------
    def _flush_locked(self) -> None:
        if self._buf and not self._file.closed:
            self._file.write("\n".join(self._buf) + "\n")
            self._file.flush()
        self._buf.clear()

    def flush(self) -> None:
        with self._mu:
            self._flush_locked()

    def close(self) -> None:
        with self._mu:
            self._flush_locked()
            if not self._file.closed:
                self._file.close()


# ---------------------------------------------------------------------------
# Module-level API (what instrumented code calls)
# ---------------------------------------------------------------------------

def configure(trace_dir: str, rank: Optional[int] = None,
              flush_every: int = 256,
              stream: Optional[str] = None) -> Tracer:
    """Install the process-global tracer writing under ``trace_dir``.
    Idempotent per (dir, rank): reconfiguring replaces the tracer.

    ``stream`` names a NON-RANK stream: the file becomes
    ``trace_<stream>.jsonl`` and records are tagged with the stream
    name — the serving router writes ``trace_router.jsonl`` next to
    its replicas' ``trace_rank{K}.jsonl`` so ``trace_main --merge``
    interleaves the tiers into one timeline."""
    global _tracer
    if stream is not None:
        path = os.path.join(trace_dir, f"trace_{stream}.jsonl")
        rank = stream
    else:
        if rank is None:
            rank = int(os.environ.get("DTF_PROCESS_ID", "0"))
        path = os.path.join(trace_dir, f"trace_rank{rank}.jsonl")
    with _lock:
        if _tracer is not None:
            if _tracer.path == os.path.abspath(path):
                return _tracer  # same destination — keep the live tracer
            _tracer.close()
        _tracer = Tracer(path, rank=rank, flush_every=flush_every)
    return _tracer


def maybe_configure(cfg=None) -> Optional[Tracer]:
    """Configure from ``cfg.trace_dir`` or the ``DTF_TRACE_DIR`` env var
    (launcher ranks inherit the env).  Returns the tracer, or None when
    tracing stays off.  Explicit config wins over env."""
    trace_dir = (getattr(cfg, "trace_dir", "") or
                 os.environ.get("DTF_TRACE_DIR", ""))
    if not trace_dir:
        return None
    rank = getattr(cfg, "process_id", None) if cfg is not None else None
    return configure(trace_dir, rank=rank)


def get() -> Optional[Tracer]:
    return _tracer


def enabled() -> bool:
    return _tracer is not None


def disable() -> None:
    """Close and uninstall the global tracer (tests).  Also clears the
    process default trace id so one test's run id never leaks into the
    next run's records."""
    global _tracer
    with _lock:
        if _tracer is not None:
            _tracer.close()
        _tracer = None
    set_default_trace(None)


def span(name: str, **attrs):
    """``with trace.span("step", step=n): ...`` — no-op when disabled."""
    t = _tracer
    if t is None:
        return _NULL_SPAN
    return t.span(name, **attrs)


def event(name: str, **attrs) -> None:
    t = _tracer
    if t is not None:
        t.event(name, **attrs)


def span_completed(name: str, dur_s: float, **attrs) -> None:
    """Emit a span record for a region timed by the caller (used when
    the duration comes from the caller's own clock — e.g. the train
    loop's log-window wall time, measured across an explicit device
    sync — rather than a with-block)."""
    t = _tracer
    if t is None:
        return
    rec = {"kind": "span", "name": name, "ts": time.time() - dur_s,
           "dur_s": float(dur_s)}
    rec.update(attrs)
    t.emit(rec)


def anomaly(name: str, **attrs) -> None:
    t = _tracer
    if t is not None:
        t.anomaly(name, **attrs)


def flush() -> None:
    t = _tracer
    if t is not None:
        t.flush()


@atexit.register
def _close_at_exit() -> None:
    t = _tracer
    if t is not None:
        t.close()


# ---------------------------------------------------------------------------
# Reading (trace_main + tests)
# ---------------------------------------------------------------------------

def read_records(path: str) -> List[Dict[str, Any]]:
    """Parse one JSONL trace file; tolerates a torn final line (the
    process may have died mid-write)."""
    out: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # torn tail line from a crash — skip, keep rest
    return out
