"""Metrics registry — counters, gauges, histograms, one export format.

Before this module the repo had three one-off metric paths: the serving
aggregator (serve/metrics.py) computed percentiles with numpy, the
benchmark file logger (utils/benchmark_logger.py) wrote its own record
dicts, and the PS client counted nothing at all.  This registry is the
one API behind all of them; the export stays the existing
BenchmarkMetric record shape ({"name", "value", "unit"}), so the
benchmark infrastructure keeps consuming a single format.

Pure Python, no numpy: percentile math is implemented here with the
same linear interpolation numpy's default uses (asserted equal in
tests/test_obs.py), because the PS client and the serving engine both
run in processes where importing numpy early is fine but keeping obs
dependency-free keeps it usable from any layer.

Thread safety: every mutation takes the metric's lock.  Counters and
gauges are trivially cheap; histograms append to a bounded reservoir
(beyond ``max_samples`` a deterministic LCG picks replacement slots —
uniform reservoir sampling without seeding global RNG state).
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional


class Counter:
    """Monotonic count (requests served, sheds, pushes, ...)."""

    def __init__(self, name: str, unit: str = ""):
        self.name = name
        self.unit = unit
        self._mu = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._mu:
            self._value += n

    @property
    def value(self) -> int:
        return self._value

    def snapshot(self) -> dict:
        return {"type": "counter", "value": self._value}


class Gauge:
    """Last-written value (queue depth, slot occupancy, ...)."""

    def __init__(self, name: str, unit: str = ""):
        self.name = name
        self.unit = unit
        self._mu = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._mu:
            self._value = float(v)

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> dict:
        return {"type": "gauge", "value": self._value}


class Histogram:
    """Value distribution with percentile snapshots.

    Keeps up to ``max_samples`` observations; past that, each new value
    replaces a pseudo-uniformly chosen slot with probability
    max_samples/seen (classic reservoir sampling, deterministic LCG so
    runs are reproducible).  count/sum/min/max stay exact regardless.
    """

    PERCENTILES = (50.0, 90.0, 99.0)

    def __init__(self, name: str, unit: str = "", max_samples: int = 65536):
        self.name = name
        self.unit = unit
        self.max_samples = int(max_samples)
        self._mu = threading.Lock()
        self._samples: List[float] = []
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._lcg = 0x2545F4914F6CDD1D

    def observe(self, v: float) -> None:
        v = float(v)
        with self._mu:
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            if len(self._samples) < self.max_samples:
                self._samples.append(v)
            else:
                # reservoir: keep each of the `seen` values with equal
                # probability max_samples/seen
                self._lcg = (self._lcg * 6364136223846793005 + 1442695040888963407) % (1 << 64)
                j = self._lcg % self._count
                if j < self.max_samples:
                    self._samples[j] = v

    @property
    def count(self) -> int:
        return self._count

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0

    def reset(self) -> None:
        """Drop all observations (the instrument stays registered).
        Benches use this to exclude warmup/compile traffic from the
        measured distribution — the engine's references stay live,
        unlike MetricsRegistry.reset() which drops the instruments."""
        with self._mu:
            self._samples.clear()
            self._count = 0
            self._sum = 0.0
            self._min = math.inf
            self._max = -math.inf

    def percentile(self, q: float) -> float:
        """Linear-interpolated percentile over the reservoir — the same
        definition as numpy.percentile's default method."""
        with self._mu:
            data = sorted(self._samples)
        return percentile(data, q)

    def snapshot(self) -> dict:
        with self._mu:
            data = sorted(self._samples)
            count, total = self._count, self._sum
            lo = self._min if count else 0.0
            hi = self._max if count else 0.0
        out = {"type": "histogram", "count": count,
               "mean": (total / count if count else 0.0),
               "min": lo, "max": hi}
        for q in self.PERCENTILES:
            out[f"p{q:g}"] = percentile(data, q)
        return out


def percentile(sorted_data: List[float], q: float) -> float:
    """numpy.percentile(..., method='linear') over pre-sorted data."""
    n = len(sorted_data)
    if n == 0:
        return 0.0
    if n == 1:
        return float(sorted_data[0])
    pos = (q / 100.0) * (n - 1)
    lo = int(math.floor(pos))
    hi = min(lo + 1, n - 1)
    frac = pos - lo
    return float(sorted_data[lo] * (1.0 - frac) + sorted_data[hi] * frac)


class MetricsRegistry:
    """Name → metric, get-or-create, one export.

    ``counter/gauge/histogram`` return the existing instrument when the
    name is already registered (and raise if it is registered as a
    different type — a silent type morph would corrupt the export)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get_or_create(self, cls, name: str, unit: str, **kw):
        with self._mu:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, unit=unit, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    def counter(self, name: str, unit: str = "") -> Counter:
        return self._get_or_create(Counter, name, unit)

    def gauge(self, name: str, unit: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, unit)

    def histogram(self, name: str, unit: str = "",
                  max_samples: int = 65536) -> Histogram:
        return self._get_or_create(Histogram, name, unit,
                                   max_samples=max_samples)

    def get(self, name: str):
        return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._mu:
            return sorted(self._metrics)

    def snapshot(self) -> Dict[str, dict]:
        """{name: metric snapshot} for logging/debug dumps."""
        with self._mu:
            items = list(self._metrics.items())
        return {name: m.snapshot() for name, m in items}

    def to_benchmark_metrics(self) -> List[dict]:
        """The existing BenchmarkMetric record format, one dict per
        scalar: counters/gauges export as themselves, histograms expand
        to ``<name>_p50/_p90/_p99/_mean`` plus ``<name>_count``."""
        out: List[dict] = []
        with self._mu:
            items = sorted(self._metrics.items())
        for name, m in items:
            snap = m.snapshot()
            if snap["type"] == "histogram":
                if not snap["count"]:
                    continue
                for q in Histogram.PERCENTILES:
                    key = f"p{q:g}"
                    out.append({"name": f"{name}_{key}",
                                "value": snap[key], "unit": m.unit})
                out.append({"name": f"{name}_mean", "value": snap["mean"],
                            "unit": m.unit})
                out.append({"name": f"{name}_count",
                            "value": float(snap["count"]), "unit": "count"})
            else:
                out.append({"name": name, "value": float(snap["value"]),
                            "unit": m.unit})
        return out

    def reset(self) -> None:
        with self._mu:
            self._metrics.clear()


_default: Optional[MetricsRegistry] = None
_default_mu = threading.Lock()


def default_registry() -> MetricsRegistry:
    """The process-global registry (PS client counters live here;
    subsystems with a natural owner — the serve engine — carry their
    own instance instead)."""
    global _default
    with _default_mu:
        if _default is None:
            _default = MetricsRegistry()
        return _default
