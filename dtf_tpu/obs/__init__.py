"""Observability subsystem — structured tracing, metrics, watchdogs.

The reference repo's only window into a 16-process run was grepping raw
per-rank logs after the fact (SURVEY §5.3: `ps_server/log*.log`), and
until this package our reproduction was no better: the train loop, the
PS path, the launcher supervisor, and the serving engine each printed
in their own ad-hoc format.  This package gives every subsystem one
structured, near-zero-overhead vocabulary:

  trace     — JSONL span/event emitter (step, compile, checkpoint
              save/restore, PS push/pull, serve batch-form/
              prefill-chunk/decode) with wall time, rank, and step
              attributes.  Summarize with
              `python -m dtf_tpu.cli.trace_main <trace_dir>`.
  registry  — counters / gauges / histograms with percentile
              snapshots, exported in the existing BenchmarkMetric
              record format ({"name","value","unit"}) so the benchmark
              infrastructure keeps consuming one shape.
  watchdog  — anomaly detectors wired into the train loop: NaN/Inf
              loss (loud structured abort), step-time regression
              (rolling-median × factor), and heartbeat files the
              launcher supervisor consumes instead of scraping stdout.
  ledger    — always-on MFU/cost accounting: each jitted executable's
              XLA flop/byte counts (pulled at compile time from the
              AOT executable the caller then runs) joined with
              measured wall time into achieved-FLOP/s, MFU, and
              HBM-bandwidth-fraction gauges; summarized by
              `trace_main --ledger`.

Everything is pure Python and off-device: instrumentation runs on the
host at step boundaries only, and every entry point is a no-op when
tracing is not configured (bounded by tests/test_obs.py's <5% overhead
assertion on a smoke-train step).
"""

from dtf_tpu.obs import trace
from dtf_tpu.obs.ledger import Ledger
from dtf_tpu.obs.registry import (Counter, Gauge, Histogram,
                                  MetricsRegistry, default_registry)
from dtf_tpu.obs.watchdog import (Heartbeat, NanLossWatchdog,
                                  ReaderLagWatchdog, StepTimeWatchdog,
                                  TrainingAnomaly)

__all__ = [
    "trace",
    "Counter", "Gauge", "Histogram", "Ledger", "MetricsRegistry",
    "default_registry",
    "Heartbeat", "NanLossWatchdog", "ReaderLagWatchdog",
    "StepTimeWatchdog", "TrainingAnomaly",
]
