"""Anomaly watchdogs for the training loop + launcher heartbeat.

Three detectors, all host-side and cadence-aligned with work the loop
already does (the NaN check reads the loss value the loop already
device_get()s at --log_steps cadence — no extra device sync is ever
introduced):

  NanLossWatchdog   — a non-finite loss is never recoverable for the
      run (dynamic loss *scaling* handles transient non-finite GRADS
      inside the compiled step; a NaN LOSS that reached the host means
      the model state itself is poisoned).  The watchdog emits a
      structured ``nan_loss`` anomaly record, flushes the trace, and
      raises :class:`TrainingAnomaly` — a loud, attributable abort
      instead of a run that burns its remaining budget training on NaNs
      (the reference could only discover this grepping logs after the
      fact).

  StepTimeWatchdog  — flags a log-window whose wall time exceeds
      ``factor`` × the rolling median of recent windows: the signature
      of a degrading input pipeline, a thrashing host, or a slow
      straggler rank.  Reports (anomaly record + log line), does not
      abort — slowness is a page, not a poison.

  Heartbeat         — atomically rewrites a small JSON file
      (``heartbeat_rank{N}.json``) with {ts, step, pid} at a bounded
      interval.  The launcher supervisor consumes the file's content
      instead of scraping stdout log sizes — a rank that logs nothing
      for minutes (XLA compile) but beats is alive; a rank whose log
      grows from a chatty library thread while the training thread is
      deadlocked is NOT.
"""

from __future__ import annotations

import json
import math
import os
import statistics
import time
from collections import deque
from typing import Optional

import logging

from dtf_tpu.obs import trace

log = logging.getLogger("dtf_tpu")

HEARTBEAT_DIR_ENV = "DTF_HEARTBEAT_DIR"


def heartbeat_path(directory: str, rank: int) -> str:
    return os.path.join(directory, f"heartbeat_rank{rank}.json")


class TrainingAnomaly(RuntimeError):
    """Structured training abort.  ``record`` carries the same dict the
    tracer logged, so supervisors can consume the reason without
    parsing the message string."""

    def __init__(self, record: dict):
        self.record = dict(record)
        name = self.record.get("name", "anomaly")
        detail = {k: v for k, v in self.record.items()
                  if k not in ("kind", "name", "ts", "rank")}
        super().__init__(f"training anomaly: {name} {detail}")


class NanLossWatchdog:
    """Raise on the first non-finite loss that reaches the host."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled

    def check(self, step: int, loss: float) -> None:
        if not self.enabled:
            return
        loss = float(loss)
        if math.isfinite(loss):
            return
        record = {"kind": "anomaly", "name": "nan_loss", "ts": time.time(),
                  "step": int(step), "loss": repr(loss)}
        trace.anomaly("nan_loss", step=int(step), loss=repr(loss))
        log.error("NaN watchdog: loss=%r at step %d — aborting the run "
                  "(a non-finite loss on the host means poisoned model "
                  "state, not a transient overflow)", loss, step)
        raise TrainingAnomaly(record)


class StepTimeWatchdog:
    """Rolling-median regression detector over per-window step times.

    ``observe(step, window_s)`` returns True (and emits an anomaly
    record) when ``window_s`` > factor × median of the last ``window``
    observations, once at least ``warmup`` baseline windows exist.  The
    triggering value is NOT added to the baseline — a genuine
    regression must keep triggering, not drag the median up until it
    looks normal."""

    def __init__(self, factor: float = 3.0, window: int = 32,
                 warmup: int = 5):
        if factor <= 1.0:
            raise ValueError(f"factor must be > 1.0, got {factor}")
        self.factor = float(factor)
        self.warmup = max(int(warmup), 1)
        self._history: deque = deque(maxlen=max(int(window), self.warmup))
        self.trigger_count = 0

    def observe(self, step: int, window_s: float) -> bool:
        window_s = float(window_s)
        if len(self._history) >= self.warmup:
            median = statistics.median(self._history)
            if median > 0 and window_s > self.factor * median:
                self.trigger_count += 1
                trace.anomaly("step_time_regression", step=int(step),
                              window_s=window_s, median_s=median,
                              factor=self.factor)
                log.warning(
                    "step-time watchdog: window ending at step %d took "
                    "%.3fs vs rolling median %.3fs (>%gx) — input "
                    "pipeline stall, host thrash, or straggler rank",
                    step, window_s, median, self.factor)
                return True
        self._history.append(window_s)
        return False


class ReaderLagWatchdog:
    """Report-only input-stall detector over per-batch reader lag.

    The data service (dtf_tpu/data/service) reports how long the
    consumer blocked waiting for each merged batch; this watchdog flags
    a lag exceeding ``factor`` × the rolling median of recent batches —
    AND an absolute floor ``min_lag_s``, so microsecond-scale jitter on
    a well-fed pipeline can never page — with a structured
    ``reader_lag`` anomaly.  Reports, never aborts: a starving device
    is a provisioning problem (add input workers/cores), not a poisoned
    run.  Same shape as StepTimeWatchdog: the triggering value is not
    added to the baseline, so a genuine stall keeps triggering."""

    def __init__(self, factor: float = 10.0, min_lag_s: float = 0.5,
                 window: int = 64, warmup: int = 8):
        if factor <= 1.0:
            raise ValueError(f"factor must be > 1.0, got {factor}")
        self.factor = float(factor)
        self.min_lag_s = float(min_lag_s)
        self.warmup = max(int(warmup), 1)
        self._history: deque = deque(maxlen=max(int(window), self.warmup))
        self.trigger_count = 0

    def observe(self, batch: int, lag_s: float) -> bool:
        lag_s = float(lag_s)
        if len(self._history) >= self.warmup and lag_s > self.min_lag_s:
            median = statistics.median(self._history)
            if lag_s > self.factor * max(median, 1e-9):
                self.trigger_count += 1
                trace.anomaly("reader_lag", batch=int(batch),
                              lag_s=lag_s, median_s=median,
                              factor=self.factor)
                log.warning(
                    "reader-lag watchdog: batch %d waited %.3fs on the "
                    "input pipeline vs rolling median %.4fs (>%gx) — "
                    "the device is input-starved; add data-service "
                    "workers or host cores", batch, lag_s, median,
                    self.factor)
                return True
        self._history.append(lag_s)
        return False


class Heartbeat:
    """Liveness file the launcher supervisor watches.

    ``beat()`` is safe to call every step: it reads one monotonic clock
    and returns unless ``interval_s`` elapsed, then atomically rewrites
    the file (tmp + rename — the supervisor never sees a torn JSON)."""

    def __init__(self, path: str, interval_s: float = 5.0):
        self.path = os.path.abspath(path)
        self.interval_s = float(interval_s)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._last = 0.0
        self.beat(step=None, force=True)

    @classmethod
    def from_env(cls, rank: Optional[int] = None,
                 interval_s: float = 5.0) -> Optional["Heartbeat"]:
        """The launcher exports DTF_HEARTBEAT_DIR to every rank; a run
        started any other way gets None (no file, no cost)."""
        directory = os.environ.get(HEARTBEAT_DIR_ENV, "")
        if not directory:
            return None
        if rank is None:
            rank = int(os.environ.get("DTF_PROCESS_ID", "0"))
        return cls(heartbeat_path(directory, rank), interval_s=interval_s)

    def beat(self, step: Optional[int] = None, force: bool = False) -> bool:
        now = time.monotonic()
        if not force and now - self._last < self.interval_s:
            return False
        # chaos heartbeat_stall@step:N: silently stop writing from step
        # N on — the deadlocked-but-alive signature the supervisor's
        # heartbeat watchdog must catch.  Lazy import (obs/__init__
        # imports this module; chaos imports obs.trace — importing
        # chaos at module top would cycle through the package init).
        from dtf_tpu import chaos
        if chaos.heartbeat_stalled(step):
            return False
        self._last = now
        payload = {"ts": time.time(), "step": step, "pid": os.getpid()}
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, self.path)
        except OSError as e:
            # never crash training over liveness reporting — but be
            # loud: once a rank has beaten, the supervisor trusts ONLY
            # heartbeats (log growth stops counting, by design — the
            # chatty-deadlock case), so persistent write failures here
            # (ENOSPC, deleted log_dir) will get this rank killed after
            # heartbeat_timeout
            log.warning("heartbeat write failed (%s) — if this persists "
                        "the supervisor will judge this rank dead in "
                        "~heartbeat_timeout", e)
            return False
        trace.event("heartbeat", step=step)
        return True


def read_heartbeat(path: str) -> Optional[dict]:
    """Parse a heartbeat file; None when missing/torn (the supervisor
    treats that as 'no heartbeat signal', not as death)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
