"""Live scrape endpoint — the obs registry as Prometheus text format.

The registry already exports post-run snapshots through the
BenchmarkMetric file logger; long runs also want a LIVE window: curl
rank 0 mid-run and see reader lag, cache hit ratio, step time.  This
module is the minimal stdlib answer — `http.server` on a daemon
thread, one handler, text-format v0.0.4 — not a prometheus_client
dependency.

Mapping (names pass through; the repo already uses snake_case with
embedded units, e.g. ``data_reader_lag_s``):

  Counter    -> `# TYPE <name> counter` + one sample
  Gauge      -> `# TYPE <name> gauge` + one sample
  Histogram  -> `# TYPE <name> summary`: quantile series from the
                registry's reservoir percentiles, plus <name>_sum /
                <name>_count

Scrape surface: ``GET /metrics`` (and ``/`` as an alias) plus
``GET /healthz`` — a JSON liveness probe for external health checkers
(k8s-style): 200 ``{"ok": true, ...}`` while healthy, 503 when the
optional ``health_fn`` reports ``ok: false`` (a draining replica, a
router whose every replica is lost).  An HA router's payload also
carries its posture — ``role`` (``leader``/``standby``), the fencing
``epoch``, and ``fenced`` — so external probes can watch a standby
takeover happen (Router.health / serve.ha.standby_health feed it
through cli/router_main's health_fn).  The registry is re-snapshotted
per request — the server holds a callable, not a frozen snapshot, so
`MetricsRegistry.reset()` between runs in one process is reflected
immediately; ``health_fn`` is likewise re-evaluated per probe.
"""

from __future__ import annotations

import json
import logging
import math
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from dtf_tpu.obs.registry import (Histogram, MetricsRegistry,
                                  default_registry)

log = logging.getLogger("dtf_tpu")

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _sample(value: float) -> str:
    """Prometheus sample value formatting (+Inf/-Inf/NaN spellings)."""
    v = float(value)
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(v)


def prometheus_text(registry: MetricsRegistry) -> str:
    """The registry as a Prometheus text-format exposition string."""
    lines = []
    snap = registry.snapshot()
    for name in sorted(snap):
        s = snap[name]
        kind = s["type"]
        if kind == "histogram":
            lines.append(f"# TYPE {name} summary")
            for q in Histogram.PERCENTILES:
                lines.append(
                    f'{name}{{quantile="{q / 100:g}"}} '
                    f'{_sample(s[f"p{q:g}"])}')
            lines.append(
                f"{name}_sum {_sample(s['mean'] * s['count'])}")
            lines.append(f"{name}_count {s['count']}")
        else:
            lines.append(f"# TYPE {name} {kind}")
            lines.append(f"{name} {_sample(s['value'])}")
    return "\n".join(lines) + "\n"


class MetricsServer:
    """`GET /metrics` over stdlib ThreadingHTTPServer, daemon threads.

    ``port=0`` binds an ephemeral port (tests); the bound port is
    ``.port``.  ``registry_fn`` defaults to the process-global default
    registry, resolved per request.  ``health_fn`` (optional) returns a
    dict merged into the ``/healthz`` JSON; ``{"ok": False, ...}``
    turns the probe into a 503 so external checkers (and the chaos
    matrix) can distinguish alive-but-degraded from healthy."""

    def __init__(self, port: int,
                 registry_fn: Optional[Callable[[], MetricsRegistry]]
                 = None, host: str = "",
                 health_fn: Optional[Callable[[], dict]] = None):
        registry_fn = registry_fn or default_registry

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server contract
                path = self.path.split("?")[0]
                if path == "/healthz":
                    payload = {"ok": True}
                    if health_fn is not None:
                        try:
                            payload.update(health_fn() or {})
                        except Exception as e:  # noqa: BLE001 — a probe
                            # must answer, not 500 into a flapping check
                            payload = {"ok": False, "error": str(e)}
                    body = (json.dumps(payload) + "\n").encode()
                    self.send_response(200 if payload.get("ok", True)
                                       else 503)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path not in ("/", "/metrics"):
                    self.send_error(404)
                    return
                body = prometheus_text(registry_fn()).encode()
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):  # scrapes are not news
                log.debug("metrics server: " + fmt, *args)

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.5},
            daemon=True, name="dtf-metrics-server")
        self._thread.start()
        log.info("metrics server: serving Prometheus text on port %d "
                 "(GET /metrics)", self.port)

    def shutdown(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)
