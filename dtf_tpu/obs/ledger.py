"""Always-on MFU/cost ledger — per-executable FLOPs, bytes, and
achieved-utilization gauges.

bench_profile.py proved the attribution method offline: XLA's own
``compiled.cost_analysis()`` (flops, bytes accessed) for exactly the
program that runs, divided by measured wall time, against the chip's
peak FLOP/s and HBM bandwidth.  This module makes the same accounting
LIVE: the train loop and the serving decoder register each jitted
executable at compile time (the AOT ``lower().compile()`` object they
then EXECUTE — cost analysis is free, nothing compiles twice), feed it
their already-measured wall times, and the ledger exports

  ledger_<exec>_flops            gauge   XLA flop count (per device)
  ledger_<exec>_bytes            gauge   XLA bytes accessed (per device)
  ledger_<exec>_wall_s           gauge   running-mean measured wall time
  ledger_<exec>_calls            gauge   observations folded in
  ledger_<exec>_achieved_tflops  gauge   flops / mean wall / 1e12
  ledger_<exec>_mfu              gauge   achieved / peak FLOP/s
  ledger_<exec>_hbm_frac         gauge   achieved bytes/s / peak HBM b/s

into whatever registry owns the subsystem (the engine's
``engine.metrics``, train's default registry) — scraped live via the
Prometheus endpoint (``--metrics_port``), exported post-run through
``BenchmarkFileLogger.log_registry``.  Registration and summaries also
land in the trace stream (``ledger_exec`` / ``ledger_summary`` events),
so ``trace_main --ledger`` renders the table from trace files alone.

Peaks come from the device kind (the same public-spec tables bench.py
and bench_profile.py carry); unknown kinds (CPU) export no mfu/hbm_frac
rather than a made-up number.  ``DTF_PEAK_TFLOPS`` / ``DTF_PEAK_HBM_GBPS``
override both — deterministic tests, and chips the table hasn't learned.

Accuracy contract (documented tolerance): the train-step wall time is
the log-window mean (sync-inclusive, measured across a device_get), so
ledger MFU sits within ~20% of bench.py's sync-cancelled-window MFU —
host dispatch overhead is IN the ledger's number, deliberately (it is
utilization the run actually achieves, not the kernel's best case).
Chunked-prefill entries are per chunk SHAPE; on the gather path several
window variants share one name and the latest compile's counts stand
for the family (serving's headline is the decode-step entry).
"""

from __future__ import annotations

import logging
import math
import os
import threading
from typing import Dict, Optional

from dtf_tpu.obs import trace
from dtf_tpu.obs.registry import MetricsRegistry, default_registry

log = logging.getLogger("dtf_tpu")

# Public-spec peaks by TPU generation, matched case-insensitively
# against jax device_kind — the same numbers bench.py (bf16 TFLOP/s)
# and bench_profile.py (HBM GB/s) carry; kept here as literals because
# obs must import without the bench scripts on sys.path (parity pinned
# by tests/test_obs.py).
PEAK_BF16_TFLOPS = {
    "v6e": 918.0, "v6": 918.0,
    "v5p": 459.0,
    "v5 lite": 197.0, "v5e": 197.0, "v5litepod": 197.0,
    "v4": 275.0,
    "v3": 123.0,
    "v2": 45.0,
}
PEAK_HBM_GBPS = {
    "v5 lite": 819.0, "v5e": 819.0, "v4": 1228.0, "v5p": 2765.0,
    "v6e": 1640.0,
}


def _lookup(table: dict, kind: str) -> Optional[float]:
    kind = kind.lower()
    for key, val in table.items():
        if key in kind:
            return val
    return None


def device_peaks() -> tuple:
    """(peak FLOP/s, peak HBM bytes/s) of the attached device — or
    (None, None) when unknown.  Env overrides DTF_PEAK_TFLOPS /
    DTF_PEAK_HBM_GBPS win (tests, unlisted chips); jax is imported
    lazily and failures degrade to unknown, never to a crash."""
    tflops = os.environ.get("DTF_PEAK_TFLOPS", "")
    gbps = os.environ.get("DTF_PEAK_HBM_GBPS", "")
    peak_f = float(tflops) * 1e12 if tflops else None
    peak_b = float(gbps) * 1e9 if gbps else None
    if peak_f is None or peak_b is None:
        try:
            import jax
            kind = getattr(jax.devices()[0], "device_kind", "")
        except Exception:  # noqa: BLE001 — diagnostics never crash a run
            kind = ""
        if peak_f is None:
            t = _lookup(PEAK_BF16_TFLOPS, kind)
            peak_f = t * 1e12 if t else None
        if peak_b is None:
            g = _lookup(PEAK_HBM_GBPS, kind)
            peak_b = g * 1e9 if g else None
    return peak_f, peak_b


def cost_of(compiled) -> tuple:
    """(flops, bytes accessed) from a compiled executable's
    cost_analysis — the bench_profile.py extraction, shared."""
    ca = compiled.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
    return (float(ca.get("flops", 0.0) or 0.0),
            float(ca.get("bytes accessed", 0.0) or 0.0))


class Ledger:
    """Per-executable cost ledger over one metrics registry.

    ``register(name, compiled=...)`` once per executable at compile
    time; ``observe(name, wall_s)`` with each measured wall time the
    caller already has (decode steps sync per step; the train loop's
    log windows span a real device sync).  ``emit_summary()`` flushes
    one ``ledger_summary`` trace event per executable — call it at
    run/engine teardown so ``trace_main --ledger`` works from the
    trace directory alone."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry if registry is not None \
            else default_registry()
        self._mu = threading.Lock()
        self._execs: Dict[str, dict] = {}
        self.peak_flops, self.peak_hbm = device_peaks()

    def register(self, name: str, compiled=None, flops: float = 0.0,
                 bytes_accessed: float = 0.0) -> None:
        """Record an executable's static cost.  ``compiled`` is an AOT
        ``lower().compile()`` object (cost pulled from XLA); without
        one, pass the counts directly.  Re-registering the same name
        (gather-path chunk window variants) updates the counts and
        keeps the accumulated timing."""
        if compiled is not None:
            try:
                flops, bytes_accessed = cost_of(compiled)
            except Exception as e:  # noqa: BLE001 — a backend without
                # cost_analysis must not take down the step it measures
                log.debug("ledger: cost_analysis unavailable for %s (%s)",
                          name, e)
                return
        with self._mu:
            e = self._execs.get(name)
            if e is None:
                e = self._execs[name] = {"flops": 0.0, "bytes": 0.0,
                                         "count": 0, "total_s": 0.0}
            e["flops"] = float(flops)
            e["bytes"] = float(bytes_accessed)
        self.registry.gauge(f"ledger_{name}_flops",
                            unit="flops").set(flops)
        self.registry.gauge(f"ledger_{name}_bytes",
                            unit="bytes").set(bytes_accessed)
        trace.event("ledger_exec", exec=name, flops=float(flops),
                    bytes=float(bytes_accessed),
                    peak_tflops=(self.peak_flops / 1e12
                                 if self.peak_flops else None),
                    peak_hbm_gbps=(self.peak_hbm / 1e9
                                   if self.peak_hbm else None))

    def observe(self, name: str, wall_s: float) -> None:
        """Fold one measured wall time into the executable's gauges.
        Unregistered names and non-positive times are ignored (the
        caller's timing sites outlive registration failures)."""
        if not wall_s or wall_s <= 0 or not math.isfinite(wall_s):
            return
        with self._mu:
            e = self._execs.get(name)
            if e is None:
                return
            e["count"] += 1
            e["total_s"] += float(wall_s)
            mean = e["total_s"] / e["count"]
            flops, nbytes, count = e["flops"], e["bytes"], e["count"]
        g = self.registry.gauge
        g(f"ledger_{name}_wall_s", unit="s").set(mean)
        g(f"ledger_{name}_calls", unit="calls").set(count)
        achieved = flops / mean if mean > 0 else 0.0
        g(f"ledger_{name}_achieved_tflops",
          unit="tflops").set(achieved / 1e12)
        if self.peak_flops:
            g(f"ledger_{name}_mfu", unit="mfu").set(
                achieved / self.peak_flops)
        if self.peak_hbm and mean > 0:
            g(f"ledger_{name}_hbm_frac", unit="fraction").set(
                nbytes / mean / self.peak_hbm)

    def summary(self) -> Dict[str, dict]:
        """{exec: {flops, bytes, count, mean_s, achieved_tflops, mfu,
        hbm_frac}} — mfu/hbm_frac None when the peak is unknown."""
        out: Dict[str, dict] = {}
        with self._mu:
            items = sorted(self._execs.items())
        for name, e in items:
            mean = e["total_s"] / e["count"] if e["count"] else 0.0
            achieved = e["flops"] / mean if mean > 0 else 0.0
            out[name] = {
                "flops": e["flops"], "bytes": e["bytes"],
                "count": e["count"], "mean_s": mean,
                "achieved_tflops": achieved / 1e12,
                "mfu": (achieved / self.peak_flops
                        if self.peak_flops and mean > 0 else None),
                "hbm_frac": (e["bytes"] / mean / self.peak_hbm
                             if self.peak_hbm and mean > 0 else None),
            }
        return out

    def emit_summary(self) -> None:
        """One ``ledger_summary`` trace event per executable — the
        record ``trace_main --ledger`` tabulates."""
        for name, s in self.summary().items():
            trace.event("ledger_summary", exec=name, **s)
