"""The trace vocabulary — single source of truth for event/anomaly
kinds.

Every record kind the subsystems emit through :mod:`dtf_tpu.obs.trace`
is registered HERE, and only here.  Two consumers enforce closure in
both directions:

  - ``cli/trace_main.py`` validates ``--allow <kind>`` arguments
    against this registry (a typo'd --allow that silently tolerates
    nothing is exactly the bug an expected-anomaly list invites);
  - ``tools/dtflint`` (rule ``trace-unregistered`` /
    ``trace-unemitted``) statically checks that every
    ``trace.event("...")`` / ``trace.anomaly("...")`` call site in the
    tree names a registered kind, AND that every registered kind is
    emitted somewhere — a registry entry nothing produces is dead
    vocabulary, an emission nothing registers is invisible to
    ``--allow`` and to operators reading the docs.

Keep the module dependency-free (no jax, no dtf_tpu imports): the
linter and trace_main both need it importable in a cold process.
"""

from __future__ import annotations

#: anomaly kinds the subsystems emit (docs for --allow; unknown kinds
#: only warn at trace_main — forward compatibility beats a stale
#: registry — but dtflint FAILS on an unregistered emission, so the
#: registry cannot rot while CI runs)
KNOWN_ANOMALY_KINDS = (
    "nan_loss", "step_time_regression", "reader_lag", "serve_shed",
    "ckpt_integrity", "injected_fault",
    # serving replica tier (dtf_tpu/serve/router.py)
    "router_shed", "replica_lost", "replica_give_up",
    "redispatch_divergence", "router_deadline", "mixed_model",
    # zero-downtime rollout (dtf_tpu/serve/rollout.py): the canary
    # gate's verdicts and the rollback record
    "canary_divergence", "rollout_rollback", "rollout_rollback_failed",
    # disaggregated serving's KV-page wire migration (serve/migrate.py
    # detects torn transfers; serve/router.py flags migrations that
    # never made it — an efficiency loss, never a lost request)
    "migration_torn", "migration_failed",
    # router HA (serve/ha.py + serve/replica.py): a replica rejecting
    # a superseded controller's wire op, and the superseded router
    # discovering it has been fenced off the tier
    "stale_epoch", "router_fenced",
    # train/loop.py step-site XLA failure classified as accelerator
    # loss (train/elastic.py is_device_loss) — precedes EXIT_DEVICE_LOST
    "device_lost",
)

#: event kinds of the run/request-timeline / ledger / profiler layer —
#: never anomalies, but part of the vocabulary the --allow typo check
#: validates against: `--allow serve_retire` is a harmless no-op on a
#: known name, while `--allow serve_retier` still warns loudly
KNOWN_EVENT_KINDS = (
    # tracer lifecycle (obs/trace.py stamps one per stream)
    "trace_start",
    # train loop (train/loop.py) + preemption (train/preemption.py)
    "train_loss", "train_end", "epoch_end", "preempted",
    # watchdog heartbeat records (obs/watchdog.py)
    "heartbeat",
    # async-PS client reconnect (parallel/ps.py)
    "ps_reconnect",
    # data-service supervision (data/service/pool.py)
    "reader_respawn",
    # request-scoped distributed tracing (router + serve engine)
    "router_submit", "router_dispatch", "router_requeue",
    "router_first_token", "router_complete", "router_hedge",
    "serve_submit", "serve_admit", "serve_retire", "serve_cancelled",
    # replica-tier supervision (serve/router.py)
    "replica_registered", "replica_respawn",
    # rollout lifecycle (serve/rollout.py + the router's rollout
    # control surface)
    "rollout_phase", "replica_drain", "replica_replaced",
    "canary_mirror", "canary_compare", "canary_drop", "prefix_rehome",
    # disaggregation: the router's chain re-home command + completion
    "chain_migrate", "chain_migrated",
    # MFU/cost ledger (obs/ledger.py)
    "ledger_exec", "ledger_summary",
    # ZeRO compute/comm overlap probe (train/loop.py --zero_probe)
    "zero_overlap",
    # elastic shrink/grow resume (train/elastic.py): the topology a
    # resumed attempt actually trained on
    "elastic_resume",
    # --profile_steps output-path marker (train/loop.py)
    "profiler_trace",
    # router HA takeover (serve/ha.py): a successor assumed the tier
    # under a new fencing epoch; per-request re-adoption confirmations
    # (the replica still held the retained tail)
    "router_takeover", "router_readopt",
)

#: raw chaos kinds — the ``fault_kind`` attr of ``injected_fault``
#: records, never record names themselves.  Accepted by trace_main's
#: --allow typo check (so `--allow replica_kill`-style near-misses
#: warn rather than pass) and cross-checked by dtflint against
#: dtf_tpu/chaos KINDS, but exempt from the emitted-somewhere rule.
CHAOS_FAULT_KINDS = (
    "crash", "sigterm", "heartbeat_stall", "ps_drop", "ckpt_truncate",
    "reader_crash", "replica_kill", "net_partition", "slow_replica",
    "rollout_kill", "device_loss", "host_loss", "page_fetch_stall",
    "router_kill", "lease_stall",
)

#: metric-name grammar: <subsystem>_<name>[_<unit-ish suffix>], where
#: the leading segment must be one of these subsystem prefixes
#: (dtflint rule ``metric-grammar``)
METRIC_SUBSYSTEMS = ("data", "ps", "router", "serve", "plan", "train",
                     "ledger")


def allowable_kinds() -> frozenset:
    """Every name ``trace_main --allow`` accepts without a typo
    warning."""
    return frozenset(KNOWN_ANOMALY_KINDS) | frozenset(KNOWN_EVENT_KINDS) \
        | frozenset(CHAOS_FAULT_KINDS)
