from dtf_tpu.utils.logs import TimeHistory, BatchTimestamp, build_stats  # noqa: F401
