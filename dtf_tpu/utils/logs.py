"""Observability: BenchmarkMetric lines + run-stats normalization.

Parity targets (SURVEY §5.5):
  (a) `keras_utils.TimeHistory` — every `log_steps` steps emit
      "BenchmarkMetric: {'global step':N, 'time_taken': …,
      'examples_per_second': …}" plus per-epoch wall time (log evidence
      ps_server/log1.log, emitted at keras_utils.py:85,93).
  (b) `common.build_stats` (common.py:202-245) — the final dict a run
      returns: loss, training_accuracy_top_1, accuracy_top_1,
      eval_loss, step_timestamp_log, train_finish_time,
      avg_exp_per_second.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

log = logging.getLogger("dtf_tpu")


class BatchTimestamp:
    """Parity with keras_utils.BatchTimestamp."""

    def __init__(self, batch_index: int, timestamp: float):
        self.batch_index = batch_index
        self.timestamp = timestamp

    def __repr__(self):
        return f"'BatchTimestamp<batch_index: {self.batch_index}, timestamp: {self.timestamp}>'"


class TimeHistory:
    """Step/epoch timing with the reference's exact log cadence."""

    def __init__(self, batch_size: int, log_steps: int,
                 initial_global_step: int = 0):
        self.batch_size = batch_size      # global batch size
        self.log_steps = log_steps
        self.global_steps = initial_global_step  # continues across resume
        self.timestamp_log = []
        self.train_finish_time: Optional[float] = None
        self._step_start: Optional[float] = None
        self._epoch_start: Optional[float] = None

    def on_train_begin(self, logs=None):
        # reference logs the first timestamp at train start (step 0 entry
        # comes from the first on_batch_begin)
        pass

    def on_epoch_begin(self, epoch: int, logs=None):
        self._epoch_start = time.time()

    def on_batch_begin(self, batch: int, logs=None):
        self.global_steps += 1
        if self._step_start is None:
            # first batch of this run — which on a resumed run is NOT
            # global step 1 (r1 crashed here: now - None at the first
            # BenchmarkMetric line after a checkpoint restore)
            self._step_start = time.time()
            self.timestamp_log.append(
                BatchTimestamp(self.global_steps, self._step_start))

    def on_batch_end(self, batch: int, logs=None):
        if self.global_steps % self.log_steps == 0:
            now = time.time()
            elapsed = now - self._step_start
            examples_per_second = (self.batch_size * self.log_steps) / elapsed
            self.timestamp_log.append(BatchTimestamp(self.global_steps, now))
            log.info(
                "BenchmarkMetric: {'global step':%d, 'time_taken': %f,"
                "'examples_per_second': %f}",
                self.global_steps, elapsed, examples_per_second)
            self._step_start = now

    def on_epoch_end(self, epoch: int, logs=None):
        epoch_run_time = time.time() - self._epoch_start
        log.info("BenchmarkMetric: {'epoch':%d, 'time_taken': %f}",
                 epoch, epoch_run_time)

    def on_train_end(self, logs=None):
        self.train_finish_time = time.time()


def build_stats(history: dict, eval_output, time_callback: Optional[TimeHistory]
                ) -> dict:
    """Normalize final results — key-for-key with common.build_stats.

    `history` is {'loss': [...], 'categorical_accuracy': [...]} or the
    sparse variant; `eval_output` is (eval_loss, accuracy_top_1) or None.
    """
    stats: dict = {}
    if eval_output:
        if eval_output[1] is not None:  # --report_accuracy_metrics false
            stats["accuracy_top_1"] = float(eval_output[1])
        stats["eval_loss"] = float(eval_output[0])
    if history and history.get("loss"):
        stats["loss"] = float(history["loss"][-1])
        for key in ("categorical_accuracy", "sparse_categorical_accuracy"):
            if history.get(key):
                stats["training_accuracy_top_1"] = float(history[key][-1])
                break
    if time_callback is not None:
        timestamp_log = time_callback.timestamp_log
        stats["step_timestamp_log"] = timestamp_log
        stats["train_finish_time"] = time_callback.train_finish_time
        if len(timestamp_log) > 1:
            stats["avg_exp_per_second"] = (
                time_callback.batch_size * time_callback.log_steps *
                (len(timestamp_log) - 1) /
                (timestamp_log[-1].timestamp - timestamp_log[0].timestamp))
    return stats
