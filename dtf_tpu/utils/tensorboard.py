"""TensorBoard scalar writer — event files from first principles.

Parity with the reference's `--enable_tensorboard` →
`tf.keras.callbacks.TensorBoard(log_dir=model_dir)` (common.py:187-190),
without TensorFlow: an Event protobuf is hand-serialized (the wire
format is tiny — wall_time, step, Summary{tag, simple_value}) and
framed with the TFRecord framing records.py already owns.  Files are
readable by stock TensorBoard.
"""

from __future__ import annotations

import os
import socket
import struct
import time

from dtf_tpu.data.records import _len_delim, _varint, masked_crc32c


def _double_field(field: int, value: float) -> bytes:
    return _varint(field << 3 | 1) + struct.pack("<d", value)


def _float_field(field: int, value: float) -> bytes:
    return _varint(field << 3 | 5) + struct.pack("<f", value)


def _varint_field(field: int, value: int) -> bytes:
    return _varint(field << 3 | 0) + _varint(value)


def _event(wall_time: float, step: int = 0, summary: bytes = b"",
           file_version: str = "") -> bytes:
    out = _double_field(1, wall_time) + _varint_field(2, step)
    if file_version:
        out += _len_delim(3, file_version.encode())
    if summary:
        out += _len_delim(5, summary)
    return out


class SummaryWriter:
    """Append-only scalar event writer for one log dir."""

    def __init__(self, log_dir: str):
        os.makedirs(log_dir, exist_ok=True)
        fname = (f"events.out.tfevents.{int(time.time())}."
                 f"{socket.gethostname()}")
        self._f = open(os.path.join(log_dir, fname), "ab")
        self._write(_event(time.time(), file_version="brain.Event:2"))

    def _write(self, payload: bytes) -> None:
        header = struct.pack("<Q", len(payload))
        self._f.write(header)
        self._f.write(struct.pack("<I", masked_crc32c(header)))
        self._f.write(payload)
        self._f.write(struct.pack("<I", masked_crc32c(payload)))

    def scalar(self, tag: str, value: float, step: int) -> None:
        value_msg = _len_delim(1, tag.encode()) + _float_field(2, float(value))
        summary = _len_delim(1, value_msg)
        self._write(_event(time.time(), step=step, summary=summary))

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.close()


class TensorBoardCallback:
    """Writes per-epoch train metrics + eval results as scalars."""

    def __init__(self, model_dir: str):
        self.writer = SummaryWriter(os.path.join(model_dir, "train"))

    def on_epoch_end(self, epoch: int, logs=None):
        if not logs:
            return
        history = logs.get("history") or {}
        state = logs.get("state")
        step = int(state.step) if state is not None else epoch
        for key, series in history.items():
            if series:
                self.writer.scalar(f"epoch_{key}", series[-1], step)
        self.writer.flush()

    def on_train_end(self, logs=None):
        self.writer.close()
