"""Benchmark run/metric file logging.

Parity target: `official.utils.logs.logger.benchmark_context(FLAGS)`
(reference resnet_cifar_main.py:234, SURVEY §5.5c) — when
`--benchmark_log_dir` is set, the run is wrapped in a context that
writes two files the benchmark infrastructure consumes:

  benchmark_run.log — one JSON object of run metadata (model, dataset,
      run parameters, machine info, run date, test id)
  metric.log        — one JSON line per recorded metric:
      {"name", "value", "unit", "global_step", "timestamp", "extras"}

With no log dir the context is a no-op, matching the reference's
BaseBenchmarkLogger fallback.
"""

from __future__ import annotations

import contextlib
import datetime
import json
import logging
import os
from typing import Optional

import jax

log = logging.getLogger("dtf_tpu")

_RUN_FILE = "benchmark_run.log"
_METRIC_FILE = "metric.log"


def _utcnow() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%S.%fZ")


class BenchmarkFileLogger:
    """Writes benchmark_run.log + metric.log under `log_dir`."""

    def __init__(self, log_dir: str):
        self.log_dir = os.path.abspath(log_dir)
        os.makedirs(self.log_dir, exist_ok=True)
        self._metric_path = os.path.join(self.log_dir, _METRIC_FILE)

    def log_run_info(self, model_name: str, dataset_name: str,
                     run_params: dict, test_id: str = "") -> None:
        devices = jax.devices()
        info = {
            "model_name": model_name,
            "dataset": {"name": dataset_name},
            "machine_config": {
                "platform": devices[0].platform if devices else "unknown",
                "device_kind": devices[0].device_kind if devices else "unknown",
                "device_count": len(devices),
                "process_count": jax.process_count(),
            },
            "run_date": _utcnow(),
            "jax_version": {"version": jax.__version__},
            "run_parameters": _jsonable(run_params),
            "test_id": test_id or None,
        }
        path = os.path.join(self.log_dir, _RUN_FILE)
        with open(path, "w") as f:
            json.dump(info, f, indent=2)
            f.write("\n")

    def log_metric(self, name: str, value, unit: Optional[str] = None,
                   global_step: Optional[int] = None,
                   extras: Optional[dict] = None) -> None:
        try:
            value = float(value)
        except (TypeError, ValueError):
            log.warning("benchmark metric %r has non-numeric value %r — "
                        "skipped", name, value)
            return
        record = {
            "name": name,
            "value": value,
            "unit": unit,
            "global_step": global_step,
            "timestamp": _utcnow(),
            "extras": [{"name": k, "value": str(v)}
                       for k, v in (extras or {}).items()],
        }
        with open(self._metric_path, "a") as f:
            f.write(json.dumps(record) + "\n")

    def log_stats(self, stats: dict, global_step: Optional[int] = None) -> None:
        """Record a run's final stats dict (build_stats output) as metrics."""
        for key in ("loss", "training_accuracy_top_1", "accuracy_top_1",
                    "eval_loss", "avg_exp_per_second"):
            if key in stats and stats[key] is not None:
                self.log_metric(key, stats[key], global_step=global_step)

    def log_serving_stats(self, serving_stats) -> None:
        """Record a serving run (serve.metrics.ServingStats) in the same
        metric.log format — one line per latency/throughput metric."""
        for rec in serving_stats.to_metrics():
            self.log_metric(rec["name"], rec["value"], unit=rec["unit"])

    def log_registry(self, registry,
                     global_step: Optional[int] = None) -> None:
        """Record an obs.MetricsRegistry's contents: counters/gauges as
        themselves, histograms expanded to percentile scalars — every
        line still the one BenchmarkMetric record shape."""
        for rec in registry.to_benchmark_metrics():
            self.log_metric(rec["name"], rec["value"], unit=rec["unit"],
                            global_step=global_step)


def _jsonable(obj):
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        if isinstance(obj, dict):
            return {k: _jsonable(v) for k, v in obj.items()}
        return str(obj)


@contextlib.contextmanager
def benchmark_context(cfg):
    """Wraps a run: yields a BenchmarkFileLogger (or None when
    benchmark logging is off / this is not the coordinator process)."""
    if cfg.benchmark_log_dir and jax.process_index() == 0:
        logger = BenchmarkFileLogger(cfg.benchmark_log_dir)
        logger.log_run_info(cfg.model, cfg.dataset, cfg.to_dict(),
                            test_id=cfg.benchmark_test_id)
        yield logger
    else:
        yield None
