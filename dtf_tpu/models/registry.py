"""Model registry + the L2-as-loss-term rule.

The reference applies L2 through Keras kernel_regularizers, which fold
into the loss (SURVEY §7 hard-part 5; resnet_model.py:37-43).  Here the
same behavior is a pure function over the param pytree: every 'kernel'
leaf is penalized, plus the final classifier's bias (the reference sets
bias_regularizer only on fc1000/fc10 — resnet_model.py:378-380,
resnet_cifar_model.py:250-251).  BatchNorm scale/bias are never
penalized, matching Keras.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

import functools

from dtf_tpu.models import (moe, pipeline_lm, resnet, resnet_cifar,
                            transformer, trivial)

# reference weight-decay constants
L2_IMAGENET = 1e-4  # resnet_model.py:37
L2_CIFAR = 2e-4     # resnet_cifar_model.py:36

_REGISTRY = {
    "resnet50": (resnet.ResNet50, 1001, L2_IMAGENET),
    "resnet20": (resnet_cifar.resnet20, 10, L2_CIFAR),
    "resnet32": (resnet_cifar.resnet32, 10, L2_CIFAR),
    "resnet56": (resnet_cifar.resnet56, 10, L2_CIFAR),
    "resnet110": (resnet_cifar.resnet110, 10, L2_CIFAR),
    "resnet662": (resnet_cifar.resnet662, 10, L2_CIFAR),
    "trivial": (trivial.TrivialModel, 1001, 0.0),
    # LM family (no L2: the reference's weight-decay rule is ResNet-only)
    "transformer": (transformer.TransformerLM, 32_768, 0.0),
    "transformer_small": (
        functools.partial(transformer.TransformerLM, num_layers=4,
                          d_model=256, num_heads=4, d_ff=1024),
        32_768, 0.0),
    # GPT-2-small-sized flagship with the TPU-native head layout:
    # 6 heads × d_head 128 instead of GPT-2's 12 × 64 — identical
    # parameter shapes and count (768 = 12·64 = 6·128).  The 12×64
    # penalty is intrinsic MXU geometry, not a kernel gap: matmuls
    # bill output_tiles × ceil(d/128) full passes (a 64-deep matmul
    # measures 0.7-1.3× the wall time of the 128-deep one at half the
    # FLOPs), so head-packing constructions cancel exactly, and 12
    # heads compute 2× the softmax score elements.  Measured: flash
    # f+b 5.0 vs 11.2 ms at the flagship shapes — 2.2×, +33%
    # end-to-end tokens/s for this layout (bench_lm.py --variant
    # dhead holds the reproducible probe)
    "transformer_tpu": (
        functools.partial(transformer.TransformerLM, num_layers=12,
                          d_model=768, num_heads=6, d_ff=3072),
        32_768, 0.0),
    # routed-expert LM family (expert parallelism over 'data')
    "moe_transformer": (moe.MoETransformerLM, 32_768, 0.0),
    "moe_transformer_small": (
        functools.partial(moe.MoETransformerLM, num_layers=4, d_model=256,
                          num_heads=4, d_ff=1024, num_experts=4),
        32_768, 0.0),
    # pipeline-stacked LM family (pipeline stages over 'model')
    "pipeline_transformer": (pipeline_lm.PipelinedTransformerLM,
                             32_768, 0.0),
    "pipeline_transformer_small": (
        functools.partial(pipeline_lm.PipelinedTransformerLM, num_layers=4,
                          d_model=256, num_heads=4, d_ff=1024),
        32_768, 0.0),
}


def build_model(name: str, num_classes: int | None = None,
                dtype: Any = jnp.float32, bn_axis: str | None = None,
                seq_axis: str | None = None, model_axis: str | None = None,
                expert_axis: str | None = None, pipe_axis: str | None = None,
                **model_kw):
    """Returns (module, l2_weight).

    `bn_axis` names the mesh axis for cross-replica (sync) BatchNorm;
    None = per-replica statistics, the reference's implicit
    MirroredStrategy behavior (SURVEY §7.4).  `seq_axis` names the mesh
    axis the sequence dimension is sharded over (transformer family
    only) — it switches attention to the ring implementation.
    `model_axis` enables Megatron-style tensor parallelism (transformer
    family only): heads/ff sharded; pair with
    transformer.param_partition_specs.  `expert_axis` shards MoE
    experts (moe_transformer family; pair with
    moe.moe_param_partition_specs); `pipe_axis` makes the axis shards
    pipeline stages (pipeline_transformer family; pair with
    pipeline_lm.pipeline_param_partition_specs)."""
    if name not in _REGISTRY:
        raise ValueError(f"unknown model {name!r}; have {sorted(_REGISTRY)}")
    ctor, default_classes, l2 = _REGISTRY[name]
    if name.startswith("moe_transformer"):
        kw = dict(vocab_size=num_classes or default_classes, dtype=dtype,
                  seq_axis=seq_axis, expert_axis=expert_axis, **model_kw)
    elif name.startswith("pipeline_transformer"):
        kw = dict(vocab_size=num_classes or default_classes, dtype=dtype,
                  pipe_axis=pipe_axis, **model_kw)
    elif name.startswith("transformer"):
        kw = dict(vocab_size=num_classes or default_classes, dtype=dtype,
                  seq_axis=seq_axis, model_axis=model_axis, **model_kw)
    else:
        kw = dict(num_classes=num_classes or default_classes, dtype=dtype,
                  **model_kw)
        if name != "trivial":
            kw["bn_axis"] = bn_axis
    module = ctor(**kw)
    return module, l2


def l2_weight_penalty(params, l2_weight: float, param_specs=None
                      ) -> jax.Array:
    """Keras-parity L2 term: l2 * sum(w²) over conv/dense kernels and the
    classifier bias.  Note Keras `regularizers.l2(l)` is `l * sum(w²)`
    (no 0.5 factor).

    With ``param_specs`` (a PartitionSpec tree matching ``params``, for
    model-sharded runs inside shard_map), each sharded leaf's local
    sum-of-squares is summed over its sharding axes with `tp_psum` (sum
    forward, identity backward), so the penalty — and its gradient on
    each local shard — matches the unsharded model exactly.  Without it,
    a TP/EP/PP-sharded kernel would be silently under-counted.

    Penalized leaves sharded over a BATCH axis ('data'/'seq') are
    rejected: the trainer's gradient reduction divides such leaves'
    grads by the axis size (the all_to_all-transpose convention), which
    would scale the tp_psum L2 gradient down by the same factor.  No
    model family hits this (expert weights are named w1/w2, outside the
    penalize rule), so it is a guard, not a capability."""
    if not l2_weight:
        return jnp.zeros((), jnp.float32)
    spec_leaves = None
    if param_specs is not None:
        from jax.sharding import PartitionSpec
        spec_leaves = [
            s for _, s in jax.tree_util.tree_leaves_with_path(
                param_specs,
                is_leaf=lambda x: isinstance(x, PartitionSpec))]
    total = jnp.zeros((), jnp.float32)
    for i, (path, leaf) in enumerate(
            jax.tree_util.tree_leaves_with_path(params)):
        keys = [getattr(p, "key", getattr(p, "name", "")) for p in path]
        last = keys[-1] if keys else ""
        penalized = last == "kernel" or (last == "bias" and "fc" in keys)
        if not penalized:
            continue
        ss = jnp.sum(jnp.square(leaf.astype(jnp.float32)))
        if spec_leaves is not None:
            from dtf_tpu.models.partition import spec_axes
            axes = spec_axes(spec_leaves[i])
            batch_sharded = axes & {"data", "seq"}
            if batch_sharded:
                raise ValueError(
                    f"L2-penalized leaf {'/'.join(keys)} is sharded over "
                    f"batch axes {sorted(batch_sharded)}; the L2 gradient "
                    f"would be divided by the axis size in gradient "
                    f"reduction — unsupported")
            if axes:
                from dtf_tpu.parallel.collectives import tp_psum
                for ax in sorted(axes):
                    ss = tp_psum(ss, ax)
        total = total + ss
    return l2_weight * total
