from dtf_tpu.models.registry import build_model, l2_weight_penalty  # noqa: F401
from dtf_tpu.models.resnet import ResNet50  # noqa: F401
from dtf_tpu.models.resnet_cifar import (  # noqa: F401
    CifarResNet,
    resnet20,
    resnet32,
    resnet56,
    resnet110,
)
from dtf_tpu.models.trivial import TrivialModel  # noqa: F401
