"""Shared scaffolding for per-family parameter partition specs.

Each parallelism family (tensor, expert, pipeline) contributes only its
match rule; the path-key extraction and tree walk live here once.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax


def partition_specs(params, rule: Callable):
    """Map ``rule(keys, last, leaf) -> PartitionSpec`` over a param
    tree.  ``keys`` is the module path as strings, ``last`` its final
    component (the param name)."""

    def wrap(path, leaf):
        keys: Sequence[str] = [getattr(p, "key", getattr(p, "name", ""))
                               for p in path]
        last = keys[-1] if keys else ""
        return rule(keys, last, leaf)

    return jax.tree_util.tree_map_with_path(wrap, params)


def spec_axes(spec) -> set:
    """Mesh axis names a PartitionSpec shards over (the one shared
    implementation — loop/registry/consumers import this)."""
    axes: set = set()
    if spec is None:
        return axes
    for part in spec:
        if part is None:
            continue
        axes.update(part if isinstance(part, (tuple, list)) else (part,))
    return axes
