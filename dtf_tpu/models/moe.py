"""Mixture-of-Experts transformer — expert parallelism over the mesh.

No reference counterpart (the reference is dense vision-only, SURVEY.md
§2.2 lists EP/MoE as an explicit absence); this closes that axis of the
parallelism matrix TPU-first.

Design (GShard/Switch lineage, re-expressed for XLA):
  - **Static-shape dispatch.** Routing never gathers with dynamic
    shapes: a top-k router (k=1 Switch, k=2 GShard — ``router_top_k``)
    assigns every token an (expert, capacity-slot) pair and moves
    tokens with a static scatter-add into the ``[experts·capacity, d]``
    slot buffer and a gather back (``dispatch_mode="scatter"``, the
    default) — O(n·k·d + E·C·d) memory.  The r1 dense formulation
    (one-hot ``[tokens, experts, capacity]`` einsums, O(n·E·C)) is kept
    as ``dispatch_mode="dense"``: it is the numerical oracle in tests
    and the faster choice for tiny E.  Tokens beyond an expert's
    capacity are dropped (their MoE output is 0; the residual carries
    them), exactly the GShard overflow rule.  Top-2 gates renormalize
    to sum to 1 (GShard); top-1 keeps the raw router probability
    (Switch — the router's gradient path).
  - **Expert parallelism, two placements.**
    (a) Over the batch ('data') axis — the classic DeepSpeed-MoE/GShard
    placement (``expert_axis_along_batch=True``): each data shard holds
    ``E / ep`` experts and two tiled ``lax.all_to_all`` collectives
    (ICI) exchange capacity slots so every expert sees the tokens
    routed to it from the whole group.
    (b) Over the 'model' axis (``--model_parallelism`` with a MoE
    family; ``expert_axis_along_batch=False``): the batch is replicated
    across 'model', so no token exchange is needed at all — each model
    rank runs its E/mp experts on the tokens routed to them and the
    partial outputs psum over 'model' (`tp_psum`: identity backward).
    This decouples the expert-parallel group size from the DP world —
    E=8 experts on dp=64 runs as mesh (64, 1, 8) — at the cost of
    replicating the dense blocks' compute across 'model'.
  - **Router in fp32** (softmax numerics), expert matmuls in the
    compute dtype (bf16 on TPU), combine in fp32.
  - **Aux load-balance loss** (Switch §2.2 form: ``E · Σ f_e · p_e``)
    is sown into the ``aux_loss`` collection; the Trainer adds every
    sown aux term to the objective.

Gradient contract (enforced by ``Trainer`` via
``moe_param_partition_specs``): placement (a)'s expert leaves are
sharded over 'data', so their local grads — which reverse-mode
all_to_all already sums across the expert group — are divided by the
data-axis size instead of being pmean-ed (a pmean would average
*different experts'* grads).  Placement (b)'s leaves shard over
'model' and take the ordinary data-parallel pmean.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from dtf_tpu.models.transformer import Block, CausalSelfAttention


class MoEMLP(nn.Module):
    """Top-k routed expert MLP with static capacity (k=1 Switch, k=2
    GShard; see module docstring for gate semantics).

    Call with ``x: [batch, seq, d_model]``; returns the same shape.
    ``expert_axis`` names the mesh axis experts are sharded over (the
    module must then run inside shard_map and receive its local expert
    shards); None means all experts live on every device.
    """

    num_experts: int
    d_ff: int
    capacity_factor: float = 1.25
    router_top_k: int = 2    # 1 = Switch routing, 2 = GShard top-2
    dtype: Any = jnp.float32
    expert_axis: Optional[str] = None
    # True: expert_axis also shards the batch (all_to_all exchange);
    # False: batch replicated over expert_axis (local slice + psum)
    expert_axis_along_batch: bool = True
    # "scatter" (default): O(n·k·d + E·C·d) slot scatter/gather;
    # "dense": r1's one-hot einsums, O(n·E·C) — oracle / tiny-E path
    dispatch_mode: str = "scatter"
    aux_weight: float = 0.01

    @nn.compact
    def __call__(self, x):
        b, s, d = x.shape
        e = self.num_experts
        tokens = x.reshape(b * s, d)
        n = b * s

        ep = 1
        e_loc = e
        if self.expert_axis is not None:
            ep = lax.psum(1, self.expert_axis)  # static axis size
            if e % ep:
                raise ValueError(
                    f"num_experts {e} not divisible by expert-parallel "
                    f"group size {ep}")
            e_loc = e // ep
        along_batch = self.expert_axis_along_batch
        if self.dispatch_mode not in ("scatter", "dense"):
            raise ValueError(f"unknown dispatch_mode {self.dispatch_mode!r}")
        if (self.dispatch_mode == "dense" and self.expert_axis is not None
                and not along_batch):
            raise ValueError(
                "dense dispatch implements the along-batch (all_to_all) "
                "placement only; use dispatch_mode='scatter' for "
                "model-axis expert parallelism")

        k_init = nn.initializers.lecun_normal(batch_axis=(0,))
        w1 = self.param("w1", k_init, (e_loc, d, self.d_ff))
        b1 = self.param("b1", nn.initializers.zeros, (e_loc, self.d_ff))
        w2 = self.param("w2", k_init, (e_loc, self.d_ff, d))
        b2 = self.param("b2", nn.initializers.zeros, (e_loc, d))

        # ---- router (fp32) ------------------------------------------
        logits = nn.Dense(e, dtype=jnp.float32, name="router")(
            tokens.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)            # [n, E]

        k = self.router_top_k
        if k < 1:
            raise ValueError(f"router_top_k {k} must be >= 1")
        k = min(k, e)  # a single expert degenerates top-2 to top-1
        # iterative top-k: each choice takes the argmax of what earlier
        # choices left (k=1 is Switch routing, k=2 is GShard's top-2)
        masks, idxs = [], []
        remaining = probs
        for _ in range(k):
            idx_c = jnp.argmax(remaining, axis=-1)
            m_c = jax.nn.one_hot(idx_c, e, dtype=jnp.float32)  # [n, E]
            masks.append(m_c)
            idxs.append(idx_c.astype(jnp.int32))
            remaining = remaining * (1.0 - m_c)

        # load balance: fraction routed (first choice) × mean prob
        frac = jnp.mean(masks[0], axis=0)
        p_mean = jnp.mean(probs, axis=0)
        aux = e * jnp.sum(frac * p_mean)
        self.sow("aux_loss", "load_balance", self.aux_weight * aux)

        # ---- capacity positions (static C) --------------------------
        cap = max(1, min(n, int(round(self.capacity_factor * k * n / e))))
        keeps, slots = [], []
        count_prev = jnp.zeros((1, e), jnp.float32)
        for m_c in masks:
            # a choice's slots start after every earlier choice's tokens
            pos_c = jnp.sum(
                (jnp.cumsum(m_c, axis=0) - m_c + count_prev) * m_c,
                axis=-1)                                    # [n]
            count_prev = count_prev + jnp.sum(m_c, axis=0, keepdims=True)
            keeps.append((pos_c < cap).astype(jnp.float32))
            slots.append(lax.stop_gradient(pos_c).astype(jnp.int32))

        # Gate/token sources.  The model-axis placement consumes gates
        # and tokens PER-RANK (each rank sees only its experts'
        # contribution paths), so replicated producers — the router and
        # everything upstream — must be entered through tp_region
        # (identity forward, psum backward): the summed cotangent is
        # exactly the unsharded gradient, and every rank derives
        # identical replicated-param grads.  Without it the router
        # kernel would silently desynchronize across 'model'.
        model_axis_ep = (self.expert_axis is not None and not along_batch)
        if model_axis_ep:
            from dtf_tpu.parallel.collectives import tp_psum, tp_region
            probs_src = tp_region(probs, self.expert_axis)
            tok_src = tp_region(tokens.astype(jnp.float32),
                                self.expert_axis)
        else:
            probs_src = probs
            tok_src = tokens.astype(jnp.float32)
        gates = [jnp.sum(probs_src * m_c, axis=-1) * keep_c
                 for m_c, keep_c in zip(masks, keeps)]
        if k > 1:
            # GShard renormalizes the kept top-k gates to sum to 1
            denom = sum(gates)
            denom = jnp.where(denom > 0, denom, 1.0)
        else:
            # Switch keeps the raw router probability — renormalizing
            # would make the gate a constant 1 and starve the router of
            # gradient signal
            denom = 1.0

        def run_experts(xin):
            """[e_loc, slots, d] expert batch → same shape."""
            h = jnp.einsum("ecd,edf->ecf", xin, w1.astype(self.dtype))
            h = nn.gelu(h + b1[:, None, :].astype(self.dtype))
            out = jnp.einsum("ecf,efd->ecd", h, w2.astype(self.dtype))
            return out + b2[:, None, :].astype(self.dtype)

        if self.dispatch_mode == "dense":
            dispatch = jnp.zeros((n, e, cap), jnp.float32)
            combine = jnp.zeros((n, e, cap), jnp.float32)
            for m_c, g_c, keep_c, pos_c in zip(masks, gates, keeps, slots):
                # one_hot of an out-of-range position is all-zero, so
                # dropped tokens vanish from dispatch/combine
                oh_c = jax.nn.one_hot(pos_c, cap,
                                      dtype=jnp.float32) * keep_c[:, None]
                slot = m_c[:, :, None] * oh_c[:, None, :]   # [n, E, C]
                dispatch = dispatch + slot
                combine = combine + (g_c / denom)[:, None, None] * slot
            dispatch = lax.stop_gradient(dispatch)
            xin = jnp.einsum("nec,nd->ecd", dispatch,
                             tokens.astype(jnp.float32)).astype(self.dtype)
            if self.expert_axis is not None and ep > 1:
                # NETWORK BOUNDARY: exchange capacity slots across the
                # expert group so each device holds its local experts'
                # tokens from every peer — [E, C, d] → [E/ep, ep·C, d]
                xin = lax.all_to_all(xin, self.expert_axis, split_axis=0,
                                     concat_axis=1, tiled=True)
            out = run_experts(xin)
            if self.expert_axis is not None and ep > 1:
                # inverse exchange: [E/ep, ep·C, d] → [E, C, d]
                out = lax.all_to_all(out, self.expert_axis, split_axis=1,
                                     concat_axis=0, tiled=True)
            y = jnp.einsum("nec,ecd->nd", combine,
                           out.astype(jnp.float32))
            return y.reshape(b, s, d).astype(x.dtype)

        # ---- scatter dispatch (default): no [n, E, C] tensor --------
        tok32 = tok_src
        if along_batch or self.expert_axis is None:
            rows = e * cap
            xin_flat = jnp.zeros((rows, d), jnp.float32)
            for idx_c, pos_c, keep_c in zip(idxs, slots, keeps):
                # out-of-capacity tokens get index `rows` → mode="drop"
                safe = jnp.where(keep_c > 0, idx_c * cap + pos_c, rows)
                xin_flat = xin_flat.at[safe].add(
                    tok32 * keep_c[:, None], mode="drop")
            xin = xin_flat.reshape(e, cap, d).astype(self.dtype)
            if self.expert_axis is not None and ep > 1:
                # NETWORK BOUNDARY (see dense path)
                xin = lax.all_to_all(xin, self.expert_axis, split_axis=0,
                                     concat_axis=1, tiled=True)
            out = run_experts(xin)
            if self.expert_axis is not None and ep > 1:
                out = lax.all_to_all(out, self.expert_axis, split_axis=1,
                                     concat_axis=0, tiled=True)
            out_flat = out.reshape(rows, d).astype(jnp.float32)
            y = jnp.zeros((n, d), jnp.float32)
            for idx_c, pos_c, keep_c, g_c in zip(idxs, slots, keeps, gates):
                safe = jnp.where(keep_c > 0, idx_c * cap + pos_c, 0)
                y = y + (g_c / denom)[:, None] * out_flat[safe]
            return y.reshape(b, s, d).astype(x.dtype)

        # experts over a non-batch axis ('model'): the batch is
        # replicated across the axis, so each rank scatters only the
        # tokens routed to ITS E/mp experts and partial outputs psum —
        # no all_to_all, no token movement at all
        rank = lax.axis_index(self.expert_axis)
        rows = e_loc * cap
        xin_flat = jnp.zeros((rows, d), jnp.float32)
        oks = []
        for idx_c, pos_c, keep_c in zip(idxs, slots, keeps):
            local = idx_c - rank * e_loc
            ok = ((local >= 0) & (local < e_loc)
                  & (keep_c > 0)).astype(jnp.float32)
            oks.append(ok)
            safe = jnp.where(ok > 0, local * cap + pos_c, rows)
            xin_flat = xin_flat.at[safe].add(tok32 * ok[:, None],
                                             mode="drop")
        out = run_experts(xin_flat.reshape(e_loc, cap, d).astype(self.dtype))
        out_flat = out.reshape(rows, d).astype(jnp.float32)
        y = jnp.zeros((n, d), jnp.float32)
        for idx_c, pos_c, ok, g_c in zip(idxs, slots, oks, gates):
            local = idx_c - rank * e_loc
            safe = jnp.where(ok > 0, local * cap + pos_c, 0)
            y = y + (ok * g_c / denom)[:, None] * out_flat[safe]
        # identity backward: every rank's partial already carries the
        # full cotangent of its own tokens' outputs
        y = tp_psum(y, self.expert_axis)
        return y.reshape(b, s, d).astype(x.dtype)


class MoEBlock(nn.Module):
    """Pre-LN block: causal attention + routed-expert MLP."""

    num_heads: int
    d_ff: int
    num_experts: int
    capacity_factor: float = 1.25
    router_top_k: int = 2
    dtype: Any = jnp.float32
    seq_axis: Optional[str] = None
    expert_axis: Optional[str] = None
    expert_axis_along_batch: bool = True
    dispatch_mode: str = "scatter"
    aux_weight: float = 0.01
    use_pallas: Any = None

    @nn.compact
    def __call__(self, x):
        h = nn.LayerNorm(dtype=self.dtype, name="ln1")(x)
        x = x + CausalSelfAttention(
            self.num_heads, dtype=self.dtype, seq_axis=self.seq_axis,
            use_pallas=self.use_pallas, name="attn")(h)
        h = nn.LayerNorm(dtype=self.dtype, name="ln2")(x)
        return x + MoEMLP(
            self.num_experts, self.d_ff,
            capacity_factor=self.capacity_factor,
            router_top_k=self.router_top_k, dtype=self.dtype,
            expert_axis=self.expert_axis,
            expert_axis_along_batch=self.expert_axis_along_batch,
            dispatch_mode=self.dispatch_mode, aux_weight=self.aux_weight,
            name="moe")(h)


class MoETransformerLM(nn.Module):
    """Decoder-only LM with routed-expert MLPs every ``moe_every``-th
    block (the interleaved dense/MoE stacking of GShard/ST-MoE).

    Composes with sequence parallelism (``seq_axis``: ring attention;
    routing is per-token and needs no cross-shard coordination).  The
    'model' axis is available as a dedicated expert axis
    (``expert_axis_along_batch=False``) rather than for Megatron TP of
    the dense layers — experts already shard the ff computation."""

    vocab_size: int
    num_layers: int = 12
    d_model: int = 512
    num_heads: int = 8
    d_ff: int = 2048
    num_experts: int = 8
    moe_every: int = 2
    capacity_factor: float = 1.25
    router_top_k: int = 2
    aux_weight: float = 0.01
    max_seq_len: int = 2048
    dtype: Any = jnp.float32
    seq_axis: Optional[str] = None
    expert_axis: Optional[str] = None
    expert_axis_along_batch: bool = True
    dispatch_mode: str = "scatter"
    use_pallas: Any = None
    remat: bool = False
    # selective remat ("dots", models/transformer.py remat_policy):
    # matmul/attention outputs saved, elementwise recomputed.  NB the
    # expert all_to_all dispatch outputs are NOT dots, so the token
    # exchange re-runs during backward recompute — same communication
    # cost full remat already pays, at less recompute FLOPs
    remat_policy: Optional[str] = None

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        del train  # LN only — same train/eval behavior
        b, s_local = tokens.shape
        x = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype,
                     name="embed")(tokens)
        pos_table = self.param(
            "pos_embed", nn.initializers.normal(0.02),
            (self.max_seq_len, self.d_model))
        offset = 0
        if self.seq_axis is not None:
            offset = lax.axis_index(self.seq_axis) * s_local
        x = x + lax.dynamic_slice_in_dim(
            pos_table, offset, s_local).astype(self.dtype)

        dense_block, moe_block = Block, MoEBlock
        if self.remat_policy is not None:
            from dtf_tpu.models.transformer import remat_policy
            policy = remat_policy(self.remat_policy)
            dense_block = nn.remat(Block, policy=policy)
            moe_block = nn.remat(MoEBlock, policy=policy)
        elif self.remat:
            dense_block = nn.remat(Block)
            moe_block = nn.remat(MoEBlock)
        for i in range(self.num_layers):
            if (i % self.moe_every) == self.moe_every - 1:
                x = moe_block(
                    self.num_heads, self.d_ff, self.num_experts,
                    capacity_factor=self.capacity_factor,
                    router_top_k=self.router_top_k, dtype=self.dtype,
                    seq_axis=self.seq_axis, expert_axis=self.expert_axis,
                    expert_axis_along_batch=self.expert_axis_along_batch,
                    dispatch_mode=self.dispatch_mode,
                    aux_weight=self.aux_weight, use_pallas=self.use_pallas,
                    name=f"block{i}")(x)
            else:
                x = dense_block(self.num_heads, self.d_ff, dtype=self.dtype,
                                seq_axis=self.seq_axis,
                                use_pallas=self.use_pallas,
                                name=f"block{i}")(x)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        logits = nn.Dense(self.vocab_size, dtype=self.dtype,
                          name="lm_head")(x)
        return logits.astype(jnp.float32)


def moe_param_partition_specs(params, expert_axis: str):
    """PartitionSpec tree sharding expert weights (w1/b1/w2/b2 under any
    ``moe`` module) over the expert-parallel axis; router and all dense
    layers replicated."""
    from jax.sharding import PartitionSpec as P

    from dtf_tpu.models.partition import partition_specs

    def rule(keys, last, leaf):
        if "moe" in keys and last in ("w1", "b1", "w2", "b2"):
            return P(expert_axis, *([None] * (leaf.ndim - 1)))
        return P()

    return partition_specs(params, rule)
