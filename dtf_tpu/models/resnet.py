"""ResNet-50 v1.5 for 224×224 ImageNet, as a flax module.

Capability parity with reference resnet_model.py (resnet50, :224-389):
  - bottleneck blocks [1×1, 3×3(stride), 1×1]; the stride sits on the
    3×3 ("v1.5", reference conv_block:124-221)
  - stage layout 3/4/6/3, filters (64,64,256)→(512,512,2048)
  - conv1: 7×7 stride 2, explicit (3,3) zero-pad, no bias
  - BatchNorm momentum 0.9, eps 1e-5 (resnet_model.py:38-39)
  - he_normal conv init; final Dense init N(0, 0.01) (:377)
  - L2 weight decay 1e-4 applied as a loss term over conv/dense kernels
    AND the final dense bias (:37-43, :378-380) — see registry.l2_weight_penalty
  - logits cast to float32 before softmax under mixed precision (:383-385)

TPU-first choices: NHWC layout (MXU/XLA native), bf16 compute with fp32
params and fp32 BatchNorm, padding='SAME' where it is numerically
identical, logits returned (loss applies log-softmax — cheaper and
fused by XLA; the reference bakes softmax into the model).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

BATCH_NORM_DECAY = 0.9
BATCH_NORM_EPSILON = 1e-5

conv_init = nn.initializers.he_normal()
dense_init = nn.initializers.normal(stddev=0.01)


class Conv1SpaceToDepth(nn.Module):
    """The stem 7×7/2 conv, computed as a 4×4/1 conv over 2×2
    space-to-depth blocks — numerically identical, ~4× better MXU
    utilization (12 input channels instead of 3; the standard TPU
    ResNet stem trick).  The parameter keeps the reference shape
    (7,7,3,64) and the `conv1/kernel` tree path, so checkpoints and
    the plain-conv path are interchangeable; the zero-pad + block
    reshape of the kernel is traced into the step (trivially small)."""
    features: int = 64
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        assert c == 3, (f"Conv1SpaceToDepth is the RGB stem; got "
                        f"{c}-channel input")
        kernel = self.param("kernel", conv_init, (7, 7, c, self.features),
                            jnp.float32)
        x = jnp.pad(x, ((0, 0), (3, 3), (3, 3), (0, 0)))
        # 2×2 space-to-depth: [B, (H+6)/2, (W+6)/2, 12]
        hb, wb = (h + 6) // 2, (w + 6) // 2
        x = x.reshape(b, hb, 2, wb, 2, c).transpose(0, 1, 3, 2, 4, 5)
        x = x.reshape(b, hb, wb, 4 * c).astype(self.dtype)
        # kernel 7×7 → zero-pad to 8×8 → 4×4 blocks over 12 channels
        k = jnp.pad(kernel, ((0, 1), (0, 1), (0, 0), (0, 0)))
        k = k.reshape(4, 2, 4, 2, c, self.features)
        k = k.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * c,
                                                  self.features)
        return lax.conv_general_dilated(
            x, k.astype(self.dtype), window_strides=(1, 1),
            padding="VALID", dimension_numbers=("NHWC", "HWIO", "NHWC"))


class BottleneckBlock(nn.Module):
    """conv_block / identity_block of reference resnet_model.py:46-221."""
    filters: Sequence[int]
    strides: int = 1
    projection: bool = False
    dtype: Any = jnp.float32
    bn_axis: Any = None  # axis_name for cross-replica (sync) BN

    @nn.compact
    def __call__(self, x, train: bool = True):
        f1, f2, f3 = self.filters
        conv = partial(nn.Conv, use_bias=False, kernel_init=conv_init,
                       dtype=self.dtype, param_dtype=jnp.float32)
        # dtype=self.dtype keeps activations bf16 between convs (half the
        # HBM traffic of fp32 BN I/O — the r1 bench's top time sink); the
        # mean/var math itself is still fp32 (flax _compute_stats upcasts)
        bn = partial(nn.BatchNorm, use_running_average=not train,
                     axis_name=self.bn_axis,
                     momentum=BATCH_NORM_DECAY, epsilon=BATCH_NORM_EPSILON,
                     dtype=self.dtype, param_dtype=jnp.float32)
        shortcut = x
        y = conv(f1, (1, 1), name="conv_a")(x)
        y = bn(name="bn_a")(y)
        y = nn.relu(y)
        y = conv(f2, (3, 3), strides=(self.strides, self.strides),
                 padding="SAME", name="conv_b")(y)
        y = bn(name="bn_b")(y)
        y = nn.relu(y)
        y = conv(f3, (1, 1), name="conv_c")(y)
        y = bn(name="bn_c")(y)
        if self.projection:
            shortcut = conv(f3, (1, 1), strides=(self.strides, self.strides),
                            name="conv_proj")(x)
            shortcut = bn(name="bn_proj")(shortcut)
        return nn.relu(y + shortcut.astype(y.dtype))


class ResNet50(nn.Module):
    """Returns float32 logits of shape [batch, num_classes]."""
    num_classes: int = 1001
    dtype: Any = jnp.float32
    bn_axis: Any = None  # axis_name for cross-replica (sync) BN
    # stem as a space-to-depth conv (exact reformulation, see
    # Conv1SpaceToDepth); False = the literal reference conv1
    stem_space_to_depth: bool = True

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        # conv1: explicit (3,3) pad + VALID 7×7/2 ≡ reference conv1_pad+conv1
        if self.stem_space_to_depth and x.shape[1] % 2 == 0 and \
                x.shape[2] % 2 == 0 and x.shape[3] == 3:
            x = Conv1SpaceToDepth(dtype=self.dtype, name="conv1")(x)
        else:
            x = nn.Conv(64, (7, 7), strides=(2, 2),
                        padding=[(3, 3), (3, 3)],
                        use_bias=False, kernel_init=conv_init,
                        dtype=self.dtype,
                        param_dtype=jnp.float32, name="conv1")(x)
        x = nn.BatchNorm(use_running_average=not train,
                         axis_name=self.bn_axis,
                         momentum=BATCH_NORM_DECAY, epsilon=BATCH_NORM_EPSILON,
                         dtype=self.dtype, param_dtype=jnp.float32,
                         name="bn_conv1")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

        stages = (
            ((64, 64, 256), 3, 1),
            ((128, 128, 512), 4, 2),
            ((256, 256, 1024), 6, 2),
            ((512, 512, 2048), 3, 2),
        )
        for s, (filters, blocks, stride) in enumerate(stages, start=2):
            x = BottleneckBlock(filters, strides=stride, projection=True,
                                dtype=self.dtype, bn_axis=self.bn_axis, name=f"stage{s}_block0")(
                                    x, train=train)
            for b in range(1, blocks):
                x = BottleneckBlock(filters, dtype=self.dtype, bn_axis=self.bn_axis,
                                    name=f"stage{s}_block{b}")(x, train=train)

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, kernel_init=dense_init,
                     dtype=self.dtype, param_dtype=jnp.float32, name="fc")(x)
        # mixed-precision parity: logits in float32 (resnet_model.py:383-385)
        return x.astype(jnp.float32)
