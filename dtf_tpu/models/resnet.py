"""ResNet-50 v1.5 for 224×224 ImageNet, as a flax module.

Capability parity with reference resnet_model.py (resnet50, :224-389):
  - bottleneck blocks [1×1, 3×3(stride), 1×1]; the stride sits on the
    3×3 ("v1.5", reference conv_block:124-221)
  - stage layout 3/4/6/3, filters (64,64,256)→(512,512,2048)
  - conv1: 7×7 stride 2, explicit (3,3) zero-pad, no bias
  - BatchNorm momentum 0.9, eps 1e-5 (resnet_model.py:38-39)
  - he_normal conv init; final Dense init N(0, 0.01) (:377)
  - L2 weight decay 1e-4 applied as a loss term over conv/dense kernels
    AND the final dense bias (:37-43, :378-380) — see registry.l2_weight_penalty
  - logits cast to float32 before softmax under mixed precision (:383-385)

TPU-first choices: NHWC layout (MXU/XLA native), bf16 compute with fp32
params and fp32 BatchNorm, padding='SAME' where it is numerically
identical, logits returned (loss applies log-softmax — cheaper and
fused by XLA; the reference bakes softmax into the model).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

BATCH_NORM_DECAY = 0.9
BATCH_NORM_EPSILON = 1e-5

conv_init = nn.initializers.he_normal()
dense_init = nn.initializers.normal(stddev=0.01)

# Selective-remat policy for the bandwidth-bound ResNet step: save conv
# outputs and BN batch statistics as backward residuals; recompute the
# elementwise normalize/relu chains in the backward instead of storing
# their outputs.  The step is HBM-floored (docs/DESIGN.md roofline:
# 78.8 GB/step at 97.5% of peak with 65 ms of FLOP headroom), so
# trading free VPU recompute for residual reads/writes attacks the only
# binding constraint.  BN stats are saved so the backward never re-runs
# the mean/var reductions (those would re-read the conv output).
RESNET_REMAT_POLICY = jax.checkpoint_policies.save_only_these_names(
    "conv_out", "bn_stats")


def _bn_stats(x, reduction_axes, dtype, axis_name):
    """Batch mean/variance, vendored op-for-op from flax's
    ``_compute_stats`` (the real-input, fast-variance path) so a flax
    upgrade can't rename a private helper out from under ResNet import
    (ADVICE r5): reductions promoted to ≥ f32, Var = E[x²] − E[x]² with
    the negative-roundoff clamp, and the distributed (sync-BN) form
    stacking [mean, mean-of-squares] into ONE ``lax.pmean``.  Parity is
    pinned by test_tagged_batchnorm_bit_exact_vs_flax."""
    if dtype is None:
        dtype = jnp.result_type(x)
    dtype = jnp.promote_types(dtype, jnp.float32)
    x = jnp.asarray(x, dtype)
    mu = x.mean(reduction_axes)
    mu2 = lax.square(x).mean(reduction_axes)
    if axis_name is not None:
        mu, mu2 = lax.pmean(jnp.stack([mu, mu2]), axis_name)
    var = jnp.maximum(0.0, mu2 - lax.square(mu))
    return mu, var


class TaggedBatchNorm(nn.Module):
    """nn.BatchNorm (feature-last), bit-identical by construction — the
    ~15 lines of stat/normalize math are vendored op-for-op from flax
    (see `_bn_stats`; the normalize below keeps flax's exact operation
    order: y = x − mean, mul = rsqrt(var + ε) · scale, y·mul + bias) —
    plus `checkpoint_name` tags on the batch mean/var so the
    selective-remat policy can keep the statistics as residuals while
    the normalize itself is recomputed.  Parameter/collection tree
    paths match nn.BatchNorm ('scale', 'bias'; batch_stats 'mean',
    'var')."""
    use_running_average: bool = False
    momentum: float = BATCH_NORM_DECAY
    epsilon: float = BATCH_NORM_EPSILON
    dtype: Any = None
    param_dtype: Any = jnp.float32
    axis_name: Any = None  # cross-replica (sync) BN

    @nn.compact
    def __call__(self, x):
        feature_shape = (x.shape[-1],)
        reduction_axes = tuple(range(x.ndim - 1))
        ra_mean = self.variable(
            "batch_stats", "mean",
            lambda s: jnp.zeros(s, jnp.float32), feature_shape)
        ra_var = self.variable(
            "batch_stats", "var",
            lambda s: jnp.ones(s, jnp.float32), feature_shape)
        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            mean, var = _bn_stats(x, reduction_axes, self.dtype,
                                  self.axis_name)
            mean = checkpoint_name(mean, "bn_stats")
            var = checkpoint_name(var, "bn_stats")
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mean
                ra_var.value = m * ra_var.value + (1 - m) * var
        # normalize (flax `_normalize`, feature-last + scale&bias case)
        bshape = (1,) * (x.ndim - 1) + feature_shape
        y = x - mean.reshape(bshape)
        mul = lax.rsqrt(var.reshape(bshape) + self.epsilon)
        scale = self.param("scale", nn.initializers.ones_init(),
                           feature_shape, self.param_dtype)
        mul *= scale.reshape(bshape)
        y *= mul
        bias = self.param("bias", nn.initializers.zeros_init(),
                          feature_shape, self.param_dtype)
        y += bias.reshape(bshape)
        dtype = (jnp.result_type(x, scale, bias) if self.dtype is None
                 else self.dtype)
        return jnp.asarray(y, dtype)


class Conv1SpaceToDepth(nn.Module):
    """The stem 7×7/2 conv, computed as a 4×4/1 conv over 2×2
    space-to-depth blocks — numerically identical, ~4× better MXU
    utilization (12 input channels instead of 3; the standard TPU
    ResNet stem trick).  The parameter keeps the reference shape
    (7,7,3,64) and the `conv1/kernel` tree path, so checkpoints and
    the plain-conv path are interchangeable; the zero-pad + block
    reshape of the kernel is traced into the step (trivially small)."""
    features: int = 64
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        assert c == 3, (f"Conv1SpaceToDepth is the RGB stem; got "
                        f"{c}-channel input")
        kernel = self.param("kernel", conv_init, (7, 7, c, self.features),
                            jnp.float32)
        x = jnp.pad(x, ((0, 0), (3, 3), (3, 3), (0, 0)))
        # 2×2 space-to-depth: [B, (H+6)/2, (W+6)/2, 12]
        hb, wb = (h + 6) // 2, (w + 6) // 2
        x = x.reshape(b, hb, 2, wb, 2, c).transpose(0, 1, 3, 2, 4, 5)
        x = x.reshape(b, hb, wb, 4 * c).astype(self.dtype)
        # kernel 7×7 → zero-pad to 8×8 → 4×4 blocks over 12 channels
        k = jnp.pad(kernel, ((0, 1), (0, 1), (0, 0), (0, 0)))
        k = k.reshape(4, 2, 4, 2, c, self.features)
        k = k.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * c,
                                                  self.features)
        return lax.conv_general_dilated(
            x, k.astype(self.dtype), window_strides=(1, 1),
            padding="VALID", dimension_numbers=("NHWC", "HWIO", "NHWC"))


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _conv_fp8_resid(x, w, strides, padding):
    """Convolution whose backward reads an fp8(e4m3) copy of the input
    activation instead of the bf16 original ("lower-precision activation
    storage", docs/DESIGN.md byte-lever probe).  dx is exact (needs only
    w and the cotangent); dW sees the quantized activations."""
    return lax.conv_general_dilated(
        x, w, strides, padding, dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _conv_fp8_fwd(x, w, strides, padding):
    y = _conv_fp8_resid(x, w, strides, padding)
    return y, (x.astype(jnp.float8_e4m3fn), w)


def _conv_fp8_bwd(strides, padding, res, g):
    x8, w = res
    x = x8.astype(w.dtype)
    _, vjp = jax.vjp(
        lambda xx, ww: lax.conv_general_dilated(
            xx, ww, strides, padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC")), x, w)
    return vjp(g)


_conv_fp8_resid.defvjp(_conv_fp8_fwd, _conv_fp8_bwd)


class Fp8ResidConv(nn.Module):
    """nn.Conv-compatible (no-bias, feature-last) conv storing its
    backward activation residual in fp8.  Parameter tree path matches
    nn.Conv ('kernel'), so the L2 rule and checkpoints line up."""
    features: int
    kernel_size: Sequence[int]
    strides: Sequence[int] = (1, 1)
    padding: str = "SAME"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        kh, kw = self.kernel_size
        w = self.param("kernel", conv_init,
                       (kh, kw, x.shape[-1], self.features), jnp.float32)
        return _conv_fp8_resid(x, w.astype(self.dtype),
                               tuple(self.strides), self.padding)


class BottleneckBlock(nn.Module):
    """conv_block / identity_block of reference resnet_model.py:46-221."""
    filters: Sequence[int]
    strides: int = 1
    projection: bool = False
    dtype: Any = jnp.float32
    bn_axis: Any = None  # axis_name for cross-replica (sync) BN
    fp8_residuals: bool = False  # byte-lever probe, see Fp8ResidConv

    @nn.compact
    def __call__(self, x, train: bool = True):
        f1, f2, f3 = self.filters
        if self.fp8_residuals and train:
            conv = partial(Fp8ResidConv, dtype=self.dtype)
        else:
            conv = partial(nn.Conv, use_bias=False, kernel_init=conv_init,
                           dtype=self.dtype, param_dtype=jnp.float32)
        # dtype=self.dtype keeps activations bf16 between convs (half the
        # HBM traffic of fp32 BN I/O — the r1 bench's top time sink); the
        # mean/var math itself is still fp32 (flax _compute_stats upcasts)
        bn = partial(TaggedBatchNorm, use_running_average=not train,
                     axis_name=self.bn_axis,
                     momentum=BATCH_NORM_DECAY, epsilon=BATCH_NORM_EPSILON,
                     dtype=self.dtype, param_dtype=jnp.float32)
        shortcut = x
        y = checkpoint_name(conv(f1, (1, 1), name="conv_a")(x), "conv_out")
        y = bn(name="bn_a")(y)
        y = nn.relu(y)
        y = conv(f2, (3, 3), strides=(self.strides, self.strides),
                 padding="SAME", name="conv_b")(y)
        y = checkpoint_name(y, "conv_out")
        y = bn(name="bn_b")(y)
        y = nn.relu(y)
        y = checkpoint_name(conv(f3, (1, 1), name="conv_c")(y), "conv_out")
        y = bn(name="bn_c")(y)
        if self.projection:
            shortcut = conv(f3, (1, 1), strides=(self.strides, self.strides),
                            name="conv_proj")(x)
            shortcut = checkpoint_name(shortcut, "conv_out")
            shortcut = bn(name="bn_proj")(shortcut)
        return nn.relu(y + shortcut.astype(y.dtype))


class ResNet50(nn.Module):
    """Returns float32 logits of shape [batch, num_classes]."""
    num_classes: int = 1001
    dtype: Any = jnp.float32
    bn_axis: Any = None  # axis_name for cross-replica (sync) BN
    # stem as a space-to-depth conv (exact reformulation, see
    # Conv1SpaceToDepth); False = the literal reference conv1
    stem_space_to_depth: bool = True
    # selective remat: save conv outputs + BN stats only, recompute the
    # elementwise normalize/relu chains in the backward (see
    # RESNET_REMAT_POLICY).  A bytes lever, not a memory one — the step
    # is HBM-bound.  Identical math either way.
    remat: bool = False
    # store conv input residuals in fp8 for the backward wgrad (probe;
    # changes dW numerics — see Fp8ResidConv)
    fp8_residuals: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        # conv1: explicit (3,3) pad + VALID 7×7/2 ≡ reference conv1_pad+conv1
        if self.stem_space_to_depth and x.shape[1] % 2 == 0 and \
                x.shape[2] % 2 == 0 and x.shape[3] == 3:
            x = Conv1SpaceToDepth(dtype=self.dtype, name="conv1")(x)
        else:
            x = nn.Conv(64, (7, 7), strides=(2, 2),
                        padding=[(3, 3), (3, 3)],
                        use_bias=False, kernel_init=conv_init,
                        dtype=self.dtype,
                        param_dtype=jnp.float32, name="conv1")(x)
        x = TaggedBatchNorm(use_running_average=not train,
                            axis_name=self.bn_axis,
                            momentum=BATCH_NORM_DECAY,
                            epsilon=BATCH_NORM_EPSILON,
                            dtype=self.dtype, param_dtype=jnp.float32,
                            name="bn_conv1")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

        # remat only where it matters (train step); lifted nn.remat does
        # not change variable tree paths, so train/eval stay compatible
        block_cls = BottleneckBlock
        if self.remat and train:
            # prevent_cse=False: we are under jit (not pmap/scan), where
            # the CSE-barrier workaround is unnecessary — and its
            # optimization barriers would force XLA to materialize the
            # recomputed elementwise chains instead of fusing them into
            # the backward convolutions' operand reads
            block_cls = nn.remat(BottleneckBlock,
                                 policy=RESNET_REMAT_POLICY,
                                 prevent_cse=False,
                                 static_argnums=(2,))
        stages = (
            ((64, 64, 256), 3, 1),
            ((128, 128, 512), 4, 2),
            ((256, 256, 1024), 6, 2),
            ((512, 512, 2048), 3, 2),
        )
        for s, (filters, blocks, stride) in enumerate(stages, start=2):
            x = block_cls(filters, strides=stride, projection=True,
                          dtype=self.dtype, bn_axis=self.bn_axis,
                          fp8_residuals=self.fp8_residuals,
                          name=f"stage{s}_block0")(x, train)
            for b in range(1, blocks):
                x = block_cls(filters, dtype=self.dtype,
                              bn_axis=self.bn_axis,
                              fp8_residuals=self.fp8_residuals,
                              name=f"stage{s}_block{b}")(x, train)

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, kernel_init=dense_init,
                     dtype=self.dtype, param_dtype=jnp.float32, name="fc")(x)
        # mixed-precision parity: logits in float32 (resnet_model.py:383-385)
        return x.astype(jnp.float32)
