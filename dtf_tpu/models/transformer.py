"""Decoder-only transformer LM — the long-context workload.

No reference equivalent (the reference is vision-only, SURVEY.md §5.7);
this is the model family that exercises the framework's first-class
long-context machinery: the Pallas flash-attention kernel
(`ops.flash_attention`) on a single chip, and ring attention over the
'seq' mesh axis (`parallel.ring_attention`) when the sequence dimension
is sharded (`--seq_parallelism N`).

Design (TPU-first):
  - pre-LN blocks, GELU MLP — everything fuses into the two MXU matmuls
    per sublayer under XLA.
  - causal attention via the flash kernel: O(S·D) HBM traffic instead
    of an [S, S] score matrix.
  - `seq_axis` set ⇒ the module is running *inside* `shard_map` with
    its sequence dimension sharded: attention switches to the K/V ring
    (ICI neighbor exchange overlapped with compute) and position
    embeddings are offset by the shard's global position.
  - `model_axis` set ⇒ Megatron-style tensor parallelism: qkv and fc1
    are column-parallel (heads / ff dim sharded — the param arrays this
    module receives inside shard_map are the local shards), out and fc2
    are row-parallel with a `psum` forward; `tp_region` (identity
    forward, psum backward) guards each region entry so upstream
    LayerNorm/embedding gradients stay correct.  Composes freely with
    the seq ring (heads never communicate during attention).
  - optional `remat` wraps each block in `jax.checkpoint`, trading
    FLOPs for HBM (the standard long-context memory lever).
  - `remat_policy="dots"` is the selective variant: matmul outputs and
    the flash-attention output stay saved (no MXU work is recomputed),
    only LayerNorm/GELU/bias-add intermediates recompute in the
    backward.  Measured on v5e (flagship recipe): a cheaper *memory*
    lever than full remat — 131k vs 115k tokens/s at seq 2048 with
    temp buffers 8.7 vs 6.0 GB (no-remat: 141k at 9.7 GB) — but NOT
    faster than no-remat when memory fits: XLA:TPU materializes the
    recomputed elementwise ops rather than fusing them into consuming
    matmul operands.  On this chip the flagship fits un-remat'd through
    seq 32768, so both remat flavors exist for larger batches, more
    optimizer state, or smaller HBM (bench_lm `--variant remat_mem`
    carries the frontier's buffer table).

Use `param_partition_specs(params)` for the per-leaf PartitionSpecs
that shard a full (replicated-shape) param tree onto the 'model' axis.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from dtf_tpu.ops.flash_attention import flash_attention
from dtf_tpu.ops.paged_attention import (cached_attention,
                                         paged_attention_auto, write_pages)
from dtf_tpu.parallel.collectives import tp_psum, tp_region
from dtf_tpu.parallel.ring_attention import ring_attention


def remat_policy(name: str):
    """Named jax.checkpoint policies for the transformer families.

    "dots": save every dot_general result plus the flash-attention
    output (tagged `attn_out` in CausalSelfAttention) — nothing the MXU
    produced is recomputed; everything elementwise (LayerNorm, GELU,
    bias adds, residual sums) is, fused into the backward kernels."""
    if name == "dots":
        cp = jax.checkpoint_policies
        return cp.save_from_both_policies(
            cp.checkpoint_dots,
            # attn_out: the kernel output as seen by the block;
            # flash_out/flash_lse: the custom_vjp residuals named inside
            # ops.flash_attention._flash_fwd — without them the policy
            # would re-run the flash forward in the backward pass
            cp.save_only_these_names("attn_out", "flash_out", "flash_lse"))
    raise ValueError(f"unknown remat_policy {name!r}; choose 'dots'")


# dense fixed-window cache attention — shared with the paged gather
# path, single-sourced in ops.paged_attention
_cached_attention = cached_attention


class CausalSelfAttention(nn.Module):
    num_heads: int
    dtype: Any = jnp.float32
    seq_axis: Optional[str] = None   # set when seq dim is mesh-sharded
    model_axis: Optional[str] = None  # set when heads are mesh-sharded
    use_pallas: Any = None           # None=auto; False forces blockwise-JAX
    # serving: maintain a KV cache ('cache' collection) and attend
    # incrementally — see TransformerLM.decode
    decode: bool = False
    # paged KV cache (decode only): the cache is a SHARED page pool
    # [kv_pool_pages, kv_page_size, H, Dh] per K/V plus a caller-owned
    # block table — see TransformerLM.kv_page_size and
    # ops.paged_attention for the layout/invariants
    kv_page_size: Optional[int] = None
    kv_pool_pages: Optional[int] = None

    @nn.compact
    def __call__(self, x, cache_index=None, block_table=None,
                 flash_prefill: bool = False,
                 window_pages: Optional[int] = None):
        b, s, d = x.shape
        head_dim = d // self.num_heads
        heads = self.num_heads
        if self.decode and self.seq_axis is not None:
            # checked before the ring touches the (unbound) axis.
            # model_axis DOES compose with decode: serving tensor
            # parallelism shards heads (and the KV page pool's head
            # dim) over 'model' — the attention math is per-head, so
            # each shard decodes its local heads and the row-parallel
            # out projection psums exactly as in training
            raise ValueError(
                "decode mode (KV cache) does not compose with seq_axis "
                "sharding (ring attention)")
        if self.model_axis is not None:
            x = tp_region(x, self.model_axis)
            # lax.psum of a Python scalar is the static axis size, so
            # the local head count is a concrete feature dim
            mp = jax.lax.psum(1, self.model_axis)
            if heads % mp:
                raise ValueError(
                    f"num_heads {heads} not divisible by "
                    f"model_parallelism {mp}")
            heads //= mp
        qkv = nn.DenseGeneral((3, heads, head_dim), dtype=self.dtype,
                              name="qkv")(x)
        q, k, v = (qkv[..., i, :, :] for i in range(3))  # [B, S, Hloc, Dh]
        if self.decode and self.kv_page_size is not None:
            if cache_index is None or block_table is None:
                raise ValueError("paged decode mode needs cache_index [B] "
                                 "and block_table [B, M], both int32")
            # paged cache: one shared pool per K/V, sized by the module
            # attrs (NOT by the init call's shapes — admission capacity
            # is a pool property, not a per-slot reservation)
            pool_shape = (self.kv_pool_pages, self.kv_page_size,
                          heads, head_dim)
            paged_key = self.variable(
                "cache", "paged_key", jnp.zeros, pool_shape, k.dtype)
            paged_value = self.variable(
                "cache", "paged_value", jnp.zeros, pool_shape, v.dtype)
            if not self.is_initializing():
                # write-then-attend, same ordering contract as the
                # contiguous path below.  Prefill chunks (S a page
                # multiple; page-aligned starts by engine construction)
                # scatter whole pages; decode steps (S = 1) scatter
                # single token rows
                aligned = s > 1 and s % self.kv_page_size == 0
                paged_key.value = write_pages(
                    paged_key.value, k, block_table, cache_index,
                    page_aligned=aligned)
                paged_value.value = write_pages(
                    paged_value.value, v, block_table, cache_index,
                    page_aligned=aligned)
                if flash_prefill:
                    # first prefill chunk (cache_index == 0, engine
                    # invariant): there is no prefix to gather — the
                    # chunk IS the whole attended history, plain causal
                    # self-attention through the flash kernel at
                    # O(S·D) HBM traffic instead of an [S, L] gather
                    o = flash_attention(q, k, v, causal=True,
                                        use_pallas=self.use_pallas)
                else:
                    # paged_attention_auto: the Pallas flash-decode
                    # kernel on TPU (default-on — pages read through
                    # the block table in-kernel, no gathered window,
                    # window trim fused as a dynamic page skip), the
                    # gather oracle elsewhere.  window_pages (STATIC,
                    # decode.py computes it from the chunk's start)
                    # trims the GATHER path to the pages the chunk can
                    # actually see: continuation-chunk attention costs
                    # O(S · progress), so total prefill work is
                    # O(prompt²/2) regardless of the pool's logical
                    # capacity.  None (the decode step) attends the
                    # full per-slot window — lengths vary per row
                    o = paged_attention_auto(
                        q, paged_key.value, paged_value.value,
                        block_table, cache_index,
                        window_pages=window_pages,
                        use_pallas=self.use_pallas)
            else:
                # init trace: only the pool variables' shapes matter,
                # but keep the math valid (plain causal attention)
                o = flash_attention(q, k, v, causal=True,
                                    use_pallas=self.use_pallas)
        elif self.decode:
            if cache_index is None:
                raise ValueError("decode mode needs cache_index [B] int32")
            # cache capacity is fixed by the INIT call's sequence length
            # (the serving engine initializes with [B, max_seq] dummies);
            # subsequent applies write their S-token chunk at each row's
            # cache_index and attend q over the prefix — one code path
            # for prefill (S = padded prompt) and decode (S = 1)
            cached_key = self.variable(
                "cache", "cached_key", jnp.zeros,
                (b, s, heads, head_dim), k.dtype)
            cached_value = self.variable(
                "cache", "cached_value", jnp.zeros,
                (b, s, heads, head_dim), v.dtype)
            if not self.is_initializing():
                max_len = cached_key.value.shape[1]

                def write(cache, new, idx):
                    return jax.lax.dynamic_update_slice(
                        cache, new, (idx, 0, 0))

                cached_key.value = jax.vmap(write)(
                    cached_key.value, k, cache_index)
                cached_value.value = jax.vmap(write)(
                    cached_value.value, v, cache_index)
                # query i (global position idx+i) sees cache slots
                # j <= idx+i: the just-written chunk causally, the
                # prefix fully, and never the stale tail beyond idx+i
                # (overwritten before it can enter the mask)
                jpos = jnp.arange(max_len)[None, None, :]
                qpos = (cache_index[:, None, None]
                        + jnp.arange(s)[None, :, None])
                o = _cached_attention(q, cached_key.value,
                                      cached_value.value, jpos <= qpos)
            else:
                # init trace: only the cache variables' shapes matter,
                # but keep the math valid (plain causal attention)
                o = flash_attention(q, k, v, causal=True,
                                    use_pallas=self.use_pallas)
        elif self.seq_axis is not None:
            # sequence-parallel: K/V rotate around the 'seq' ring; every
            # query still attends to the full global sequence
            o = ring_attention(q, k, v, axis_name=self.seq_axis, causal=True)
        else:
            o = flash_attention(q, k, v, causal=True,
                                use_pallas=self.use_pallas)
        # tag for remat_policy="dots": the Pallas kernel's output is not
        # a dot_general, so checkpoint_dots alone would recompute the
        # whole flash forward in the backward pass — saving it by name
        # keeps the policy's "no MXU recompute" property
        o = checkpoint_name(o, "attn_out")
        o = o.reshape(b, s, -1)
        # row-parallel: each shard contributes its heads' slice; no bias
        # (a replicated bias would be summed mp times by the psum)
        out = nn.Dense(d, dtype=self.dtype, use_bias=False, name="out")(o)
        if self.model_axis is not None:
            # g operator: sum forward, identity backward (a raw psum
            # would scale cotangents by mp under shard_map AD)
            out = tp_psum(out, self.model_axis)
        return out


class Block(nn.Module):
    num_heads: int
    d_ff: int
    dtype: Any = jnp.float32
    seq_axis: Optional[str] = None
    model_axis: Optional[str] = None
    use_pallas: Any = None
    decode: bool = False
    kv_page_size: Optional[int] = None
    kv_pool_pages: Optional[int] = None

    @nn.compact
    def __call__(self, x, cache_index=None, block_table=None,
                 flash_prefill: bool = False,
                 window_pages: Optional[int] = None):
        d = x.shape[-1]
        h = nn.LayerNorm(dtype=self.dtype, name="ln1")(x)
        x = x + CausalSelfAttention(
            self.num_heads, dtype=self.dtype, seq_axis=self.seq_axis,
            model_axis=self.model_axis, use_pallas=self.use_pallas,
            decode=self.decode, kv_page_size=self.kv_page_size,
            kv_pool_pages=self.kv_pool_pages,
            name="attn")(h, cache_index, block_table, flash_prefill,
                         window_pages)
        h = nn.LayerNorm(dtype=self.dtype, name="ln2")(x)
        d_ff = self.d_ff
        if self.model_axis is not None:
            h = tp_region(h, self.model_axis)
            mp = jax.lax.psum(1, self.model_axis)
            if d_ff % mp:
                raise ValueError(
                    f"d_ff {d_ff} not divisible by model_parallelism {mp}")
            d_ff //= mp
        h = nn.Dense(d_ff, dtype=self.dtype, name="fc1")(h)  # column
        h = nn.gelu(h)
        h = nn.Dense(d, dtype=self.dtype, use_bias=False, name="fc2")(h)  # row
        if self.model_axis is not None:
            h = tp_psum(h, self.model_axis)  # g operator (see attn)
        return x + h


class TransformerLM(nn.Module):
    """Next-token LM.  __call__(tokens [B, S] int32, train) -> logits
    [B, S, vocab] (f32 — softmax precision, like the ResNets' fp32
    softmax cast, reference resnet_model.py:385-388)."""

    vocab_size: int
    num_layers: int = 12
    d_model: int = 512
    num_heads: int = 8
    d_ff: int = 2048
    max_seq_len: int = 2048
    dtype: Any = jnp.float32
    seq_axis: Optional[str] = None
    model_axis: Optional[str] = None
    # column-parallel lm_head over `model_axis`: this module then
    # returns LOCAL logits [B, S, V/mp] and the loss must be the
    # collective softmax CE (train.loop.sharded_cross_entropy) — the
    # full [B, S, V] logits never materialize (Megatron's
    # vocab-parallel output layer)
    shard_vocab: bool = False
    use_pallas: Any = None
    remat: bool = False
    # None = save everything jax's autodiff wants (plain remat if
    # `remat`); "dots" = selective remat per the module docstring
    remat_policy: Optional[str] = None
    # Serving mode (serve/decode.py drives this): every attention keeps
    # a KV cache in the 'cache' collection, sized by the INIT call's
    # sequence length, and __call__ takes `cache_index` [B] int32 — the
    # per-row write offset (each request's current length, which is what
    # makes slot-based continuous batching possible).  Composes with
    # model_axis (serving tensor parallelism: heads + KV pool sharded
    # over 'model', run inside shard_map — serve/decode.py Decoder);
    # incompatible with seq_axis sharding and shard_vocab.
    decode: bool = False
    # Paged KV cache (decode only; serve/decode.py Decoder drives it):
    # instead of a per-slot [B, max_seq_len] slab, every attention keeps
    # a SHARED [kv_pool_pages, kv_page_size, H, Dh] page pool per K/V,
    # and __call__ additionally takes `block_table` [B, M] int32 (the
    # engine-allocated page ids mapping each row's logical positions
    # into the pool — ops.paged_attention has the layout and the
    # scratch-page invariant) plus `flash_prefill` (static bool: the
    # chunk starts at position 0, so attention runs causal-only through
    # the flash kernel with no gather).  HBM then scales with tokens in
    # flight, not num_slots × max_seq_len.
    kv_page_size: Optional[int] = None
    kv_pool_pages: Optional[int] = None

    @nn.compact
    def __call__(self, tokens, train: bool = False, cache_index=None,
                 block_table=None, flash_prefill: bool = False,
                 window_pages: Optional[int] = None):
        del train  # no dropout/BN: LN only, same train/eval behavior
        b, s_local = tokens.shape
        x = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype,
                     name="embed")(tokens)
        # learned positions; under seq sharding each shard takes its
        # global slice of the table
        pos_table = self.param(
            "pos_embed", nn.initializers.normal(0.02),
            (self.max_seq_len, self.d_model))
        if self.decode:
            if self.shard_vocab:
                raise ValueError("decode mode does not compose with "
                                 "shard_vocab (single-device serving)")
            if cache_index is None:
                raise ValueError("decode mode needs cache_index [B] int32")
            if (self.kv_page_size is None) != (self.kv_pool_pages is None):
                raise ValueError(
                    "kv_page_size and kv_pool_pages must be set together "
                    "(both for the paged cache, neither for contiguous)")
            # per-row global positions; clamp so a padded prefill chunk
            # can't index past the table (those rows' logits are unused)
            pos_idx = jnp.minimum(
                cache_index[:, None] + jnp.arange(s_local)[None, :],
                self.max_seq_len - 1)
            pos = jnp.take(pos_table, pos_idx, axis=0)  # [B, S, d]
        else:
            offset = 0
            if self.seq_axis is not None:
                offset = jax.lax.axis_index(self.seq_axis) * s_local
            pos = jax.lax.dynamic_slice_in_dim(pos_table, offset, s_local)
        x = x + pos.astype(self.dtype)

        block = Block
        if self.remat_policy is not None:
            block = nn.remat(Block, policy=remat_policy(self.remat_policy))
        elif self.remat:
            block = nn.remat(Block)
        for i in range(self.num_layers):
            x = block(self.num_heads, self.d_ff, dtype=self.dtype,
                      seq_axis=self.seq_axis, model_axis=self.model_axis,
                      use_pallas=self.use_pallas, decode=self.decode,
                      kv_page_size=self.kv_page_size,
                      kv_pool_pages=self.kv_pool_pages,
                      name=f"block{i}")(x, cache_index, block_table,
                                        flash_prefill, window_pages)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        vocab = self.vocab_size
        if self.shard_vocab and self.model_axis is not None:
            mp = jax.lax.psum(1, self.model_axis)
            if vocab % mp:
                raise ValueError(
                    f"vocab_size {vocab} not divisible by "
                    f"model_parallelism {mp}")
            vocab //= mp
            # x is fully replicated here (the last block exited through
            # tp_psum) but its cotangent arrives vocab-shard-partial —
            # the f operator restores the full upstream gradient
            x = tp_region(x, self.model_axis)
        logits = nn.Dense(vocab, dtype=self.dtype, name="lm_head")(x)
        return logits.astype(jnp.float32)


def param_partition_specs(params, model_axis: str,
                          shard_vocab: bool = False):
    """PartitionSpec tree sharding a full TransformerLM param tree onto
    the tensor-parallel axis: qkv kernel/bias on the head dim, fc1
    kernel/bias on the ff dim, out/fc2 kernels on their input (row)
    dim, and (with ``shard_vocab``) the lm_head on its vocab (column)
    dim; everything else replicated."""
    from jax.sharding import PartitionSpec as P

    from dtf_tpu.models.partition import partition_specs

    def rule(keys, last, leaf):
        if "qkv" in keys:
            # kernel [d, 3, H, Dh] / bias [3, H, Dh]: shard H
            return (P(None, None, model_axis, None) if last == "kernel"
                    else P(None, model_axis, None))
        if "fc1" in keys:
            # kernel [d, ff] / bias [ff]: shard ff
            return (P(None, model_axis) if last == "kernel"
                    else P(model_axis))
        if ("out" in keys or "fc2" in keys) and last == "kernel":
            return P(model_axis, None)   # row-parallel input dim
        if shard_vocab and "lm_head" in keys:
            # kernel [d, V] / bias [V]: shard V (column-parallel)
            return (P(None, model_axis) if last == "kernel"
                    else P(model_axis))
        return P()

    return partition_specs(params, rule)
