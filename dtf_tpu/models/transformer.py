"""Decoder-only transformer LM — the long-context workload.

No reference equivalent (the reference is vision-only, SURVEY.md §5.7);
this is the model family that exercises the framework's first-class
long-context machinery: the Pallas flash-attention kernel
(`ops.flash_attention`) on a single chip, and ring attention over the
'seq' mesh axis (`parallel.ring_attention`) when the sequence dimension
is sharded (`--seq_parallelism N`).

Design (TPU-first):
  - pre-LN blocks, GELU MLP — everything fuses into the two MXU matmuls
    per sublayer under XLA.
  - causal attention via the flash kernel: O(S·D) HBM traffic instead
    of an [S, S] score matrix.
  - `seq_axis` set ⇒ the module is running *inside* `shard_map` with
    its sequence dimension sharded: attention switches to the K/V ring
    (ICI neighbor exchange overlapped with compute) and position
    embeddings are offset by the shard's global position.
  - `model_axis` set ⇒ Megatron-style tensor parallelism: qkv and fc1
    are column-parallel (heads / ff dim sharded — the param arrays this
    module receives inside shard_map are the local shards), out and fc2
    are row-parallel with a `psum` forward; `tp_region` (identity
    forward, psum backward) guards each region entry so upstream
    LayerNorm/embedding gradients stay correct.  Composes freely with
    the seq ring (heads never communicate during attention).
  - optional `remat` wraps each block in `jax.checkpoint`, trading
    FLOPs for HBM (the standard long-context memory lever).
  - `remat_policy="dots"` is the selective variant: matmul outputs and
    the flash-attention output stay saved (no MXU work is recomputed),
    only LayerNorm/GELU/bias-add intermediates recompute in the
    backward.  Measured on v5e (flagship recipe): a cheaper *memory*
    lever than full remat — 131k vs 115k tokens/s at seq 2048 with
    temp buffers 8.7 vs 6.0 GB (no-remat: 141k at 9.7 GB) — but NOT
    faster than no-remat when memory fits: XLA:TPU materializes the
    recomputed elementwise ops rather than fusing them into consuming
    matmul operands.  On this chip the flagship fits un-remat'd through
    seq 32768, so both remat flavors exist for larger batches, more
    optimizer state, or smaller HBM (bench_lm `--variant remat_mem`
    carries the frontier's buffer table).

Use `param_partition_specs(params)` for the per-leaf PartitionSpecs
that shard a full (replicated-shape) param tree onto the 'model' axis.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from dtf_tpu.ops.flash_attention import flash_attention
from dtf_tpu.parallel.collectives import tp_psum, tp_region
from dtf_tpu.parallel.ring_attention import ring_attention


def remat_policy(name: str):
    """Named jax.checkpoint policies for the transformer families.

    "dots": save every dot_general result plus the flash-attention
    output (tagged `attn_out` in CausalSelfAttention) — nothing the MXU
    produced is recomputed; everything elementwise (LayerNorm, GELU,
    bias adds, residual sums) is, fused into the backward kernels."""
    if name == "dots":
        cp = jax.checkpoint_policies
        return cp.save_from_both_policies(
            cp.checkpoint_dots,
            # attn_out: the kernel output as seen by the block;
            # flash_out/flash_lse: the custom_vjp residuals named inside
            # ops.flash_attention._flash_fwd — without them the policy
            # would re-run the flash forward in the backward pass
            cp.save_only_these_names("attn_out", "flash_out", "flash_lse"))
    raise ValueError(f"unknown remat_policy {name!r}; choose 'dots'")


def _cached_attention(q, k, v, mask):
    """Dense attention against a fixed-size KV cache.

    q [B, S, H, Dh] (S = the chunk being decoded), k/v [B, L, H, Dh]
    (L = the cache capacity), mask [B, S, L] True where the query may
    attend.  Scores/softmax run in f32 (the flash kernels' accumulator
    precision); masked positions get a large negative score, and the
    output is cast back to q's dtype.  At decode shapes (S ∈ {1, P},
    L fixed) the [S, L] score tile is small — no flash kernel needed."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    scores = jnp.where(mask[:, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return o.astype(q.dtype)


class CausalSelfAttention(nn.Module):
    num_heads: int
    dtype: Any = jnp.float32
    seq_axis: Optional[str] = None   # set when seq dim is mesh-sharded
    model_axis: Optional[str] = None  # set when heads are mesh-sharded
    use_pallas: Any = None           # None=auto; False forces blockwise-JAX
    # serving: maintain a KV cache ('cache' collection) and attend
    # incrementally — see TransformerLM.decode
    decode: bool = False

    @nn.compact
    def __call__(self, x, cache_index=None):
        b, s, d = x.shape
        head_dim = d // self.num_heads
        heads = self.num_heads
        if self.decode and (self.seq_axis is not None
                            or self.model_axis is not None):
            # checked before tp_region/psum touch the (unbound) axes
            raise ValueError(
                "decode mode (KV cache) is single-device: it does not "
                "compose with seq_axis/model_axis sharding")
        if self.model_axis is not None:
            x = tp_region(x, self.model_axis)
            # lax.psum of a Python scalar is the static axis size, so
            # the local head count is a concrete feature dim
            mp = jax.lax.psum(1, self.model_axis)
            if heads % mp:
                raise ValueError(
                    f"num_heads {heads} not divisible by "
                    f"model_parallelism {mp}")
            heads //= mp
        qkv = nn.DenseGeneral((3, heads, head_dim), dtype=self.dtype,
                              name="qkv")(x)
        q, k, v = (qkv[..., i, :, :] for i in range(3))  # [B, S, Hloc, Dh]
        if self.decode:
            if cache_index is None:
                raise ValueError("decode mode needs cache_index [B] int32")
            # cache capacity is fixed by the INIT call's sequence length
            # (the serving engine initializes with [B, max_seq] dummies);
            # subsequent applies write their S-token chunk at each row's
            # cache_index and attend q over the prefix — one code path
            # for prefill (S = padded prompt) and decode (S = 1)
            cached_key = self.variable(
                "cache", "cached_key", jnp.zeros,
                (b, s, heads, head_dim), k.dtype)
            cached_value = self.variable(
                "cache", "cached_value", jnp.zeros,
                (b, s, heads, head_dim), v.dtype)
            if not self.is_initializing():
                max_len = cached_key.value.shape[1]

                def write(cache, new, idx):
                    return jax.lax.dynamic_update_slice(
                        cache, new, (idx, 0, 0))

                cached_key.value = jax.vmap(write)(
                    cached_key.value, k, cache_index)
                cached_value.value = jax.vmap(write)(
                    cached_value.value, v, cache_index)
                # query i (global position idx+i) sees cache slots
                # j <= idx+i: the just-written chunk causally, the
                # prefix fully, and never the stale tail beyond idx+i
                # (overwritten before it can enter the mask)
                jpos = jnp.arange(max_len)[None, None, :]
                qpos = (cache_index[:, None, None]
                        + jnp.arange(s)[None, :, None])
                o = _cached_attention(q, cached_key.value,
                                      cached_value.value, jpos <= qpos)
            else:
                # init trace: only the cache variables' shapes matter,
                # but keep the math valid (plain causal attention)
                o = flash_attention(q, k, v, causal=True,
                                    use_pallas=self.use_pallas)
        elif self.seq_axis is not None:
            # sequence-parallel: K/V rotate around the 'seq' ring; every
            # query still attends to the full global sequence
            o = ring_attention(q, k, v, axis_name=self.seq_axis, causal=True)
        else:
            o = flash_attention(q, k, v, causal=True,
                                use_pallas=self.use_pallas)
        # tag for remat_policy="dots": the Pallas kernel's output is not
        # a dot_general, so checkpoint_dots alone would recompute the
        # whole flash forward in the backward pass — saving it by name
        # keeps the policy's "no MXU recompute" property
        o = checkpoint_name(o, "attn_out")
        o = o.reshape(b, s, -1)
        # row-parallel: each shard contributes its heads' slice; no bias
        # (a replicated bias would be summed mp times by the psum)
        out = nn.Dense(d, dtype=self.dtype, use_bias=False, name="out")(o)
        if self.model_axis is not None:
            # g operator: sum forward, identity backward (a raw psum
            # would scale cotangents by mp under shard_map AD)
            out = tp_psum(out, self.model_axis)
        return out


class Block(nn.Module):
    num_heads: int
    d_ff: int
    dtype: Any = jnp.float32
    seq_axis: Optional[str] = None
    model_axis: Optional[str] = None
    use_pallas: Any = None
    decode: bool = False

    @nn.compact
    def __call__(self, x, cache_index=None):
        d = x.shape[-1]
        h = nn.LayerNorm(dtype=self.dtype, name="ln1")(x)
        x = x + CausalSelfAttention(
            self.num_heads, dtype=self.dtype, seq_axis=self.seq_axis,
            model_axis=self.model_axis, use_pallas=self.use_pallas,
            decode=self.decode, name="attn")(h, cache_index)
        h = nn.LayerNorm(dtype=self.dtype, name="ln2")(x)
        d_ff = self.d_ff
        if self.model_axis is not None:
            h = tp_region(h, self.model_axis)
            mp = jax.lax.psum(1, self.model_axis)
            if d_ff % mp:
                raise ValueError(
                    f"d_ff {d_ff} not divisible by model_parallelism {mp}")
            d_ff //= mp
        h = nn.Dense(d_ff, dtype=self.dtype, name="fc1")(h)  # column
        h = nn.gelu(h)
        h = nn.Dense(d, dtype=self.dtype, use_bias=False, name="fc2")(h)  # row
        if self.model_axis is not None:
            h = tp_psum(h, self.model_axis)  # g operator (see attn)
        return x + h


class TransformerLM(nn.Module):
    """Next-token LM.  __call__(tokens [B, S] int32, train) -> logits
    [B, S, vocab] (f32 — softmax precision, like the ResNets' fp32
    softmax cast, reference resnet_model.py:385-388)."""

    vocab_size: int
    num_layers: int = 12
    d_model: int = 512
    num_heads: int = 8
    d_ff: int = 2048
    max_seq_len: int = 2048
    dtype: Any = jnp.float32
    seq_axis: Optional[str] = None
    model_axis: Optional[str] = None
    # column-parallel lm_head over `model_axis`: this module then
    # returns LOCAL logits [B, S, V/mp] and the loss must be the
    # collective softmax CE (train.loop.sharded_cross_entropy) — the
    # full [B, S, V] logits never materialize (Megatron's
    # vocab-parallel output layer)
    shard_vocab: bool = False
    use_pallas: Any = None
    remat: bool = False
    # None = save everything jax's autodiff wants (plain remat if
    # `remat`); "dots" = selective remat per the module docstring
    remat_policy: Optional[str] = None
    # Serving mode (serve/decode.py drives this): every attention keeps
    # a KV cache in the 'cache' collection, sized by the INIT call's
    # sequence length, and __call__ takes `cache_index` [B] int32 — the
    # per-row write offset (each request's current length, which is what
    # makes slot-based continuous batching possible).  Incompatible with
    # seq/model sharding and shard_vocab (decode is single-device).
    decode: bool = False

    @nn.compact
    def __call__(self, tokens, train: bool = False, cache_index=None):
        del train  # no dropout/BN: LN only, same train/eval behavior
        b, s_local = tokens.shape
        x = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype,
                     name="embed")(tokens)
        # learned positions; under seq sharding each shard takes its
        # global slice of the table
        pos_table = self.param(
            "pos_embed", nn.initializers.normal(0.02),
            (self.max_seq_len, self.d_model))
        if self.decode:
            if self.shard_vocab:
                raise ValueError("decode mode does not compose with "
                                 "shard_vocab (single-device serving)")
            if cache_index is None:
                raise ValueError("decode mode needs cache_index [B] int32")
            # per-row global positions; clamp so a padded prefill chunk
            # can't index past the table (those rows' logits are unused)
            pos_idx = jnp.minimum(
                cache_index[:, None] + jnp.arange(s_local)[None, :],
                self.max_seq_len - 1)
            pos = jnp.take(pos_table, pos_idx, axis=0)  # [B, S, d]
        else:
            offset = 0
            if self.seq_axis is not None:
                offset = jax.lax.axis_index(self.seq_axis) * s_local
            pos = jax.lax.dynamic_slice_in_dim(pos_table, offset, s_local)
        x = x + pos.astype(self.dtype)

        block = Block
        if self.remat_policy is not None:
            block = nn.remat(Block, policy=remat_policy(self.remat_policy))
        elif self.remat:
            block = nn.remat(Block)
        for i in range(self.num_layers):
            x = block(self.num_heads, self.d_ff, dtype=self.dtype,
                      seq_axis=self.seq_axis, model_axis=self.model_axis,
                      use_pallas=self.use_pallas, decode=self.decode,
                      name=f"block{i}")(x, cache_index)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        vocab = self.vocab_size
        if self.shard_vocab and self.model_axis is not None:
            mp = jax.lax.psum(1, self.model_axis)
            if vocab % mp:
                raise ValueError(
                    f"vocab_size {vocab} not divisible by "
                    f"model_parallelism {mp}")
            vocab //= mp
            # x is fully replicated here (the last block exited through
            # tp_psum) but its cotangent arrives vocab-shard-partial —
            # the f operator restores the full upstream gradient
            x = tp_region(x, self.model_axis)
        logits = nn.Dense(vocab, dtype=self.dtype, name="lm_head")(x)
        return logits.astype(jnp.float32)


def param_partition_specs(params, model_axis: str,
                          shard_vocab: bool = False):
    """PartitionSpec tree sharding a full TransformerLM param tree onto
    the tensor-parallel axis: qkv kernel/bias on the head dim, fc1
    kernel/bias on the ff dim, out/fc2 kernels on their input (row)
    dim, and (with ``shard_vocab``) the lm_head on its vocab (column)
    dim; everything else replicated."""
    from jax.sharding import PartitionSpec as P

    from dtf_tpu.models.partition import partition_specs

    def rule(keys, last, leaf):
        if "qkv" in keys:
            # kernel [d, 3, H, Dh] / bias [3, H, Dh]: shard H
            return (P(None, None, model_axis, None) if last == "kernel"
                    else P(None, model_axis, None))
        if "fc1" in keys:
            # kernel [d, ff] / bias [ff]: shard ff
            return (P(None, model_axis) if last == "kernel"
                    else P(model_axis))
        if ("out" in keys or "fc2" in keys) and last == "kernel":
            return P(model_axis, None)   # row-parallel input dim
        if shard_vocab and "lm_head" in keys:
            # kernel [d, V] / bias [V]: shard V (column-parallel)
            return (P(None, model_axis) if last == "kernel"
                    else P(model_axis))
        return P()

    return partition_specs(params, rule)
