"""Trivial throughput-probe model.

Parity with reference trivial_model.py:26-41: flatten → Dense(1) →
Dense(num_classes).  Exists to benchmark the input pipeline with
near-zero device compute (used via --use_trivial_model,
reference resnet_imagenet_main.py:189-191).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class TrivialModel(nn.Module):
    num_classes: int = 1001
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(1, dtype=self.dtype, param_dtype=jnp.float32, name="fc1")(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32, name="fc")(x)
        return x.astype(jnp.float32)
