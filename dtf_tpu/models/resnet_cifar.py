"""ResNet-(6n+2) family for 32×32 CIFAR, as a flax module.

Capability parity with reference resnet_cifar_model.py:
  - basic blocks (two 3×3 convs), stages of filters 16/32/64
    (resnet:192-256), stage widths: num_blocks each, strides 1/2/2
  - conv1: 3×3 stride 1, explicit (1,1) pad, no bias
  - BatchNorm momentum 0.997, eps 1e-5 (:34-35)
  - he_normal conv init; final Dense N(0, 0.01) with softmax (:247-252)
  - L2 weight decay 2e-4 on conv kernels + final dense kernel AND bias
    (:36, :250-251) as a loss term
  - the (6n+2) sizing: resnet20 (n=3), resnet32 (n=5), resnet56 (n=9);
    the reference also defines `resnet10 = partial(resnet, num_blocks=110)`
    which is actually ResNet-662 — a naming bug noted in SURVEY §2.1; we
    expose the honest `resnet110` (n=18) plus `resnet662` for strict parity.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

BATCH_NORM_DECAY = 0.997
BATCH_NORM_EPSILON = 1e-5

conv_init = nn.initializers.he_normal()
dense_init = nn.initializers.normal(stddev=0.01)


class BasicBlock(nn.Module):
    """identity_building_block / conv_building_block
    (resnet_cifar_model.py:39-155)."""
    filters: int
    strides: int = 1
    projection: bool = False
    dtype: Any = jnp.float32
    bn_axis: Any = None  # axis_name for cross-replica (sync) BN

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, kernel_init=conv_init,
                       padding="SAME", dtype=self.dtype, param_dtype=jnp.float32)
        bn = partial(nn.BatchNorm, use_running_average=not train,
                     axis_name=self.bn_axis,
                     momentum=BATCH_NORM_DECAY, epsilon=BATCH_NORM_EPSILON,
                     dtype=jnp.float32, param_dtype=jnp.float32)
        shortcut = x
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                 name="conv_a")(x)
        y = bn(name="bn_a")(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), name="conv_b")(y)
        y = bn(name="bn_b")(y)
        if self.projection:
            # reference conv_building_block shortcut: 1×1 conv + BN (:138-148)
            shortcut = conv(self.filters, (1, 1),
                            strides=(self.strides, self.strides),
                            name="conv_proj")(x)
            shortcut = bn(name="bn_proj")(shortcut)
        return nn.relu(y + shortcut.astype(y.dtype))


class CifarResNet(nn.Module):
    """Returns float32 logits of shape [batch, classes]."""
    num_blocks: int = 9
    num_classes: int = 10
    dtype: Any = jnp.float32
    bn_axis: Any = None  # axis_name for cross-replica (sync) BN

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(16, (3, 3), strides=(1, 1), padding=[(1, 1), (1, 1)],
                    use_bias=False, kernel_init=conv_init, dtype=self.dtype,
                    param_dtype=jnp.float32, name="conv1")(x)
        x = nn.BatchNorm(use_running_average=not train,
                         axis_name=self.bn_axis,
                         momentum=BATCH_NORM_DECAY, epsilon=BATCH_NORM_EPSILON,
                         dtype=jnp.float32, param_dtype=jnp.float32,
                         name="bn_conv1")(x)
        x = nn.relu(x)

        for s, (filters, stride) in enumerate(((16, 1), (32, 2), (64, 2)), start=2):
            x = BasicBlock(filters, strides=stride, projection=True,
                           dtype=self.dtype, bn_axis=self.bn_axis, name=f"stage{s}_block0")(x, train=train)
            for b in range(1, self.num_blocks):
                x = BasicBlock(filters, dtype=self.dtype, bn_axis=self.bn_axis,
                               name=f"stage{s}_block{b}")(x, train=train)

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, kernel_init=dense_init,
                     dtype=self.dtype, param_dtype=jnp.float32, name="fc")(x)
        return x.astype(jnp.float32)


resnet20 = partial(CifarResNet, num_blocks=3)
resnet32 = partial(CifarResNet, num_blocks=5)
resnet56 = partial(CifarResNet, num_blocks=9)
resnet110 = partial(CifarResNet, num_blocks=18)
# strict parity with the reference's misnamed "resnet10" (num_blocks=110)
resnet662 = partial(CifarResNet, num_blocks=110)
