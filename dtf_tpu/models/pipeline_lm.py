"""Pipeline-parallel decoder-only LM.

The transformer blocks are *stacked*: every block parameter carries a
leading layer dimension, sharded over the pipeline axis (the 'model'
mesh axis) via ``pipeline_param_partition_specs`` — stage j's device
holds only its ``num_layers / pp`` blocks, so model depth scales
linearly with the pipeline length.  Inside ``shard_map`` the stage is a
``lax.scan`` over the local block stack, and stages exchange
activations through ``parallel.pipeline.pipeline_spmd`` (GPipe schedule
over ``lax.ppermute``).

The blocks are implemented in raw JAX (explicit ``self.param`` tensors
+ functional layer math) rather than nested flax modules: the stage
body runs under two levels of ``lax.scan`` (layers × pipeline ticks)
where explicit parameter pytrees are the natural representation.

Replicated-parameter gradients under PP use two tricks, both free of
Trainer special-casing:
  - embedding/positional params: only stage 0's embedding output feeds
    the pipeline, so its cotangent lives on stage 0 alone.  Wrapping
    the embedded input in ``tp_region`` (identity forward, psum
    backward) hands every stage the same output-cotangent, and since
    every stage computed the identical embedding forward, all stages
    derive identical (correct) embedding grads — replicas stay in sync.
  - final-norm/lm-head params: the pipeline output is mask-psum
    broadcast (``last_stage_broadcast``) before the head, so every
    stage computes the head on identical inputs and gets identical
    grads directly.

Why every stage recomputes the head (vs last-stage-only + logits
broadcast): broadcasting [b,s,V] logits costs 4·b·s·V bytes of ICI
while recomputing costs 2·b·s·d·V MXU flops — per logit element that is
4 bytes of ICI (~10s of GB/s per link) vs 2·d flops (~100s of TFLOP/s);
for any d ≥ a few hundred the recompute is faster and removes a
serialization point.  The [b,s,d] broadcast before the head is the
cheap one.  The GPipe bubble is attacked where the SPMD formulation
allows: the runner auto-scales num_microbatches to 4·pp (bubble
(pp-1)/(M+pp-1) ≤ ~20%); per-tick idle-stage compute skipping would
need per-device control flow that SPMD scan cannot express.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from dtf_tpu.ops.flash_attention import flash_attention
from dtf_tpu.parallel.collectives import tp_region
from dtf_tpu.parallel.pipeline import (last_stage_broadcast, pipeline_spmd,
                                       pipeline_spmd_interleaved)

# parameter names that carry a leading stacked-layer dimension
BLOCK_PARAMS = ("ln1_s", "ln1_b", "qkv_k", "qkv_b", "out_k", "out_b",
                "ln2_s", "ln2_b", "fc1_k", "fc1_b", "fc2_k", "fc2_b")


def _layernorm(x, scale, bias, eps: float = 1e-6):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    y = (xf - mu) * lax.rsqrt(var + eps)
    y = y * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    return y.astype(x.dtype)


class PipelinedTransformerLM(nn.Module):
    """Next-token LM with pipeline-stacked blocks.

    ``pipe_axis`` names the mesh axis whose shards are pipeline stages
    (None: all blocks run locally in sequence — the single-device
    twin, numerically identical).  ``num_layers`` must divide evenly by
    the axis size; the scan length is taken from the parameter shapes,
    so the same module works on full or stage-local stacks."""

    vocab_size: int
    num_layers: int = 12
    d_model: int = 512
    num_heads: int = 8
    d_ff: int = 2048
    max_seq_len: int = 2048
    num_microbatches: int = 4
    dtype: Any = jnp.float32
    pipe_axis: Optional[str] = None
    use_pallas: Any = None
    remat: bool = False
    # selective remat ("dots", models/transformer.py remat_policy):
    # the raw-matmul blocks here are dot_generals, so the policy saves
    # exactly the matmul + flash outputs and recomputes the rest
    remat_policy: Optional[str] = None
    # interleave=2: two virtual stages per device (Megatron-style) —
    # the stage's local block stack splits into two chunks and each
    # microbatch circles the ring twice, halving the fill/drain bubble
    # at equal num_microbatches (parallel.pipeline docstring).  The
    # depth-order then visits global layers chunk-interleaved, so the
    # single-device twin needs `interleave_pp` (the logical pipeline
    # length) to reproduce the identical visitation order off-mesh.
    interleave: int = 1
    interleave_pp: Optional[int] = None

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        del train  # LN only — same train/eval behavior
        b, s = tokens.shape
        d, heads = self.d_model, self.num_heads
        dh = d // heads
        layers = self.num_layers
        if self.pipe_axis is not None:
            # inside shard_map each stage holds (and declares) only its
            # local slice of the stacked block params
            pp = lax.psum(1, self.pipe_axis)  # static axis size
            if layers % pp:
                raise ValueError(
                    f"num_layers {layers} not divisible by pipeline "
                    f"length {pp}")
            layers //= pp
        init = nn.initializers
        k_init = init.lecun_normal(batch_axis=(0,))

        embed = self.param("embed", init.normal(0.02), (self.vocab_size, d))
        pos = self.param("pos_embed", init.normal(0.02),
                         (self.max_seq_len, d))
        blocks = dict(
            ln1_s=self.param("ln1_s", init.ones, (layers, d)),
            ln1_b=self.param("ln1_b", init.zeros, (layers, d)),
            qkv_k=self.param("qkv_k", k_init, (layers, d, 3 * heads * dh)),
            qkv_b=self.param("qkv_b", init.zeros, (layers, 3 * heads * dh)),
            out_k=self.param("out_k", k_init, (layers, heads * dh, d)),
            out_b=self.param("out_b", init.zeros, (layers, d)),
            ln2_s=self.param("ln2_s", init.ones, (layers, d)),
            ln2_b=self.param("ln2_b", init.zeros, (layers, d)),
            fc1_k=self.param("fc1_k", k_init, (layers, d, self.d_ff)),
            fc1_b=self.param("fc1_b", init.zeros, (layers, self.d_ff)),
            fc2_k=self.param("fc2_k", k_init, (layers, self.d_ff, d)),
            fc2_b=self.param("fc2_b", init.zeros, (layers, d)),
        )
        ln_f_s = self.param("ln_f_s", init.ones, (d,))
        ln_f_b = self.param("ln_f_b", init.zeros, (d,))
        head_k = self.param("head_k", init.lecun_normal(),
                            (d, self.vocab_size))
        head_b = self.param("head_b", init.zeros, (self.vocab_size,))

        dtype = self.dtype
        use_pallas = self.use_pallas

        def block_step(h, p):
            """One pre-LN transformer block on [mb, s, d]."""
            bsz = h.shape[0]
            hn = _layernorm(h, p["ln1_s"], p["ln1_b"])
            qkv = hn @ p["qkv_k"].astype(dtype) + p["qkv_b"].astype(dtype)
            qkv = qkv.reshape(bsz, s, 3, heads, dh)
            q, k, v = (qkv[..., i, :, :] for i in range(3))
            o = flash_attention(q, k, v, causal=True, use_pallas=use_pallas)
            o = o.reshape(bsz, s, heads * dh)
            h = h + (o @ p["out_k"].astype(dtype) + p["out_b"].astype(dtype))
            hn = _layernorm(h, p["ln2_s"], p["ln2_b"])
            f = nn.gelu(hn @ p["fc1_k"].astype(dtype)
                        + p["fc1_b"].astype(dtype))
            return h + (f @ p["fc2_k"].astype(dtype)
                        + p["fc2_b"].astype(dtype))

        if self.remat_policy is not None:
            from dtf_tpu.models.transformer import remat_policy
            step = jax.checkpoint(
                block_step, policy=remat_policy(self.remat_policy))
        elif self.remat:
            step = jax.checkpoint(block_step)
        else:
            step = block_step

        if self.interleave not in (1, 2):
            raise ValueError(f"interleave must be 1 or 2, got "
                             f"{self.interleave}")

        def stage_fn(h):
            # scan over this shard's block stack (leading dim of the
            # received params — full depth off-mesh, depth/pp on it)
            h, _ = lax.scan(lambda c, p: (step(c, p), None), h, blocks)
            return h

        def stage_fn_chunk(h, chunk):
            # interleaved: run only this lap's half of the local stack
            local = jax.tree_util.tree_leaves(blocks)[0].shape[0]
            half = local // 2
            part = jax.tree_util.tree_map(
                lambda a: lax.dynamic_slice_in_dim(a, chunk * half, half,
                                                   axis=0), blocks)
            h, _ = lax.scan(lambda c, p: (step(c, p), None), h, part)
            return h

        x = embed[tokens].astype(dtype) + pos[:s].astype(dtype)
        if self.pipe_axis is None:
            if self.interleave == 2:
                # reproduce the interleaved visitation order off-mesh:
                # lap 0 chunks of every stage, then lap 1 chunks
                pp = self.interleave_pp
                if not pp or layers % (2 * pp):
                    raise ValueError(
                        "interleave=2 off-mesh needs interleave_pp with "
                        "num_layers divisible by 2*interleave_pp")
                per, half = layers // pp, layers // pp // 2
                order = jnp.array(
                    [dev * per + lap * half + i
                     for lap in range(2) for dev in range(pp)
                     for i in range(half)])
                blocks = jax.tree_util.tree_map(lambda a: a[order], blocks)
            h = stage_fn(x)
        else:
            if b % self.num_microbatches:
                raise ValueError(
                    f"per-shard batch {b} not divisible by "
                    f"num_microbatches {self.num_microbatches}")
            # identity forward / psum backward: keeps embedding grads
            # identical across stages (see module docstring)
            x = tp_region(x, self.pipe_axis)
            mb = b // self.num_microbatches
            xmb = x.reshape(self.num_microbatches, mb, s, d)
            if self.interleave == 2:
                if layers % 2:
                    raise ValueError(
                        f"interleave=2 needs an even per-stage layer "
                        f"count, got {layers}")
                h = pipeline_spmd_interleaved(stage_fn_chunk, xmb,
                                              self.pipe_axis)
            else:
                h = pipeline_spmd(stage_fn, xmb, self.pipe_axis)
            h = last_stage_broadcast(h.reshape(b, s, d), self.pipe_axis)
        h = _layernorm(h, ln_f_s, ln_f_b)
        logits = h @ head_k.astype(dtype) + head_b.astype(dtype)
        return logits.astype(jnp.float32)


def pipeline_param_partition_specs(params, pipe_axis: str):
    """PartitionSpec tree: stacked block params shard their layer dim
    over the pipeline axis; embedding/head/final-norm replicated."""
    from jax.sharding import PartitionSpec as P

    from dtf_tpu.models.partition import partition_specs

    def rule(keys, last, leaf):
        if last in BLOCK_PARAMS:
            return P(pipe_axis, *([None] * (leaf.ndim - 1)))
        return P()

    return partition_specs(params, rule)
