"""Deterministic fault injection — the chaos layer.

On TPU pods preemption and rank loss are routine; a recovery story that
is never exercised is a recovery story that does not work.  This
package turns "kill a process and see" into a first-class, reproducible
experiment: a fault spec names exactly which failure fires, on which
rank, at which point of the run — and the test suite / CI chaos smoke
stage replays it deterministically.

Shaped like ``obs/trace``: a module-level injector that is ``None``
unless configured, so every probe is a single attribute read + compare
when chaos is off — provably no behavior or cost on production runs
(pinned by tests/test_chaos.py).

Spec grammar (``--fault`` flag or the ``DTF_FAULT`` env var the
launcher forwards; comma-separated specs compose)::

    spec  := kind "@" [ selector ":" ] point
    selector := "rank" INT | "replica" INT
    point := "step" ":" INT | "version" ":" INT | "batch" ":" INT
             | "req" ":" INT | "latest" | INT-or-FLOAT

The ``rank`` selector picks which PROCESS a fault fires in; the
``replica`` selector names which serving replica a distributed fault
TARGETS (the fault itself fires where the observation point lives —
``net_partition``/``replica_kill`` fire in the router, ``slow_replica``
in the targeted replica process, where replica id == DTF_PROCESS_ID).
The bare numeric point form is the distributed kinds' shorthand:
``net_partition@replica1:6`` means 6 probe ticks.

Kinds and their firing semantics:

  crash@step:N            hard process death (os._exit) at the train
                          step-N boundary — fires on EXACT step match,
                          so a run resumed at/past N does not re-die.
                          Exit code EXIT_INJECTED_CRASH (77): the
                          supervisor classifies it as a budgeted crash.
  sigterm@step:N          delivers SIGTERM to the process itself at the
                          step-N boundary (exact match) — exercises the
                          preemption path: emergency checkpoint +
                          EXIT_PREEMPTED (75) + unbudgeted restart.
  heartbeat_stall@step:N  from step N on, heartbeat files silently stop
                          being written (latched) — the deadlocked-but-
                          alive signature the supervisor's heartbeat
                          watchdog exists to catch.
  ps_drop@version:N       the PS client closes its store connection
                          once its observed store version reaches N
                          (one-shot) — exercises reconnect + backoff.
  ckpt_truncate@latest    truncates a payload file of the NEWEST
                          checkpoint step before the next restore
                          (one-shot) — exercises the integrity manifest
                          fallback to the previous verified step.
  device_loss@step:N      the rank's accelerators vanish at the step-N
                          boundary (exact match): the process exits
                          EXIT_DEVICE_LOST (76) — the host survives but
                          its chips are gone (a pod-slice preemption, a
                          PCIe/ICI fault).  Under `launch.py --elastic`
                          the supervisor RESHARDS: it relaunches on the
                          surviving topology at the last checkpoint
                          instead of burning the crash-restart budget.
  host_loss@[rankK:]step:N  the whole host vanishes at the step-N
                          boundary (exact match): the rank SIGKILLs
                          itself — death by an UNPROMPTED SIGKILL, the
                          rank-exit pattern of a host disappearing
                          (OOM-killer, infra teardown), which the
                          supervisor classifies as host loss (no python
                          crash exits via SIGKILL on its own).  Elastic
                          supervisors drop the lost host's devices and
                          resume smaller.
  reader_crash@batch:N    SIGKILLs the data-service shard worker that
                          owns merged batch N, as the consumer reaches
                          that batch (exact match, one-shot) — the
                          service supervisor must respawn the worker at
                          its recorded per-shard position and the
                          merged stream must be unchanged
                          (dtf_tpu/data/service).
  replica_kill@req:N      the serving ROUTER SIGKILLs a replica as it
                          dispatches its Nth request (exact match,
                          one-shot) — by default the replica that Nth
                          request was just routed to; an explicit
                          ``replica<K>`` selector overrides the target
                          (``replica_kill@replica0:req:3``).  The
                          router must re-dispatch the dead replica's
                          in-flight requests token-exactly and respawn
                          it under the restart budget.
  net_partition@replicaK:D  the router's health PROBES of replica K are
                          dropped for D consecutive probe ticks,
                          starting at the first probe after traffic
                          began — the router sees silence (a partition
                          or stalled host), NOT a clean exit; the
                          replica process itself stays healthy and must
                          re-register when the partition heals.
  slow_replica@replicaK:F replica K's decode steps run F× slower
                          (latched; the engine sleeps (F−1)× each
                          measured step) — the straggler signature the
                          router's deadline + least-loaded placement
                          must absorb.
  page_fetch_stall@replicaK:S  replica K's KV-page migration CLIENT
                          stalls S seconds before each page_fetch
                          window request (latched) — the slow-fabric
                          signature the disaggregated router's
                          migration timeout + local-prefill fallback
                          must absorb without losing a request.
  router_kill@req:N       the serving ROUTER itself dies uncleanly
                          (os._exit, no drain, no journal sync) as it
                          performs its Nth dispatch (exact match,
                          one-shot) — in-process tiers substitute the
                          router's ``crash_hook``.  The HA standby
                          (serve/ha.py) must take over: replay the
                          request journal, fence the dead leader's
                          epoch, and re-adopt every in-flight request
                          exactly-once (zero lost, zero replica
                          respawns).
  lease_stall@T           the leader's lease RENEWALS are silently
                          dropped for T consecutive renewal attempts
                          (countdown, starts at the first renewal
                          after arming) — the leader freezes without
                          dying, its lease expires, the standby takes
                          over, and the old leader must come back
                          FENCED (stale-epoch rejected), not resume
                          control.  The split-brain drill.
  rollout_kill@phase:P    the rollout controller (serve/rollout.py)
                          SIGKILLs a replica as the rollout works in
                          phase P ∈ {canary, rolling} (one-shot; an
                          explicit ``replica<K>`` selector overrides
                          the default target — the replica the phase
                          is currently operating on).  The rollout
                          must detect the instability, abort, and
                          ROLL BACK with the fleet token-exact on the
                          old model and zero lost requests.

Every fired fault emits a structured ``injected_fault`` anomaly record
through obs.trace (flushed before dying), so
``trace_main --check --allow injected_fault`` can assert a chaos run
contained the injected fault and nothing else.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal
import sys
import threading
from typing import List, Optional

log = logging.getLogger("dtf_tpu")

# Exit-code contract with the launch.py supervisor (which is stdlib-only
# by design and carries its own copy; parity is test-pinned).
EXIT_PREEMPTED = 75        # EX_TEMPFAIL: graceful preemption checkpoint
EXIT_DEVICE_LOST = 76      # accelerators gone, host alive: the elastic
                           # supervisor reshards instead of budgeting it
                           # as a crash (train/elastic.py owns the
                           # canonical constant; parity test-pinned)
EXIT_INJECTED_CRASH = 77   # injected hard crash (budgeted restart)

KINDS = ("crash", "sigterm", "heartbeat_stall", "ps_drop", "ckpt_truncate",
         "reader_crash", "replica_kill", "net_partition", "slow_replica",
         "rollout_kill", "device_loss", "host_loss", "page_fetch_stall",
         "router_kill", "lease_stall")
_POINTS = {
    "crash": "step",
    "sigterm": "step",
    "device_loss": "step",
    "host_loss": "step",
    "heartbeat_stall": "step",
    "ps_drop": "version",
    "ckpt_truncate": "latest",
    "reader_crash": "batch",
    "replica_kill": "req",
    "net_partition": "ticks",
    "slow_replica": "factor",
    "rollout_kill": "phase",
    "page_fetch_stall": "seconds",
    "router_kill": "req",
    "lease_stall": "ticks",
}
# rollout_kill's point value is a PHASE NAME, not a number
ROLLOUT_PHASES = ("canary", "rolling")
# distributed kinds whose point accepts the bare-value shorthand
# (net_partition@replica1:6) and which require/allow a replica target
_REPLICA_REQUIRED = ("net_partition", "slow_replica", "page_fetch_stall")
_BARE_POINT = ("net_partition", "slow_replica", "page_fetch_stall",
               "lease_stall")
# kinds whose point value is a float (everything else is an int)
_FLOAT_POINT = ("slow_replica", "page_fetch_stall")

_injector: Optional["Injector"] = None
_lock = threading.Lock()


@dataclasses.dataclass
class FaultSpec:
    kind: str
    rank: Optional[int]     # None = every rank
    value: Optional[float]  # None for point "latest"; float only for
                            # slow_replica's factor, int otherwise
    replica: Optional[int] = None  # distributed kinds: target replica
    label: Optional[str] = None    # rollout_kill: the phase name
    fired: bool = False

    @property
    def point(self) -> str:
        return _POINTS[self.kind]

    def __str__(self) -> str:
        sel = ""
        if self.rank is not None:
            sel = f"rank{self.rank}:"
        elif self.replica is not None:
            sel = f"replica{self.replica}:"
        if self.label is not None:
            p = f"{self.point}:{self.label}"
        elif self.value is None:
            p = "latest"
        else:
            v = (self.value if self.kind in _FLOAT_POINT
                 else int(self.value))
            p = f"{self.point}:{v}"
        return f"{self.kind}@{sel}{p}"


def parse_spec(text: str) -> List[FaultSpec]:
    """Parse a comma-separated fault spec string; raises ValueError with
    the offending token on any grammar violation (a typo'd fault that
    silently never fires would invalidate the whole experiment)."""
    out: List[FaultSpec] = []
    for tok in (t.strip() for t in text.split(",")):
        if not tok:
            continue
        if "@" not in tok:
            raise ValueError(f"fault spec {tok!r}: expected kind@point")
        kind, _, point = tok.partition("@")
        if kind not in KINDS:
            raise ValueError(
                f"fault spec {tok!r}: unknown kind {kind!r} "
                f"(choose from {KINDS})")
        rank: Optional[int] = None
        replica: Optional[int] = None
        if point.startswith("rank"):
            rtok, _, point = point.partition(":")
            try:
                rank = int(rtok[4:])
            except ValueError:
                raise ValueError(
                    f"fault spec {tok!r}: bad rank selector {rtok!r}")
        elif point.startswith("replica"):
            rtok, _, point = point.partition(":")
            try:
                replica = int(rtok[7:])
            except ValueError:
                raise ValueError(
                    f"fault spec {tok!r}: bad replica selector {rtok!r}")
        if kind in _REPLICA_REQUIRED and replica is None:
            raise ValueError(
                f"fault spec {tok!r}: {kind} needs a replica<K> selector "
                f"(which replica to target)")
        want = _POINTS[kind]
        if want == "latest":
            if point != "latest":
                raise ValueError(
                    f"fault spec {tok!r}: {kind} takes the point 'latest'")
            out.append(FaultSpec(kind, rank, None, replica=replica))
            continue
        if want == "phase":
            sel, _, val = point.partition(":")
            if sel != "phase" or val not in ROLLOUT_PHASES:
                raise ValueError(
                    f"fault spec {tok!r}: {kind} takes "
                    f"'phase:<{'|'.join(ROLLOUT_PHASES)}>'")
            out.append(FaultSpec(kind, rank, None, replica=replica,
                                 label=val))
            continue
        sel, _, val = point.partition(":")
        if not val and kind in _BARE_POINT:
            # bare-value shorthand: net_partition@replica1:6
            sel, val = want, sel
        if sel != want or not val:
            hint = (f"'{want}:<value>' or a bare value"
                    if kind in _BARE_POINT else f"'{want}:<int>'")
            raise ValueError(f"fault spec {tok!r}: {kind} takes {hint}")
        try:
            value = (float(val) if kind in _FLOAT_POINT else int(val))
        except ValueError:
            raise ValueError(f"fault spec {tok!r}: {val!r} is not a number")
        if kind == "slow_replica":
            if value <= 1.0:
                raise ValueError(
                    f"fault spec {tok!r}: slow-down factor must be > 1")
        elif kind == "page_fetch_stall":
            if value <= 0.0:
                raise ValueError(
                    f"fault spec {tok!r}: stall needs > 0 seconds")
        elif kind == "net_partition":
            if value < 1:
                raise ValueError(
                    f"fault spec {tok!r}: partition needs >= 1 probe tick")
        elif kind == "lease_stall":
            if value < 1:
                raise ValueError(
                    f"fault spec {tok!r}: lease stall needs >= 1 "
                    f"renewal tick")
        elif value < 0:
            raise ValueError(f"fault spec {tok!r}: value must be >= 0")
        out.append(FaultSpec(kind, rank, value, replica=replica))
    return out


class Injector:
    """Holds the armed fault specs for THIS rank and fires them at the
    probe points.  Each spec fires at most once per process."""

    def __init__(self, specs: List[FaultSpec], rank: int = 0):
        self.rank = int(rank)
        self.specs = [s for s in specs
                      if s.rank is None or s.rank == self.rank]
        self._mu = threading.Lock()
        # net_partition bookkeeping: spec index -> remaining probe ticks
        # (None until the partition starts)
        self._partition_left: dict = {}
        # lease_stall bookkeeping: spec index -> remaining renewal ticks
        self._stall_left: dict = {}

    def _armed(self, kind: str):
        return [s for s in self.specs if s.kind == kind and not s.fired]

    # -- firing helpers -------------------------------------------------
    def _record(self, spec: FaultSpec, **attrs) -> None:
        # lazy import: chaos stays stdlib-light so the supervisor-side
        # tests and early process bootstrap can import it freely
        from dtf_tpu.obs import trace
        spec.fired = True
        log.error("chaos: firing injected fault %s %s", spec, attrs)
        # "fault_kind", not "kind": the record's own "kind" field is the
        # span/event/anomaly discriminator and must not be clobbered
        trace.anomaly("injected_fault", fault=str(spec),
                      fault_kind=spec.kind, **attrs)
        trace.flush()

    # -- probe points ---------------------------------------------------
    def step(self, step: int) -> None:
        """Train/PS-worker step-boundary probe.  EXACT-match semantics:
        a resumed run whose restored step is at/past the fault value
        must not re-fire it (or a deterministic fault would crash-loop
        the supervisor's whole restart budget away)."""
        step = int(step)
        with self._mu:
            for spec in self._armed("crash"):
                if step == spec.value:
                    self._record(spec, step=step)
                    # emulate hard death: no atexit, no finally blocks —
                    # exactly what a segfault/OOM-kill looks like to the
                    # supervisor (minus this distinct exit code)
                    os._exit(EXIT_INJECTED_CRASH)
            for spec in self._armed("sigterm"):
                if step == spec.value:
                    self._record(spec, step=step)
                    # the preemption signal, delivered for real so the
                    # actual production handler path runs
                    os.kill(os.getpid(), signal.SIGTERM)
            for spec in self._armed("device_loss"):
                if step == spec.value:
                    self._record(spec, step=step)
                    # accelerator loss: the runtime is gone but the host
                    # can still report it — the distinct exit code the
                    # elastic supervisor reshards on (no atexit/finally,
                    # like a runtime abort)
                    os._exit(EXIT_DEVICE_LOST)
            for spec in self._armed("host_loss"):
                if step == spec.value:
                    self._record(spec, step=step)
                    # the whole host vanishes: death by SIGKILL, which
                    # the supervisor reads as an UNPROMPTED kill (the
                    # host-loss rank-exit pattern — a python crash
                    # cannot exit via SIGKILL by itself)
                    os.kill(os.getpid(), signal.SIGKILL)

    def heartbeat_stalled(self, step: Optional[int]) -> bool:
        """True once a heartbeat_stall fault latched (permanent: a
        deadlocked rank does not recover by itself)."""
        with self._mu:
            for spec in self.specs:
                if spec.kind != "heartbeat_stall":
                    continue
                if spec.fired:
                    return True
                if step is not None and int(step) >= spec.value:
                    self._record(spec, step=int(step))
                    return True
        return False

    def ps_drop(self, version: int) -> bool:
        """One-shot: True when the PS client should drop its connection
        (observed store version reached the spec value)."""
        with self._mu:
            for spec in self._armed("ps_drop"):
                if int(version) >= spec.value:
                    self._record(spec, version=int(version))
                    return True
        return False

    def reader_crash(self, batch: int) -> bool:
        """One-shot, EXACT-match: True when the data-service consumer
        reaching merged batch `batch` should kill the owning shard
        worker.  Exact match for the same reason as step(): a resumed
        run positioned at/past the batch must not re-fire."""
        with self._mu:
            for spec in self._armed("reader_crash"):
                if int(batch) == spec.value:
                    self._record(spec, batch=int(batch))
                    return True
        return False

    def ckpt_truncate(self) -> bool:
        """One-shot: True when the next restore should first truncate
        the newest checkpoint step (the torn-write simulation)."""
        with self._mu:
            for spec in self._armed("ckpt_truncate"):
                self._record(spec)
                return True
        return False

    # -- distributed serving faults (dtf_tpu/serve/router.py) -----------
    def replica_kill(self, req_seq: int,
                     dispatched_to: int) -> Optional[int]:
        """Router-side, one-shot, EXACT-match on the dispatch sequence
        number: returns the replica id to SIGKILL when the router's
        ``req_seq``-th dispatch should trigger the kill — the explicit
        ``replica<K>`` target if the spec named one, else the replica
        this request was just routed to.  None = don't fire."""
        with self._mu:
            for spec in self._armed("replica_kill"):
                if int(req_seq) == spec.value:
                    target = (spec.replica if spec.replica is not None
                              else int(dispatched_to))
                    self._record(spec, req=int(req_seq), replica=target)
                    return target
        return None

    def net_partition(self, replica: int, traffic_started: bool) -> bool:
        """Router-side, called ONCE per health-probe tick per replica:
        True while the probe of ``replica`` should be dropped.  The
        partition starts at the first probe tick after traffic began
        (so it always lands mid-traffic) and lasts ``value`` ticks,
        then heals — the replica process never died, so it must
        re-register and take traffic again."""
        with self._mu:
            for i, spec in enumerate(self.specs):
                if spec.kind != "net_partition" or spec.replica != int(
                        replica):
                    continue
                left = self._partition_left.get(i)
                if left is None:
                    if not traffic_started:
                        continue
                    left = int(spec.value)
                    self._record(spec, replica=int(replica),
                                 ticks=left)
                if left <= 0:
                    continue    # healed
                self._partition_left[i] = left - 1
                return True
        return False

    def router_kill(self, req_seq: int) -> bool:
        """Router-side, one-shot, EXACT-match on the dispatch sequence
        number: True when the router should die uncleanly at its
        ``req_seq``-th dispatch (serve/ha.py's takeover drill)."""
        with self._mu:
            for spec in self._armed("router_kill"):
                if int(req_seq) == spec.value:
                    self._record(spec, req=int(req_seq))
                    return True
        return False

    def lease_stall(self) -> bool:
        """Leader-lease-side, called once per renewal attempt: True
        while the renewal write should be silently dropped (the lease
        ages toward expiry under the standby's nose).  Counts down
        ``value`` renewal ticks from the first attempt, then heals —
        by which time the lease has expired and the old leader must
        discover it is FENCED, not resume."""
        with self._mu:
            for i, spec in enumerate(self.specs):
                if spec.kind != "lease_stall":
                    continue
                left = self._stall_left.get(i)
                if left is None:
                    left = int(spec.value)
                    self._record(spec, ticks=left)
                if left <= 0:
                    continue    # healed
                self._stall_left[i] = left - 1
                return True
        return False

    def rollout_kill(self, phase: str,
                     candidate: int) -> Optional[int]:
        """Rollout-controller-side, one-shot: returns the replica id to
        SIGKILL when the rollout is working in ``phase`` — the explicit
        ``replica<K>`` target if the spec named one, else ``candidate``
        (the replica the phase is currently operating on).  None =
        don't fire."""
        with self._mu:
            for spec in self._armed("rollout_kill"):
                if spec.label == phase:
                    target = (spec.replica if spec.replica is not None
                              else int(candidate))
                    self._record(spec, phase=phase, replica=target)
                    return target
        return None

    def slow_replica(self) -> float:
        """Replica-side, latched: the slow-down factor for THIS process
        (replica id == rank), or 0.0 when no slow fault targets it.  A
        straggler does not recover by itself, so the factor stays on
        once armed."""
        with self._mu:
            for spec in self.specs:
                if spec.kind != "slow_replica":
                    continue
                if spec.replica is not None and spec.replica != self.rank:
                    continue
                if not spec.fired:
                    self._record(spec, factor=float(spec.value))
                return float(spec.value)
        return 0.0

    def page_fetch_stall(self) -> float:
        """Migration-client-side, latched: seconds to stall before each
        ``page_fetch`` window request when a stall fault targets THIS
        process (replica id == rank), or 0.0.  A congested fabric does
        not heal between windows, so the stall stays on once armed."""
        with self._mu:
            for spec in self.specs:
                if spec.kind != "page_fetch_stall":
                    continue
                if spec.replica is not None and spec.replica != self.rank:
                    continue
                if not spec.fired:
                    self._record(spec, seconds=float(spec.value))
                return float(spec.value)
        return 0.0


# ---------------------------------------------------------------------------
# Module-level API (what instrumented code calls) — every probe is a
# None-check when chaos is off.
# ---------------------------------------------------------------------------

def configure(spec: str, rank: Optional[int] = None) -> Injector:
    """Arm the process-global injector.  Reconfiguring replaces it."""
    global _injector
    if rank is None:
        rank = int(os.environ.get("DTF_PROCESS_ID", "0"))
    specs = parse_spec(spec)
    with _lock:
        _injector = Injector(specs, rank=rank)
    if specs:
        log.warning("chaos armed (rank %d): %s", rank,
                    ", ".join(str(s) for s in _injector.specs) or
                    "(no spec targets this rank)")
    return _injector


def maybe_configure(cfg=None) -> Optional[Injector]:
    """Arm from ``cfg.fault`` or the ``DTF_FAULT`` env var.  When
    neither is set chaos is DISARMED (not merely left alone): a fault
    armed by a previous run in the same process must never leak into a
    run that did not ask for one.  Explicit config wins over env."""
    spec = (getattr(cfg, "fault", "") or os.environ.get("DTF_FAULT", ""))
    if not spec:
        disable()
        return None
    rank = getattr(cfg, "process_id", None) if cfg is not None else None
    return configure(spec, rank=rank)


def get() -> Optional[Injector]:
    return _injector


def enabled() -> bool:
    return _injector is not None


def disable() -> None:
    """Disarm (tests)."""
    global _injector
    with _lock:
        _injector = None


def step(step_value: int) -> None:
    inj = _injector
    if inj is None:
        return
    inj.step(step_value)


def heartbeat_stalled(step_value: Optional[int]) -> bool:
    inj = _injector
    if inj is None:
        return False
    return inj.heartbeat_stalled(step_value)


def ps_drop(version: int) -> bool:
    inj = _injector
    if inj is None:
        return False
    return inj.ps_drop(version)


def ckpt_truncate() -> bool:
    inj = _injector
    if inj is None:
        return False
    return inj.ckpt_truncate()


def reader_crash(batch: int) -> bool:
    inj = _injector
    if inj is None:
        return False
    return inj.reader_crash(batch)


def replica_kill(req_seq: int, dispatched_to: int) -> Optional[int]:
    inj = _injector
    if inj is None:
        return None
    return inj.replica_kill(req_seq, dispatched_to)


def net_partition(replica: int, traffic_started: bool) -> bool:
    inj = _injector
    if inj is None:
        return False
    return inj.net_partition(replica, traffic_started)


def router_kill(req_seq: int) -> bool:
    inj = _injector
    if inj is None:
        return False
    return inj.router_kill(req_seq)


def lease_stall() -> bool:
    inj = _injector
    if inj is None:
        return False
    return inj.lease_stall()


def rollout_kill(phase: str, candidate: int) -> Optional[int]:
    inj = _injector
    if inj is None:
        return None
    return inj.rollout_kill(phase, candidate)


def slow_replica() -> float:
    inj = _injector
    if inj is None:
        return 0.0
    return inj.slow_replica()


def page_fetch_stall() -> float:
    inj = _injector
    if inj is None:
        return 0.0
    return inj.page_fetch_stall()


if sys.platform == "win32":  # pragma: no cover - posix repo, belt+braces
    raise ImportError("dtf_tpu.chaos needs posix signals")
