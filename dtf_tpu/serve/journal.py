"""Append-only request journal: the router's crash-recovery WAL.

The router journals every request's life as newline-delimited JSON in
``router_journal.jsonl`` inside the rendezvous directory (the tier's
one shared-storage requirement — same place the replica announces and
the rollout state machine already live):

    {"t":"submit","id":R,"prompt":[...],"max_new_tokens":N,
     "temperature":T,"eos_id":E,"rng_seed":S,"trace":TID,
     "version":V,"ts":...}             request accepted (admission
                                       passed); carries EVERYTHING a
                                       successor router needs to
                                       re-dispatch it bit-identically —
                                       most importantly the minted
                                       rng_seed, which pins the
                                       sampling identity so a replay
                                       is token-exact (the PR-8
                                       failover contract)
    {"t":"dispatch","id":R,"attempt":A,"replica":K,"ts":...}
                                       attempt A sent to replica K —
                                       the successor knows WHERE to
                                       look for a retained tail
    {"t":"first_token","id":R,"ts":...}   the client stream started
    {"t":"watermark","id":R,"n":N,"ts":...}  N tokens delivered to the
                                       client (bounded cadence, not
                                       per-token) — the successor seeds
                                       the dedupe index at >= N so a
                                       re-adopted stream VERIFIES the
                                       prefix instead of re-emitting it
    {"t":"complete","id":R,"ok":B,"ts":...}  resolved (result OR
                                       terminal failure) — the request
                                       needs nothing from a successor

Durability follows data/service/cache.py's WAL discipline, adapted to
a single append stream: every record is flushed to the OS immediately
(a torn PROCESS loses nothing), and fsync'd at a bounded cadence (a
torn HOST loses at most ``fsync_interval_s`` of tail).  Replay
tolerates exactly the failure modes appends create:

  * a torn final line (killed mid-write) is DROPPED, never an error;
  * duplicate records are idempotent (last dispatch wins, first
    complete wins — a complete is terminal);
  * records for unknown ids (a complete whose submit was lost to an
    fsync gap) are ignored.

The journal answers one question for a successor: *which requests were
accepted but not resolved, and where were they last dispatched?*
Everything else — the tokens themselves — lives in the replicas'
retained per-request tails (serve/replica.py ``reattach``), because
the journal must stay CHEAP: O(1) writes per request lifecycle event,
never per token.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Optional

JOURNAL_NAME = "router_journal.jsonl"


def journal_path(rendezvous_dir: str) -> str:
    return os.path.join(rendezvous_dir, JOURNAL_NAME)


class RequestJournal:
    """Thread-safe append stream of request-lifecycle records.

    Writers (router submit path, dispatch path, delivery path) append
    concurrently; ``_lock`` serializes the file writes and the fsync
    bookkeeping.  ``lag_observe`` (optional) receives the append→fsync
    delay in seconds whenever a sync retires queued records — the
    ``router_journal_lag_s`` histogram, the operator's bound on how
    much tail a host crash can cost."""

    _GUARDED_BY = {
        "_file": "_lock",
        "_pending_since": "_lock",
        "_last_fsync": "_lock",
        "_records": "_lock",
    }

    def __init__(self, path: str, fsync_interval_s: float = 0.05,
                 lag_observe: Optional[Callable[[float], None]] = None):
        self.path = path
        self.fsync_interval_s = float(fsync_interval_s)
        self._lag_observe = lag_observe
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # append mode: a successor taking over the SAME journal keeps
        # extending it — replay tolerates the dead leader's tail
        self._file = open(path, "a", encoding="utf-8")
        self._pending_since: float = 0.0   # oldest unfsynced append (0 = none)
        self._last_fsync: float = time.monotonic()
        self._records = 0

    # -- write side ----------------------------------------------------
    def append(self, record: dict) -> None:
        """Append one record: flushed always, fsync'd at bounded
        cadence.  Raises OSError only if the journal file itself is
        gone — the CALLER decides whether that is fatal."""
        line = json.dumps(record, separators=(",", ":")) + "\n"
        with self._lock:
            now = time.monotonic()
            self._file.write(line)
            self._file.flush()
            self._records += 1
            if not self._pending_since:
                self._pending_since = now
            if now - self._last_fsync >= self.fsync_interval_s:
                self._fsync_locked(now)

    def sync(self) -> None:
        """Force-fsync any pending appends (takeover/teardown path)."""
        with self._lock:
            if self._pending_since:
                self._fsync_locked(time.monotonic())

    def _fsync_locked(self, now: float) -> None:
        os.fsync(self._file.fileno())
        if self._lag_observe is not None and self._pending_since:
            self._lag_observe(now - self._pending_since)
        self._pending_since = 0.0
        self._last_fsync = now

    @property
    def records(self) -> int:
        with self._lock:
            return self._records

    def close(self) -> None:
        with self._lock:
            try:
                if self._pending_since:
                    self._fsync_locked(time.monotonic())
            except (OSError, ValueError):
                pass
            try:
                self._file.close()
            except (OSError, ValueError):
                pass

    # -- lifecycle-record helpers (the router's write vocabulary) ------
    def submit(self, req_id: str, *, prompt, max_new_tokens: int,
               temperature: float, eos_id, rng_seed: int, trace: str,
               version: str = "") -> None:
        self.append({"t": "submit", "id": req_id,
                     "prompt": [int(t) for t in prompt],
                     "max_new_tokens": int(max_new_tokens),
                     "temperature": float(temperature),
                     "eos_id": eos_id, "rng_seed": int(rng_seed),
                     "trace": trace, "version": version,
                     "ts": time.time()})

    def dispatch(self, req_id: str, attempt: int, replica: int) -> None:
        self.append({"t": "dispatch", "id": req_id,
                     "attempt": int(attempt), "replica": int(replica),
                     "ts": time.time()})

    def first_token(self, req_id: str) -> None:
        self.append({"t": "first_token", "id": req_id, "ts": time.time()})

    def watermark(self, req_id: str, n: int) -> None:
        self.append({"t": "watermark", "id": req_id, "n": int(n),
                     "ts": time.time()})

    def complete(self, req_id: str, ok: bool) -> None:
        self.append({"t": "complete", "id": req_id, "ok": bool(ok),
                     "ts": time.time()})


def replay(path: str) -> dict:
    """Parse a journal into per-request recovery state.

    Returns ``{req_id: state}`` where state is a dict with:

      * ``submit``      the submit record (None if lost — such a
                        request is unrecoverable and is EXCLUDED)
      * ``dispatches``  list of dispatch records, wire order
      * ``first_token`` True if the stream ever started
      * ``watermark``   highest client-delivered token count seen
      * ``complete``    the FIRST complete record (duplicates are
                        idempotent), or None while in-flight

    The torn final line — the signature of a router killed mid-append —
    is dropped silently; a torn line anywhere ELSE means external
    corruption and still only costs that line (each record is
    self-contained).  Missing file = empty journal (cold start)."""
    state: dict = {}
    try:
        with open(path, encoding="utf-8") as f:
            raw = f.read()
    except OSError:
        return state
    lines = raw.split("\n")
    for k, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            # torn tail (no trailing newline written) is expected;
            # anything else is tolerated the same way — one record lost
            continue
        t = rec.get("t")
        rid = rec.get("id")
        if not rid:
            continue
        if t == "submit":
            st = state.setdefault(rid, _fresh())
            if st["submit"] is None:     # duplicate submits: first wins
                st["submit"] = rec
        elif t == "dispatch":
            st = state.get(rid)
            if st is not None and st["complete"] is None:
                st["dispatches"].append(rec)
        elif t == "first_token":
            st = state.get(rid)
            if st is not None:
                st["first_token"] = True
        elif t == "watermark":
            st = state.get(rid)
            if st is not None:
                st["watermark"] = max(st["watermark"],
                                      int(rec.get("n", 0)))
        elif t == "complete":
            st = state.get(rid)
            if st is not None and st["complete"] is None:
                st["complete"] = rec     # duplicates idempotent
    return state


def _fresh() -> dict:
    return {"submit": None, "dispatches": [], "first_token": False,
            "watermark": 0, "complete": None}


def unresolved(state: dict) -> dict:
    """Filter replay() output to the requests a successor must finish:
    submitted, never completed."""
    return {rid: st for rid, st in state.items()
            if st["submit"] is not None and st["complete"] is None}
