"""Zero-downtime model rollout over the serving replica tier.

The train → export → fleet loop closes here: a running router/replica
tier moves onto a NEW checkpoint without shedding a request, mixing a
client stream across model versions, or losing the ability to return
to the old model instantly.  The mechanism is the tier's own fault
machinery pointed at a planned event: drain one replica at a time
(serve/router.py ``drain_replica`` — the same begin_drain contract
SIGTERM uses), restart it onto the new checkpoint (the spawner's
``checkpoint_map`` → DTF_SERVE_CHECKPOINT), let it warm and re-register
through the ordinary rendezvous, and advance.

State machine (persisted after every mutation, atomic tmp+rename)::

    IDLE ──► CANARY ──► ROLLING ──► DONE
               │            │
               └────────────┴─────► ROLLED_BACK

  CANARY   — the first replica is drained and restarted onto the new
      checkpoint as a SHADOW: it takes no client traffic, only
      mirrored copies of live greedy requests (router.start_mirror).
      Greedy determinism makes old-vs-new divergence a measurable,
      gateable quantity: the canary's answer is compared token-by-
      token against the old model's, and the gate passes only after
      ``canary_requests`` comparisons with the divergence rate inside
      ``max_divergence`` (default 0.0 — token-exact, the bench_gate
      posture: identical checkpoints must compare EQUAL, so any
      mismatch is a model difference, never noise).
  ROLLING  — the canary joins service (new version), then each
      remaining replica drains → restarts → warms → re-registers, one
      at a time; version-affine placement guarantees in-flight and
      failed-over requests only ever continue on their own model
      version.
  DONE     — the fleet serves the new checkpoint.  The old checkpoint
      was never touched on disk (instant rollback needs it); DONE is
      the point an operator may GC it.
  ROLLED_BACK — any breach (canary divergence, a replica that cannot
      come up on the new checkpoint — truncated/corrupt files
      included, unexpected replica death mid-rollout) re-drains every
      new-version replica back onto the RETAINED old checkpoint.  The
      persisted ``rolled`` list shrinks as replicas return, so a
      controller death mid-rollback resumes deterministically.

A router restart mid-rollout resumes from the persisted state
(:meth:`RolloutController.resume`): CANARY resumes as a rollback (an
interrupted canary proved nothing), ROLLING resumes forward from the
persisted ``rolled`` set, ROLLED_BACK finishes the rollback.  Both
directions are deterministic — no state is reconstructed by guessing.

Chaos composes: ``rollout_kill@phase:<canary|rolling>`` SIGKILLs a
replica while the rollout works in that phase, and ``ckpt_truncate``
fires against the NEW checkpoint before the canary restart; both must
end in ROLLED_BACK with the fleet token-exact on the old model and
zero lost requests (tools/rollout_smoke.py pins it).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from typing import Callable, List, Optional

from dtf_tpu import chaos
from dtf_tpu.obs import trace

log = logging.getLogger("dtf_tpu")

PHASES = ("IDLE", "CANARY", "ROLLING", "DONE", "ROLLED_BACK")
_TRANSITIONS = {
    "IDLE": ("CANARY",),
    "CANARY": ("ROLLING", "ROLLED_BACK"),
    "ROLLING": ("DONE", "ROLLED_BACK"),
    "DONE": (),
    "ROLLED_BACK": (),
}


class RolloutError(RuntimeError):
    pass


@dataclasses.dataclass
class RolloutState:
    """The rollout's durable truth.  Everything a restarted router
    needs to resume or roll back deterministically lives here —
    nothing is inferred from the fleet."""

    phase: str = "IDLE"
    new_checkpoint: str = ""
    old_checkpoint: str = ""        # "" = the tier's flag-configured one
    canary: int = -1
    order: List[int] = dataclasses.field(default_factory=list)
    rolled: List[int] = dataclasses.field(default_factory=list)
    reason: str = ""
    compared: int = 0
    diverged: int = 0
    first_divergence_pos: int = -1
    updated_ts: float = 0.0

    def advance(self, phase: str, reason: str = "") -> None:
        """Validated phase transition — an illegal edge is a bug in the
        controller, raised loudly, never silently written to disk."""
        if phase not in PHASES:
            raise RolloutError(f"unknown rollout phase {phase!r}")
        if phase not in _TRANSITIONS[self.phase]:
            raise RolloutError(
                f"illegal rollout transition {self.phase} -> {phase}")
        self.phase = phase
        if reason:
            self.reason = reason

    def save(self, path: str) -> None:
        self.updated_ts = time.time()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(dataclasses.asdict(self), f, indent=1)
        os.replace(tmp, path)   # atomic: a resume never reads torn state

    @classmethod
    def load(cls, path: str) -> "RolloutState":
        with open(path) as f:
            raw = json.load(f)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in raw.items() if k in known})


def default_state_path(rendezvous_dir: str) -> str:
    return os.path.join(os.path.abspath(rendezvous_dir),
                        "rollout_state.json")


def _truncate_checkpoint(path: str) -> None:
    """The ckpt_truncate chaos payload, aimed at the NEW checkpoint —
    the torn-upload / bad-copy failure a rollout must survive by
    rolling back, not by serving garbage.  The walk-and-halve action
    itself is the train-side fault's (one payload, two aims)."""
    from dtf_tpu.train.checkpoint import truncate_largest_file

    if truncate_largest_file(path) is None:
        raise RolloutError(f"ckpt_truncate: nothing to truncate under "
                           f"{path!r}")


class RolloutController:
    """Drives one rollout of ``router``'s whole tier onto
    ``new_checkpoint``.

    ``router`` — a started serve/router.py Router (proc mode, or any
        tier when ``restart_hook`` is given).
    ``restart_hook(replica_id, checkpoint)`` — test seam for proc-less
        tiers: kill the in-process replica and start its successor
        serving ``checkpoint``.  Proc mode uses the router's
        terminate/spawn + the spawner's checkpoint_map.
    ``canary_requests`` — completed old-vs-new comparisons the gate
        needs; ``mirror_fraction`` — the slice of live greedy traffic
        mirrored; ``max_divergence`` — gate threshold on the diverged/
        compared rate (0.0 = token-exact, the default).

    LOCK DISCIPLINE: the controller reaches into the router's replica
    state (fleet-stability checks); every such touch happens under the
    ROUTER's ``_mu`` — declared here so tools/dtflint's lock-guard
    rule enforces the cross-object contract (the with-block's base may
    be any alias of the router: ``with r._mu`` / ``with
    self.router._mu`` both satisfy it).
    """

    _GUARDED_BY = {"_replicas": "_mu"}

    def __init__(self, router, new_checkpoint: str, *,
                 old_checkpoint: str = "",
                 state_path: str = "",
                 canary_requests: int = 4,
                 mirror_fraction: float = 1.0,
                 max_divergence: float = 0.0,
                 warm_timeout_s: float = 600.0,
                 drain_timeout_s: float = 120.0,
                 gate_timeout_s: float = 600.0,
                 restart_hook: Optional[Callable] = None,
                 poll_s: float = 0.05):
        if not new_checkpoint:
            raise ValueError("new_checkpoint is required")
        if canary_requests < 1:
            raise ValueError(f"canary_requests must be >= 1, got "
                             f"{canary_requests}")
        if not 0.0 <= max_divergence <= 1.0:
            raise ValueError(f"max_divergence must be in [0, 1], got "
                             f"{max_divergence}")
        self.router = router
        self.state = RolloutState(
            new_checkpoint=str(new_checkpoint),
            old_checkpoint=str(old_checkpoint),
            order=[r.id for r in router._replicas])
        self.state_path = state_path or default_state_path(
            router.rendezvous_dir)
        self.canary_requests = int(canary_requests)
        self.mirror_fraction = float(mirror_fraction)
        self.max_divergence = float(max_divergence)
        self.warm_timeout_s = float(warm_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.gate_timeout_s = float(gate_timeout_s)
        self.restart_hook = restart_hook
        self.poll_s = float(poll_s)
        self._respawns0 = 0

    # -- labels ---------------------------------------------------------
    @property
    def old_version(self) -> str:
        return self.state.old_checkpoint or "base"

    @property
    def new_version(self) -> str:
        return self.state.new_checkpoint

    # -- persistence ----------------------------------------------------
    def _persist(self, phase: Optional[str] = None,
                 reason: str = "") -> None:
        if phase is not None:
            self.state.advance(phase, reason=reason)
            trace.event("rollout_phase", phase=self.state.phase,
                        rolled=list(self.state.rolled),
                        reason=self.state.reason)
            log.warning("rollout: phase %s%s", self.state.phase,
                        f" ({reason})" if reason else "")
        self.state.save(self.state_path)

    # -- fleet observation ----------------------------------------------
    def _snapshot_respawns(self) -> None:
        self._respawns0 = self.router.metrics.get(
            "router_replica_respawns_total").value

    def _disturbed(self) -> str:
        """Unexpected fleet instability mid-rollout: any UNPLANNED
        respawn, give-up, or a non-held replica down.  A rollout is a
        planned maneuver — instability during one means the safest
        model is the proven old one, so the policy is abort + roll
        back (the respawn machinery restores processes; this restores
        the MODEL)."""
        delta = (self.router.metrics.get(
            "router_replica_respawns_total").value - self._respawns0)
        if delta > 0:
            return f"unplanned_respawn(+{delta})"
        with self.router._mu:
            for r in self.router._replicas:
                if r.gave_up:
                    return f"replica{r.id}_gave_up"
                if not r.healthy and not r.hold_respawn:
                    return f"replica{r.id}_lost"
        return ""

    def _wait_healthy(self, rid: int, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.router.replica_healthy(rid):
                return True
            code = self.router.replica_exit_code(rid)
            if code is not None and code != 0:
                # the new process could not even start (bad/truncated
                # checkpoint, import error): fail FAST — waiting out
                # the warm timeout on a corpse helps nobody
                log.error("rollout: replica %d exited %s during "
                          "restart", rid, code)
                return False
            time.sleep(self.poll_s)
        return False

    # -- the one mechanical move ----------------------------------------
    def _replace(self, rid: int, checkpoint: str, version: str,
                 shadow: bool = False) -> bool:
        """Drain replica ``rid`` and restart it serving ``checkpoint``.
        True on healthy re-registration within the warm timeout."""
        r = self.router
        r.hold_replica(rid)
        drained = r.drain_replica(rid, timeout=self.drain_timeout_s)
        if not drained:
            log.error("rollout: replica %d did not drain in %.0fs — "
                      "its stragglers will fail over", rid,
                      self.drain_timeout_s)
        if self.restart_hook is not None:
            r.terminate_replica(rid)
            r.set_replica_version(rid, version)
            self.restart_hook(rid, checkpoint)
            r.allow_reconnect(rid)
        else:
            r.terminate_replica(rid)
            if checkpoint:
                r.replica_checkpoints[rid] = checkpoint
            else:
                r.replica_checkpoints.pop(rid, None)
            r.set_replica_version(rid, version)
            r.spawn_replica(rid)
        ok = self._wait_healthy(rid, self.warm_timeout_s)
        if ok:
            r.release_replica(rid, shadow=shadow)
        return ok

    # -- rollback -------------------------------------------------------
    def _rollback(self, reason: str) -> RolloutState:
        trace.anomaly("rollout_rollback", reason=reason,
                      rolled=list(self.state.rolled),
                      compared=self.state.compared,
                      diverged=self.state.diverged)
        self.router.stop_mirror()
        self._persist("ROLLED_BACK", reason=reason)
        return self._finish_rollback()

    def _finish_rollback(self) -> RolloutState:
        """Return every new-version (or dead) replica to the retained
        old checkpoint.  ``rolled`` shrinks as replicas come home, so
        a death mid-rollback resumes exactly here."""
        r = self.router
        targets = list(self.state.rolled)
        # a replica the chaos killed may not be in rolled — it still
        # must be standing on the old model before we call it done
        # (the prober may already have respawned it; then it's healthy
        # on the old checkpoint and needs nothing)
        with r._mu:
            targets += [rep.id for rep in r._replicas
                        if rep.id not in targets and not rep.healthy
                        and not rep.gave_up]
        for rid in targets:
            ok = self._replace(rid, self.state.old_checkpoint,
                               self.old_version, shadow=False)
            if not ok:
                # rollback onto the PROVEN checkpoint failing is as
                # loud as it gets; keep restoring the others
                trace.anomaly("rollout_rollback_failed", replica=rid)
                log.error("rollout: replica %d failed to restore onto "
                          "the old checkpoint", rid)
                continue
            if rid in self.state.rolled:
                self.state.rolled.remove(rid)
            self._persist()
        log.warning("rollout: ROLLED_BACK (%s) — fleet on the old "
                    "checkpoint", self.state.reason)
        return self.state

    # -- canary gate ----------------------------------------------------
    def _gate(self) -> str:
        """'' when the gate passes; a breach reason otherwise.  The
        comparisons come from LIVE traffic the router mirrors — the
        gate measures the models under the requests users actually
        send, not a synthetic probe set."""
        deadline = time.monotonic() + self.gate_timeout_s
        # the registry counters are CUMULATIVE across the router's
        # life — a second rollout's gate must judge only ITS OWN
        # comparisons, so everything below is a delta from here
        base = self.router.canary_stats()
        while time.monotonic() < deadline:
            stats = self.router.canary_stats()
            self.state.compared = int(stats["compared"]
                                      - base["compared"])
            self.state.diverged = int(stats["diverged"]
                                      - base["diverged"])
            self.state.first_divergence_pos = int(
                stats["first_divergence_pos"])
            if self.state.diverged and self.max_divergence == 0.0:
                # token-exact gate: ONE divergence is a verdict (the
                # same discipline bench_gate applies — an identical
                # model compares equal, so any mismatch is signal)
                return (f"canary_divergence(first_pos="
                        f"{self.state.first_divergence_pos})")
            if self.state.compared >= self.canary_requests:
                rate = self.state.diverged / self.state.compared
                if rate > self.max_divergence:
                    return (f"canary_divergence(rate={rate:.3f}>"
                            f"{self.max_divergence})")
                return ""
            why = self._disturbed()
            if why:
                return why
            if not self.router.replica_healthy(self.state.canary):
                return "canary_lost"
            time.sleep(self.poll_s)
        return (f"canary_timeout({self.state.compared}/"
                f"{self.canary_requests} comparisons)")

    # -- the rollout ----------------------------------------------------
    def run(self) -> RolloutState:
        """Execute the full rollout.  Returns the final state (phase
        DONE or ROLLED_BACK) — never raises for a gated/rolled-back
        outcome; rollback IS the designed answer to a bad checkpoint."""
        r = self.router
        if len(self.state.order) < 2:
            raise RolloutError(
                "rollout refused: a 1-replica tier has no capacity to "
                "roll — the shadow-only canary would be the ONLY "
                "replica, every live request would queue into its "
                "deadline, and the gate (fed by mirrored live traffic) "
                "could never complete")
        with r._mu:
            unhealthy = [rep.id for rep in r._replicas if not rep.healthy]
        if unhealthy:
            raise RolloutError(
                f"rollout refused: replicas {unhealthy} unhealthy — a "
                f"rollout starts from a stable fleet")
        # label the incumbent fleet (and the requests already latched
        # to its unlabeled version) so version-affine placement has a
        # ground truth from the first drained replica onward.  The
        # contract: ``old_checkpoint`` names what the fleet serves NOW
        # — a second rollout passes the first one's new checkpoint —
        # and it is ENFORCED: rolling back to a checkpoint the fleet
        # never served would end with the tier split across two models
        # while reporting success
        r.relabel_version("", self.old_version)
        wrong = [rid for rid in self.state.order
                 if r.replica_version(rid) != self.old_version]
        if wrong:
            raise RolloutError(
                f"rollout refused: replicas {wrong} serve "
                f"{[r.replica_version(i) for i in wrong]!r}, not the "
                f"declared old checkpoint {self.old_version!r} — pass "
                f"old_checkpoint= naming what the fleet serves NOW "
                f"(after a completed rollout, that is its new "
                f"checkpoint)")
        self._snapshot_respawns()
        self.state.canary = self.state.order[0]
        self._persist("CANARY")

        # chaos: the torn-upload case — the NEW checkpoint loses a
        # payload file before any replica tries to serve it
        if chaos.ckpt_truncate():
            _truncate_checkpoint(self.state.new_checkpoint)

        # the canary is on the new checkpoint from here: record it as
        # rolled BEFORE the restart, so a controller death inside the
        # restart window still knows to restore it
        self.state.rolled.append(self.state.canary)
        self._persist()
        if not self._replace(self.state.canary, self.state.new_checkpoint,
                             self.new_version, shadow=True):
            return self._rollback("canary_start_failed")

        r.start_mirror(self.state.canary, self.mirror_fraction)
        target = chaos.rollout_kill("canary", self.state.canary)
        if target is not None:
            r.kill_replica(target)
        breach = self._gate()
        r.stop_mirror()
        self._persist()   # gate counters into the durable state
        if breach:
            return self._rollback(breach)

        # gate passed: the canary joins service on the new model
        r.set_shadow(self.state.canary, False)
        self._persist("ROLLING")
        for rid in self.state.order:
            if rid in self.state.rolled:
                continue
            target = chaos.rollout_kill("rolling", rid)
            if target is not None:
                r.kill_replica(target)
                # the death registers through the ordinary detection
                # path (probe tick / conn EOF) — give it time to,
                # or the check below would race the prober and the
                # rollout would sail past its own chaos
                deadline = time.monotonic() + max(
                    2.0, 6 * r.probe_interval_s)
                while (time.monotonic() < deadline
                       and not self._disturbed()):
                    time.sleep(self.poll_s)
            why = self._disturbed()
            if why:
                return self._rollback(why)
            self.state.rolled.append(rid)
            self._persist()
            if not self._replace(rid, self.state.new_checkpoint,
                                 self.new_version, shadow=False):
                return self._rollback(f"replica{rid}_start_failed")
            why = self._disturbed()
            if why:
                return self._rollback(why)
        self._persist("DONE")
        log.warning("rollout: DONE — fleet on %s (old checkpoint "
                    "retained at %r)", self.state.new_checkpoint,
                    self.state.old_checkpoint or "<flag-configured>")
        return self.state

    # -- resume ---------------------------------------------------------
    @classmethod
    def resume(cls, router, state_path: str = "",
               restart_hook: Optional[Callable] = None,
               **kw) -> RolloutState:
        """Continue a rollout a dead router left mid-flight, from its
        persisted state alone.  CANARY resumes as a ROLLBACK (an
        interrupted canary proved nothing — the deterministic, safe
        verdict); ROLLING resumes FORWARD from the persisted rolled
        set; ROLLED_BACK finishes the rollback; DONE/IDLE are no-ops."""
        state_path = state_path or default_state_path(
            router.rendezvous_dir)
        state = RolloutState.load(state_path)
        self = cls(router, state.new_checkpoint or "-",
                   old_checkpoint=state.old_checkpoint,
                   state_path=state_path, restart_hook=restart_hook,
                   **kw)
        self.state = state
        self._snapshot_respawns()
        # the restarted router knows nothing about versions or
        # checkpoint overrides — rebuild BOTH from the durable state
        for rid in self.state.order:
            on_new = rid in self.state.rolled
            router.set_replica_version(
                rid, self.new_version if on_new else self.old_version)
            if self.restart_hook is None:
                if on_new and self.state.new_checkpoint:
                    router.replica_checkpoints[rid] = \
                        self.state.new_checkpoint
                else:
                    router.replica_checkpoints.pop(rid, None)
        log.warning("rollout: resuming from persisted phase %s "
                    "(rolled=%s)", state.phase, state.rolled)
        if state.phase in ("DONE", "IDLE"):
            return state
        if state.phase == "CANARY":
            return self._rollback("resumed_mid_canary")
        if state.phase == "ROLLED_BACK":
            return self._finish_rollback()
        # ROLLING: finish the roll forward
        for rid in self.state.order:
            if rid in self.state.rolled:
                # already targeted at the new checkpoint — make sure it
                # actually stands (the death may have struck mid-restart)
                if not router.replica_healthy(rid):
                    if not self._replace(rid, self.state.new_checkpoint,
                                         self.new_version):
                        return self._rollback(
                            f"replica{rid}_resume_failed")
                continue
            self.state.rolled.append(rid)
            self._persist()
            if not self._replace(rid, self.state.new_checkpoint,
                                 self.new_version):
                return self._rollback(f"replica{rid}_start_failed")
            why = self._disturbed()
            if why:
                return self._rollback(why)
        self._persist("DONE")
        return self.state
