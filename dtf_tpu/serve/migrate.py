"""KV-page migration: move a prompt's page chain between replicas
over the wire (the disaggregated-serving transfer layer).

Disaggregated serving (serve/router.py pool roles) runs PREFILL on
one pool of replicas and DECODE on another, which only works if the
prompt's KV pages — computed on a prefill replica — can be re-homed
onto a decode replica.  Pages already have everything a transfer
needs: stable identities (the page pool) and content-addressed names
(the prefix registry's chained digests).  This module is the wire
form: serialization, integrity digests, bounded in-flight windows,
and the client that pulls + verifies + imports a chain.

The protocol rides the existing replica wire (newline-delimited JSON,
serve/replica.py) — a decode replica dials the prefill replica's own
server socket and speaks two ops:

  client → server
    {"op":"page_fetch","xfer":X,"prompt":[...],"lo":L,"n":N}
        request window [L, L+N) of the prompt's page chain.  The
        FIRST fetch of a transfer takes a MIGRATION HOLD on the whole
        chain (engine.export_chain_begin): every held page gets one
        extra pool holder, so refcount ≥ 2 — above the eviction
        scan's refcount-1 bar.  An in-transfer page can therefore
        never be evicted, by construction.
    {"op":"page_fetch","xfer":X,"release":true}
        transfer over (complete OR aborted): drop the hold.  The
        server also drops holds when the connection dies — a vanished
        client cannot pin pages forever.

  server → client
    {"op":"page_push","xfer":X,"depth":D,"digest":CHAIN_DIGEST,
     "tokens":[...],"payload":{"leaves":[...],"digest":SHA1},
     "chain_len":L}
        one page: its depth, its chained content digest, the page's
        OWN token ids, and the serialized KV payload with an
        integrity digest over the raw bytes.
    {"op":"page_push","xfer":X,"end":true,"lo":L,"sent":K,
     "chain_len":L}                       end-of-window marker
    {"op":"page_push","xfer":X,"error":MSG}  server-side failure

VERIFICATION is layered, and each layer catches a different lie:

  payload digest   — sha1 over every leaf's dtype/shape/bytes.  A
      mismatch is a TORN TRANSFER (bit rot, truncation, a bug):
      loud ``migration_torn`` anomaly + bounded re-fetch of that one
      page; repeated tears abort the transfer.
  token comparison — the receiver compares the page's wire-carried
      tokens against ITS OWN prompt slice, byte-for-byte.  A chain
      digest that matches while the tokens differ (hash collision, or
      a corrupted sender) is rejected here — the same
      collision-degrades-to-miss guard the prefix registry applies
      locally, extended over the wire.
  chain digest     — recomputed from the receiver's own prompt and
      compared against the sender's claim; a mismatch means the two
      sides disagree about what prefix this even is.  Abort.

A page that passes all three and is imported (engine.import_chain →
Decoder.write_page) is BIT-IDENTICAL to a locally-prefilled one:
read_page/write_page are pure device_get / index-update, no casts —
the contract the token-exactness tests pin.

Bounded in-flight: the client requests ``window`` pages per fetch and
IMPORTS each window into the local pool before requesting the next,
so at most ``window`` pages are ever buffered in host memory,
regardless of chain length.
"""

from __future__ import annotations

import base64
import hashlib
import json
import logging
import os
import socket
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from dtf_tpu import chaos
from dtf_tpu.obs import trace
from dtf_tpu.serve.engine import _page_digest

log = logging.getLogger("dtf_tpu")

#: pages per fetch window — the in-flight bound (host-memory cap per
#: transfer is window × page payload size)
DEFAULT_WINDOW = 4


class TornTransfer(RuntimeError):
    """A page payload's bytes do not match its integrity digest."""


class MigrationError(RuntimeError):
    """The transfer cannot proceed (peer gone, corrupt chain, starved
    pool) — the caller falls back to local prefill, which is always
    correct, just slower."""


# -- serialization -----------------------------------------------------

def payload_digest(leaves: List[np.ndarray]) -> str:
    """Integrity digest over a page payload: sha1 of every leaf's
    dtype tag, shape and raw bytes, in leaf order.  Covers layout as
    well as content — a reshaped or re-typed leaf with identical bytes
    is still a different page."""
    h = hashlib.sha1()
    for a in leaves:
        a = np.ascontiguousarray(a)
        h.update(str(a.dtype).encode())
        h.update(np.asarray(a.shape, np.int64).tobytes())
        h.update(a.tobytes())
    return h.hexdigest()


def encode_page(leaves: List[np.ndarray]) -> dict:
    """Wire form of one page payload: per-leaf dtype/shape/base64
    bytes plus the integrity digest."""
    return {
        "leaves": [{"dtype": str(np.ascontiguousarray(a).dtype),
                    "shape": list(a.shape),
                    "data": base64.b64encode(
                        np.ascontiguousarray(a).tobytes()).decode()}
                   for a in leaves],
        "digest": payload_digest(leaves),
    }


def decode_page(obj: dict) -> List[np.ndarray]:
    """Inverse of :func:`encode_page`.  Recomputes the integrity
    digest over the decoded leaves and raises :class:`TornTransfer`
    when it does not match the sender's claim — the torn-transfer
    detector."""
    leaves = []
    for leaf in obj["leaves"]:
        a = np.frombuffer(base64.b64decode(leaf["data"]),
                          dtype=np.dtype(leaf["dtype"]))
        leaves.append(a.reshape(leaf["shape"]))
    got = payload_digest(leaves)
    if got != obj.get("digest"):
        raise TornTransfer(
            f"page payload digest mismatch: wire claims "
            f"{obj.get('digest')!r}, bytes hash to {got!r}")
    return leaves


def expected_chain(prompt: np.ndarray, page_size: int) -> List[str]:
    """The chained digests of the prompt's full pages, computed from
    the RECEIVER's own tokens — the reference every wire-carried
    digest is checked against."""
    prompt = np.asarray(prompt, np.int32).reshape(-1)
    out: List[str] = []
    digest = ""
    for d in range(int(prompt.size) // int(page_size)):
        digest = _page_digest(
            digest, prompt[d * page_size:(d + 1) * page_size])
        out.append(digest)
    return out


def new_xfer_id() -> str:
    """Transfer ids only need uniqueness per (client, connection)."""
    return f"x{os.getpid()}.{time.monotonic_ns()}"


# -- client ------------------------------------------------------------

class _Wire:
    """One blocking JSON-lines connection to a peer replica."""

    def __init__(self, host: str, port: int, timeout: float):
        self.sock = socket.create_connection((host, int(port)),
                                             timeout=timeout)
        self.sock.settimeout(timeout)
        self.rfile = self.sock.makefile("rb")
        self.wfile = self.sock.makefile("wb")
        self._wlock = threading.Lock()

    def send(self, obj: dict) -> None:
        data = (json.dumps(obj) + "\n").encode()
        with self._wlock:
            self.wfile.write(data)
            self.wfile.flush()

    def recv(self) -> dict:
        line = self.rfile.readline()
        if not line:
            raise MigrationError("peer closed the connection mid-transfer")
        return json.loads(line)

    def close(self) -> None:
        for c in (self.rfile, self.wfile, self.sock):
            try:
                c.close()
            except OSError:
                pass


def _verify_page(msg: dict, prompt: np.ndarray, page_size: int,
                 expect: List[str]) -> List[np.ndarray]:
    """All three verification layers for one page_push message.
    Raises TornTransfer (payload bytes) or MigrationError (token /
    chain-digest rejection — not retryable)."""
    depth = int(msg["depth"])
    if depth >= len(expect):
        raise MigrationError(
            f"peer sent depth {depth} but this prompt has only "
            f"{len(expect)} full pages")
    block = np.ascontiguousarray(
        prompt[depth * page_size:(depth + 1) * page_size], np.int32)
    # collision guard: compare the page's TOKENS, not just digests —
    # a colliding digest with different tokens must be rejected, the
    # wire form of the registry's stored-token verification
    wire_tokens = np.asarray(msg.get("tokens", ()), np.int32)
    if wire_tokens.shape != block.shape or not np.array_equal(
            wire_tokens, block):
        raise MigrationError(
            f"depth-{depth} page tokens differ from the local prompt — "
            f"corrupted or foreign chain, rejecting")
    if msg.get("digest") != expect[depth]:
        raise MigrationError(
            f"depth-{depth} chain digest mismatch: peer claims "
            f"{msg.get('digest')!r}, local chain says "
            f"{expect[depth]!r}")
    return decode_page(msg["payload"])   # raises TornTransfer on tear


def fetch_chain(engine, host: str, port: int, prompt,
                *, window: int = DEFAULT_WINDOW,
                io_timeout: float = 30.0,
                max_refetch: int = 2) -> Dict[str, int]:
    """Pull ``prompt``'s page chain from the replica at ``host:port``
    and import it into ``engine``'s pool + registry (the decode-
    replica side of a migration).

    Windows of ``window`` pages bound in-flight data; each window is
    imported before the next is requested.  A torn page (payload
    digest mismatch) raises a loud ``migration_torn`` anomaly and is
    re-fetched up to ``max_refetch`` times; persistent tears — and any
    token/chain-digest rejection — abort with :class:`MigrationError`.
    Returns ``{"pages": imported, "chain_len": peer chain length,
    "torn": tears seen}``."""
    prompt = np.asarray(prompt, np.int32).reshape(-1)
    page_size = int(engine.page_size)
    expect = expected_chain(prompt, page_size)
    if not expect:
        return {"pages": 0, "chain_len": 0, "torn": 0}
    metrics = getattr(engine, "metrics", None)
    torn_counter = metrics.get("serve_migration_torn_total") \
        if metrics is not None else None
    xfer = new_xfer_id()
    payloads: Dict[int, List[np.ndarray]] = {}
    imported = 0
    torn = 0
    chain_len: Optional[int] = None
    wire = _Wire(host, port, io_timeout)
    try:
        lo = 0
        while chain_len is None or lo < chain_len:
            # chaos page_fetch_stall@replica<K>:<S>: each window on
            # replica K waits S extra seconds — the slow-fabric
            # signature the router's migration timeout must absorb
            # without losing requests or token exactness
            stall = chaos.page_fetch_stall()
            if stall > 0:
                time.sleep(stall)
            wire.send({"op": "page_fetch", "xfer": xfer,
                       "prompt": [int(t) for t in prompt],
                       "lo": lo, "n": int(window)})
            got: Dict[int, List[np.ndarray]] = {}
            while True:
                msg = wire.recv()
                if msg.get("op") != "page_push" \
                        or msg.get("xfer") != xfer:
                    continue              # stale cross-talk — skip
                if msg.get("error"):
                    raise MigrationError(
                        f"peer aborted transfer: {msg['error']}")
                if msg.get("end"):
                    chain_len = int(msg["chain_len"])
                    break
                depth = int(msg["depth"])
                try:
                    got[depth] = _verify_page(msg, prompt, page_size,
                                              expect)
                except TornTransfer as e:
                    torn += 1
                    if torn_counter is not None:
                        torn_counter.inc()
                    trace.anomaly("migration_torn", depth=depth,
                                  xfer=xfer, error=str(e))
                    trace.flush()
                    log.error("migrate: torn page at depth %d (%s) — "
                              "re-fetching", depth, e)
                    if torn > max_refetch:
                        raise MigrationError(
                            f"{torn} torn pages — aborting (last: {e})"
                        ) from e
            # re-fetch any page of this window that arrived torn (one
            # page at a time: the tear already proved this path flaky)
            hi = min(lo + int(window), chain_len)
            missing = [d for d in range(lo, hi) if d not in got]
            for d in missing:
                wire.send({"op": "page_fetch", "xfer": xfer,
                           "prompt": [int(t) for t in prompt],
                           "lo": d, "n": 1})
                while True:
                    msg = wire.recv()
                    if msg.get("op") != "page_push" \
                            or msg.get("xfer") != xfer:
                        continue
                    if msg.get("error"):
                        raise MigrationError(
                            f"peer aborted transfer: {msg['error']}")
                    if msg.get("end"):
                        break
                    try:
                        got[int(msg["depth"])] = _verify_page(
                            msg, prompt, page_size, expect)
                    except TornTransfer as e:
                        torn += 1
                        if torn_counter is not None:
                            torn_counter.inc()
                        trace.anomaly("migration_torn",
                                      depth=int(msg["depth"]),
                                      xfer=xfer, error=str(e))
                        trace.flush()
                        if torn > max_refetch:
                            raise MigrationError(
                                f"{torn} torn pages — aborting "
                                f"(last: {e})") from e
                if d not in got:
                    raise MigrationError(
                        f"depth-{d} page unrecoverable after re-fetch")
            payloads.update(got)
            # commit this window before requesting the next: the
            # cumulative contiguous chain [0, hi) imports; already-
            # imported depths are skipped inside import_chain
            if all(d in payloads for d in range(hi)):
                imported = engine.import_chain(
                    prompt, [payloads[d] for d in range(hi)]) + imported
            lo = hi
        return {"pages": imported, "chain_len": int(chain_len),
                "torn": torn}
    finally:
        try:
            wire.send({"op": "page_fetch", "xfer": xfer,
                       "release": True})
        except (OSError, ValueError):
            pass                  # peer gone: its conn teardown
            # releases the hold server-side
        wire.close()
