"""Serving metrics — latency percentiles + throughput, in the same
BenchmarkMetric shape the training side logs (utils/benchmark_logger:
one ``{"name", "value", "unit", ...}`` record per metric), so the
benchmark infrastructure consumes training and serving runs uniformly.

The aggregation math lives in the obs metrics registry
(dtf_tpu/obs/registry.py) — this module's percentiles are registry
Histogram snapshots, not a second ad-hoc implementation.  The live
operational counters (queue depth, sheds, slot occupancy) are on
``ServeEngine.metrics`` directly; this aggregate is the post-run view.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from dtf_tpu.obs.registry import Histogram


@dataclasses.dataclass
class ServingStats:
    """Aggregate of one serving run (ServeEngine.completed)."""

    num_requests: int
    num_shed: int
    total_new_tokens: int
    wall_time_s: float
    tokens_per_s: float
    latency_p50_s: float
    latency_p90_s: float
    latency_p99_s: float
    ttft_p50_s: float                  # time to first token
    ttft_p99_s: float
    queue_wait_p50_s: float

    def to_metrics(self) -> List[dict]:
        """BenchmarkMetric-format records (name/value/unit)."""
        return [
            {"name": "serve_requests", "value": float(self.num_requests),
             "unit": "requests"},
            {"name": "serve_shed", "value": float(self.num_shed),
             "unit": "requests"},
            {"name": "serve_tokens_per_second",
             "value": self.tokens_per_s, "unit": "tokens/s"},
            {"name": "serve_latency_p50", "value": self.latency_p50_s,
             "unit": "s"},
            {"name": "serve_latency_p90", "value": self.latency_p90_s,
             "unit": "s"},
            {"name": "serve_latency_p99", "value": self.latency_p99_s,
             "unit": "s"},
            {"name": "serve_ttft_p50", "value": self.ttft_p50_s,
             "unit": "s"},
            {"name": "serve_ttft_p99", "value": self.ttft_p99_s,
             "unit": "s"},
            {"name": "serve_queue_wait_p50",
             "value": self.queue_wait_p50_s, "unit": "s"},
        ]


def collect_stats(results, shed_count: int = 0,
                  wall_time_s: Optional[float] = None) -> ServingStats:
    """Aggregate a list of ServeResult into :class:`ServingStats`.

    ``wall_time_s``: measured serving window; None derives it from the
    earliest submit to the latest finish (the results' absolute
    timestamps), which is exact for any traffic shape."""
    results = [r for r in results if not r.cancelled]
    if not results:
        return ServingStats(0, shed_count, 0, 0.0, 0.0,
                            0.0, 0.0, 0.0, 0.0, 0.0, 0.0)
    lat = Histogram("latency", unit="s")
    ttft = Histogram("ttft", unit="s")
    qw = Histogram("queue_wait", unit="s")
    for r in results:
        lat.observe(r.latency_s)
        ttft.observe(r.time_to_first_token_s)
        qw.observe(r.queue_wait_s)
    total_tokens = int(sum(len(r.tokens) for r in results))
    if wall_time_s is None:
        wall_time_s = (max(r.finish_time for r in results)
                       - min(r.submit_time for r in results))
    tps = total_tokens / wall_time_s if wall_time_s > 0 else 0.0
    return ServingStats(
        num_requests=len(results),
        num_shed=int(shed_count),
        total_new_tokens=total_tokens,
        wall_time_s=float(wall_time_s),
        tokens_per_s=float(tps),
        latency_p50_s=lat.percentile(50), latency_p90_s=lat.percentile(90),
        latency_p99_s=lat.percentile(99),
        ttft_p50_s=ttft.percentile(50), ttft_p99_s=ttft.percentile(99),
        queue_wait_p50_s=qw.percentile(50))
