"""Serving subsystem: checkpoint→inference bridge, KV-cache decode,
dynamic batching engine (see docs in each module)."""

from dtf_tpu.serve.bridge import (load_for_serving,       # noqa: F401
                                  load_inference_variables,
                                  place_for_serving,
                                  serving_memory_plan, serving_mesh)
from dtf_tpu.serve.decode import (Decoder, init_cache,    # noqa: F401
                                  init_paged_cache,
                                  make_decode_model,
                                  teacher_forced_logits)
from dtf_tpu.serve.engine import (Backpressure, PagePool,  # noqa: F401
                                  ServeEngine, ServeRequest, ServeResult)
from dtf_tpu.serve.metrics import ServingStats, collect_stats  # noqa: F401
from dtf_tpu.serve.replica import ReplicaServer  # noqa: F401
from dtf_tpu.serve.rollout import (RolloutController,  # noqa: F401
                                   RolloutState)
from dtf_tpu.serve.router import (DeadlineExceeded, Router,  # noqa: F401
                                  RouterResult, replica_spawner)
