"""Serving replica tier: a health-checked router over N replica serve
processes.

PR 7 made one serving process multi-chip (TP shards the model); heavy
traffic needs many serving PROCESSES.  This module is the front-end
that owns the client-facing queue and fans requests out to N replicas
(each a full ServeEngine behind serve/replica.py's wire protocol),
with failure handling as first-class contracts rather than an operator
reading ``log7.log``:

  placement — PREFIX-AFFINE by default: requests are routed by the
      chained prefix digest of their full prompt pages (the same
      digest chain the engine's PrefixRegistry keys on), so traffic
      sharing a system prompt lands on the replica whose registry is
      already warm — a prefix hit there costs zero prefill pages,
      while scattering the same traffic re-prefills the prompt once
      per replica.  Fallback (and tie-break) is least-loaded; a
      ``random`` policy exists for the bench A/B.
  health — per-replica liveness comes from the obs heartbeat files
      (``heartbeat_rank{K}.json``) the replica's ENGINE LOOP rewrites,
      read by a prober at a fixed tick — never from the socket, so a
      wedged replica with a healthy TCP stack still reads as dead, and
      a network partition (probes dropped, process fine) reads exactly
      like a stall: silence.  The announce file (``replica_rank{K}
      .json``, ephemeral port + pid) is the re-registration channel: a
      respawned or healed replica re-registers by rewriting it.
  deadlines — every request carries one; a scan at dispatch-loop
      cadence fails overdue requests with :class:`DeadlineExceeded`.
      Degrade, never hang: every accepted request resolves — tokens,
      Backpressure, or DeadlineExceeded — within its deadline.
  retry / failover — a dead or unreachable replica's in-flight
      requests re-dispatch transparently with exponential backoff.
      Decode is deterministic (greedy), so a re-dispatched request
      reproduces its token stream exactly; the router dedupes by token
      index (already-delivered tokens are verified, not re-emitted) so
      a client stream sees each token once.  A divergence (sampled
      requests re-dispatch with a different engine RNG) is counted and
      flagged, never silently mixed.
  backpressure — a replica's ``Backpressure(retry_after)`` marks it
      saturated until retry_after and the request tries its siblings
      ONCE each; when every live replica has shed it, the Backpressure
      propagates to the client instead of becoming a router retry
      storm.  A router-level admission bound sheds new submits loudly
      (``router_shed`` anomaly) when the outstanding count hits it.
  respawn — when the router owns the replica processes, a dead one
      respawns under the PR-4 supervisor discipline: a sliding-window
      budget with exponential backoff, then loud give-up.  The fresh
      process re-announces (new port, same file) and the prober folds
      it back in.

  disaggregation — ``prefill_replicas=P`` splits the tier into a
      prefill pool (replicas 0..P-1) and a decode pool (the rest).
      Cold prompts (affinity miss) route to the prefill pool; when a
      prefill-pool replica finishes a request whose prompt has full
      KV pages, the router RE-HOMES the chain: it commands the
      least-loaded decode replica to pull the pages over the wire
      (serve/migrate.py — ``migrate_in`` → ``page_fetch`` against the
      prefill replica's own server socket) and, on the ``migrated``
      ack, moves the prefix-owner entries so sibling traffic decodes
      in the decode pool with near-zero prefill.  Migration failure
      is an EFFICIENCY loss, never a correctness event: the chain
      just stays where it is and the next miss re-prefills —
      ``migration_failed`` is counted + flagged, no request is
      touched.  ``prefill_replicas=0`` (default) is the colocated
      tier, byte-identical to the pre-disaggregation router.

  high availability — the router itself is no longer a single point
      of failure.  Every request's lifecycle is journaled to an
      append-only WAL in the rendezvous dir (serve/journal.py) so a
      SUCCESSOR router — a restart, or a warm standby holding the
      shared-storage leader lease (serve/ha.py) — replays it and
      RE-ADOPTS the in-flight requests: a new ``reattach`` wire op
      rebinds each one to the replica still decoding it (engines never
      stopped — a router death is an efficiency blip, not an outage)
      and the replica replays its retained token tail through the SAME
      token-index verify+dedupe that makes replica failover
      exactly-once.  Split-brain is fenced by a monotonic epoch: every
      controller wire op carries it, replicas reject ops from a
      superseded epoch (``stale_epoch``), and a fenced router sheds
      instead of double-driving the tier.

Chaos composes (dtf_tpu/chaos): ``replica_kill@req:N`` SIGKILLs a
replica at the Nth dispatch, ``net_partition@replica<K>:<ticks>``
drops K's health probes for that many prober ticks (timeouts, not
clean exits), ``slow_replica@replica<K>:<factor>`` stretches K's
decode steps, ``page_fetch_stall@replica<K>:<s>`` stalls K's
migration client before each page-fetch window.  tools/
router_smoke.py drives the matrix and pins token-exactness + zero
lost requests (ci_check stage 9); tools/disagg_smoke.py pins the
disaggregated tier token-exact against a colocated oracle;
``router_kill@req:N`` + ``lease_stall@<ticks>`` drive the HA matrix
(tools/router_ha_smoke.py, ci_check stage 17).
"""

from __future__ import annotations

import collections
import dataclasses
import json
import logging
import os
import queue as queue_mod
import socket
import struct
import subprocess
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from dtf_tpu import chaos
from dtf_tpu.obs import trace
from dtf_tpu.obs.registry import MetricsRegistry
from dtf_tpu.obs.watchdog import heartbeat_path, read_heartbeat
from dtf_tpu.serve import journal as journal_mod
from dtf_tpu.serve.engine import Backpressure, _page_digest
from dtf_tpu.serve.replica import read_announce, send_msg

log = logging.getLogger("dtf_tpu")

PLACEMENTS = ("affinity", "least_loaded", "random")


class DeadlineExceeded(RuntimeError):
    """The request did not finish inside its deadline.  The router
    resolves it LOUDLY at the deadline instead of letting the client
    wait on a promise nobody is working on."""

    def __init__(self, request_id: int, deadline_s: float, detail: str = ""):
        super().__init__(
            f"request {request_id} exceeded its {deadline_s:.1f}s "
            f"deadline{': ' + detail if detail else ''}")
        self.request_id = request_id
        self.deadline_s = deadline_s


@dataclasses.dataclass
class RouterResult:
    request_id: int
    tokens: List[int]
    prompt_len: int
    latency_s: float
    replica: int                 # replica that completed it
    redispatches: int            # failover count this request survived
    diverged: bool               # re-dispatched tokens mismatched the
                                 # already-delivered prefix (sampled
                                 # requests only; greedy never)
    submit_time: float = 0.0
    finish_time: float = 0.0
    # the router-minted distributed-trace id: every record this request
    # produced — router events, replica spans, failover replays —
    # carries it; `trace_main --request <id>` renders the timeline
    trace_id: Optional[str] = None
    # the model-version label of the replica(s) that served it — ONE
    # label by construction (version-affine placement); "" outside a
    # rollout
    version: str = ""


class RouterHandle:
    """Future-lite for one routed request: ``result()`` blocks (raising
    Backpressure/DeadlineExceeded when that's how it resolved);
    ``stream()`` yields tokens as replicas deliver them, each exactly
    once across failovers."""

    def __init__(self, req: "_Request"):
        self.request = req
        self._event = threading.Event()
        self._result: Optional[RouterResult] = None
        self._exc: Optional[BaseException] = None
        self._q: "queue_mod.Queue" = queue_mod.Queue()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> RouterResult:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.request.id} not resolved in {timeout}s")
        if self._exc is not None:
            raise self._exc
        return self._result

    def stream(self, timeout: Optional[float] = None):
        """Iterator over tokens; ends when the request resolves.  A
        request that resolved in failure raises its exception here
        too, so a streaming consumer cannot mistake a shed request
        for a short answer."""
        while True:
            try:
                kind, payload = self._q.get(timeout=timeout)
            except queue_mod.Empty:
                raise TimeoutError(
                    f"request {self.request.id}: no token in {timeout}s"
                ) from None
            if kind == "done":
                if self._exc is not None:
                    raise self._exc
                return
            yield payload

    # router-side delivery (under the router lock)
    def _emit(self, token: int) -> None:
        self._q.put(("token", int(token)))

    def _deliver(self, result: RouterResult) -> None:
        self._result = result
        self._event.set()
        self._q.put(("done", None))

    def _fail(self, exc: BaseException) -> None:
        self._exc = exc
        self._event.set()
        self._q.put(("done", None))


class _Request:
    __slots__ = ("id", "prompt", "max_new_tokens", "temperature",
                 "eos_id", "deadline", "deadline_s", "digests", "handle",
                 "delivered", "attempt", "next_try", "active",
                 "bp_replicas", "redispatches", "diverged", "done",
                 "submit_time", "last_dispatch", "last_progress",
                 "trace", "span", "queue_wait", "rng_seed", "version")

    def __init__(self, rid: int, prompt: np.ndarray, max_new_tokens: int,
                 temperature: float, eos_id, deadline_s: float,
                 digests: List[str], trace_id: Optional[str] = None,
                 rng_seed: Optional[int] = None):
        self.id = rid
        # distributed span context: one trace id for the request's
        # whole cross-process life, one router-side span id the
        # replica-side records link back to (parent_span)
        self.trace = trace_id or trace.new_trace_id()
        self.span = trace.new_span_id()
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.eos_id = eos_id
        self.deadline_s = deadline_s
        self.submit_time = time.time()
        self.deadline = time.monotonic() + deadline_s
        self.digests = digests
        self.handle = RouterHandle(self)
        self.delivered: List[int] = []
        self.attempt = 0
        self.next_try = 0.0
        self.active: Dict[str, int] = {}   # wire_id -> replica id
        self.bp_replicas: set = set()
        self.redispatches = 0
        self.diverged = False
        self.done = False
        self.last_dispatch = 0.0
        self.last_progress = 0.0
        self.queue_wait: Optional[float] = None
        # wire-carried sampling identity: every dispatch (failover
        # replays included) ships the SAME seed, so sampled requests
        # replay token-exactly like greedy ones
        self.rng_seed = rng_seed
        # model-version affinity: latched to the FIRST dispatch's
        # replica version — during a rollout, a failover may only
        # land on a replica serving the same model, so a client
        # stream is never a mix of two checkpoints
        self.version: Optional[str] = None


class _Shadow:
    """Canary-mirror bookkeeping for one mirrored request: the shadow
    copy runs on the new-checkpoint canary, its tokens are COMPARED
    against the primary's (old model), never delivered."""

    __slots__ = ("req", "wire_id", "replica", "tokens", "shadow_done",
                 "primary", "created")

    def __init__(self, req: _Request, wire_id: str, replica: int):
        self.req = req
        self.wire_id = wire_id
        self.replica = replica
        self.tokens: Optional[List[int]] = None   # canary's answer
        self.shadow_done = False
        self.primary: Optional[List[int]] = None  # old model's answer
        self.created = time.monotonic()


class _Replica:
    """Router-side state for one replica."""

    def __init__(self, rid: int, rendezvous_dir: str):
        self.id = rid
        self.rendezvous_dir = rendezvous_dir
        self.proc: Optional[subprocess.Popen] = None
        self.generation = 0
        self.host: str = "127.0.0.1"
        self.port: Optional[int] = None
        self.announced_pid: Optional[int] = None
        self.conn: Optional[socket.socket] = None
        self.wfile = None
        self.wlock = threading.Lock()
        self.healthy = False
        self.gave_up = False
        self.inflight: Dict[str, _Request] = {}
        self.saturated_until = 0.0
        self.last_beat_mono = time.monotonic()
        self.last_beat_ts = None
        self.hb_mtime = None
        self.respawn_times: collections.deque = collections.deque()
        self.respawn_at: Optional[float] = None
        self.completed = 0
        self.last_stats: Dict[str, dict] = {}   # tag -> stats msg
        # rollout surface (serve/rollout.py): a draining replica takes
        # no new placements; a shadow-only replica (the canary) takes
        # ONLY mirrored traffic; hold_respawn parks the prober's
        # auto-respawn while the rollout controller owns the process;
        # version is the model-identity label version-affine placement
        # matches against (all-"" outside a rollout → no constraint)
        self.draining = False
        self.shadow_only = False
        self.hold_respawn = False
        self.reconnect_block = False
        self.version: str = ""
        # disaggregation pool role: "both" (colocated default),
        # "prefill" or "decode" when the router splits the tier
        self.role: str = "both"


class Router:
    """The replica-tier front-end.  See the module docstring.

    ``spawn`` is a callable ``(replica_id, generation) -> Popen`` that
    starts one replica process (see :func:`replica_spawner`); None
    means the replicas are managed externally (tests, or an operator
    supervising them separately) — the router then only connects,
    probes, and fails over, and ``kill_hook`` (tests) stands in for
    SIGKILL when chaos wants a replica dead.

    LOCK DISCIPLINE: ``_mu`` guards every piece of routing state the
    dispatcher, prober, reader threads and client callers share —
    declared below in ``_GUARDED_BY`` and ENFORCED STATICALLY by
    ``tools/dtflint`` (rule lock-guard): any touch of a guarded
    attribute outside ``with self._mu`` (or a ``*_locked`` method,
    which asserts its caller holds the lock) fails CI.  NOT guarded,
    deliberately: ``_replicas`` (the list itself is fixed at
    construction; per-replica fields mutate under ``_mu`` through the
    ``*_locked`` paths), ``_stopping``/``_started``/``_draining``-free
    latches read by the loops (``_stopping`` is a monotonic bool whose
    racy read only costs one extra loop tick), and the metrics objects
    (internally consistent counters)."""

    _GUARDED_BY = {
        "_queue": "_mu", "_live": "_mu", "_outstanding": "_mu",
        "_ids": "_mu", "_dispatch_seq": "_mu", "_prefix_owner": "_mu",
        "_shadows": "_mu", "_shadow_by_req": "_mu", "_mirror": "_mu",
        "_mirror_acc": "_mu", "_stats_events": "_mu",
        "_draining": "_mu", "_ewma_latency": "_mu",
        "_migrations": "_mu", "_fenced": "_mu", "_chain_heat": "_mu",
    }

    def __init__(self, num_replicas: int, rendezvous_dir: str, *,
                 spawn: Optional[Callable] = None,
                 page_size: int = 16,
                 placement: str = "affinity",
                 deadline_s: float = 120.0,
                 admission_limit: int = 128,
                 probe_interval_s: float = 0.25,
                 health_timeout_s: float = 15.0,
                 replica_inflight: int = 16,
                 retry_backoff_s: float = 0.05,
                 max_retry_backoff_s: float = 2.0,
                 max_respawns: int = 8,
                 respawn_window_s: float = 300.0,
                 respawn_backoff_s: float = 0.5,
                 hedge_s: float = 0.0,
                 kill_hook: Optional[Callable] = None,
                 checkpoint_map: Optional[Dict[int, str]] = None,
                 prefill_replicas: int = 0,
                 migrate_timeout_s: float = 60.0,
                 seed: int = 0,
                 journal_path: Optional[str] = None,
                 journal_fsync_s: float = 0.05,
                 epoch: int = 0,
                 role: str = "leader",
                 crash_hook: Optional[Callable] = None):
        if num_replicas < 1:
            raise ValueError(f"need >= 1 replica, got {num_replicas}")
        if placement not in PLACEMENTS:
            raise ValueError(f"unknown placement {placement!r}; choose "
                             f"from {PLACEMENTS}")
        prefill_replicas = int(prefill_replicas)
        if prefill_replicas < 0 or prefill_replicas >= num_replicas:
            if prefill_replicas != 0:
                raise ValueError(
                    f"prefill_replicas ({prefill_replicas}) must leave "
                    f"at least one decode replica (num_replicas="
                    f"{num_replicas})")
        if prefill_replicas and placement != "affinity":
            raise ValueError(
                "disaggregation (prefill_replicas > 0) needs "
                "placement='affinity' — pool re-homing rides the "
                "prefix-owner map")
        if probe_interval_s >= health_timeout_s:
            raise ValueError(
                f"probe_interval_s ({probe_interval_s}) must be < "
                f"health_timeout_s ({health_timeout_s}) — a health "
                f"verdict needs multiple probe ticks")
        self.rendezvous_dir = os.path.abspath(rendezvous_dir)
        os.makedirs(self.rendezvous_dir, exist_ok=True)
        self._spawn = spawn
        self.page_size = int(page_size)
        self.placement = placement
        self.deadline_s = float(deadline_s)
        self.admission_limit = int(admission_limit)
        self.probe_interval_s = float(probe_interval_s)
        self.health_timeout_s = float(health_timeout_s)
        self.replica_inflight = int(replica_inflight)
        self.retry_backoff_s = float(retry_backoff_s)
        self.max_retry_backoff_s = float(max_retry_backoff_s)
        self.max_respawns = int(max_respawns)
        self.respawn_window_s = float(respawn_window_s)
        self.respawn_backoff_s = float(respawn_backoff_s)
        self.hedge_s = float(hedge_s)
        self._kill_hook = kill_hook
        self._rng = np.random.default_rng(seed)
        # HA identity: the fencing epoch this router controls the tier
        # under (0 = HA off / first leader), stamped on every
        # controller wire op; role is reporting-only (health/healthz)
        self.epoch = int(epoch)
        self.role = str(role)
        self._fenced = False
        self._crash_hook = crash_hook

        self._mu = threading.Condition()
        self._replicas = [_Replica(i, self.rendezvous_dir)
                          for i in range(int(num_replicas))]
        self.prefill_replicas = prefill_replicas
        self.migrate_timeout_s = float(migrate_timeout_s)
        if prefill_replicas:
            for r in self._replicas:
                r.role = ("prefill" if r.id < prefill_replicas
                          else "decode")
        # in-flight chain migrations: xfer id -> bookkeeping (digests
        # to re-home, source/target ids, start time, trace id)
        self._migrations: Dict[str, dict] = {}
        self._queue: List[_Request] = []
        self._live: Dict[int, _Request] = {}
        self._outstanding = 0
        self._ids = 0
        self._dispatch_seq = 0
        self._draining = False
        self._stopping = False
        self._ewma_latency = 0.5
        # digest -> replica id, insertion-ordered and BOUNDED: routing
        # state must not grow with total traffic (the replica-side
        # registry it mirrors is bounded by pool pages; stale owners
        # only cost a least-loaded fallback)
        self._prefix_owner: Dict[str, int] = {}
        self._prefix_owner_cap = 65536
        self._stats_events: Dict[str, threading.Event] = {}
        # per-replica checkpoint overrides, consulted by the spawner at
        # spawn time (replica_spawner's checkpoint_map) — the rollout
        # controller points a replica at the NEW checkpoint here before
        # respawning it.  Shared BY REFERENCE with the spawner closure.
        self.replica_checkpoints: Dict[int, str] = (
            checkpoint_map if checkpoint_map is not None else {})
        # canary mirroring: (replica id, fraction) while a rollout's
        # canary arm is comparing; shadows keyed by shadow wire id +
        # by primary request id (the comparison needs both answers)
        self._mirror: Optional[tuple] = None
        self._mirror_acc = 0.0
        self._shadows: Dict[str, _Shadow] = {}
        self._shadow_by_req: Dict[int, _Shadow] = {}
        # hot-chain tracker for the heal-time KV prefetch: deepest
        # digest -> (dispatch count, full digest chain, prompt tokens).
        # BOUNDED like the owner map — insertion-ordered, oldest out
        self._chain_heat: Dict[str, tuple] = {}
        self._chain_heat_cap = 64

        # obs registry: the router's operational vocabulary
        self.metrics = MetricsRegistry()
        m = self.metrics
        self._m_queue_depth = m.gauge("router_queue_depth", unit="requests")
        self._m_inflight = m.gauge("router_inflight", unit="requests")
        self._m_dispatch = m.counter("router_dispatch_total",
                                     unit="requests")
        self._m_completed = m.counter("router_completed_total",
                                      unit="requests")
        self._m_shed = m.counter("router_shed_total", unit="requests")
        self._m_bp_relayed = m.counter("router_backpressure_relayed_total",
                                       unit="requests")
        self._m_failover = m.counter("router_failover_total",
                                     unit="requests")
        self._m_hedge = m.counter("router_hedge_total", unit="requests")
        self._m_deadline = m.counter("router_deadline_exceeded_total",
                                     unit="requests")
        self._m_affinity_hit = m.counter("router_affinity_hits_total",
                                         unit="requests")
        self._m_affinity_miss = m.counter("router_affinity_miss_total",
                                          unit="requests")
        self._m_stale = m.counter("router_stale_msgs_total", unit="msgs")
        self._m_diverged = m.counter("router_redispatch_divergence_total",
                                     unit="requests")
        self._m_respawns = m.counter("router_replica_respawns_total",
                                     unit="replicas")
        self._m_latency = m.histogram("router_latency_s", unit="s")
        # CANCEL fan-out: stale attempts (deadline-exceeded, losing
        # hedge, resolved-elsewhere) told to stop decoding — reclaimed
        # replica capacity, not just discarded answers
        self._m_cancel = m.counter("router_cancel_sent_total",
                                   unit="requests")
        # prefix owner-map handoff: digests re-homed to the warmest
        # sibling when their owner is drained/replaced/lost
        self._m_rehomed = m.counter("router_prefix_rehomed_total",
                                    unit="digests")
        # canary arm (rollout): mirrored shadow traffic and its
        # token-by-token verdicts against the old model
        self._m_mirrored = m.counter("router_canary_mirrored_total",
                                     unit="requests")
        self._m_compared = m.counter("router_canary_compared_total",
                                     unit="requests")
        self._m_canary_div = m.counter("router_canary_diverged_total",
                                       unit="requests")
        self._m_first_div = m.gauge("router_canary_first_divergence_pos",
                                    unit="position")
        self._m_first_div.set(-1)
        # must stay 0: a client stream mixing two model versions
        self._m_mixed = m.counter("router_mixed_model_total",
                                  unit="requests")
        # planned (rollout) replica replacements — NOT failures, so
        # they are counted apart from router_replica_respawns_total
        self._m_replaced = m.counter("router_replica_replacements_total",
                                     unit="replicas")
        # submit → first dispatch: the router-side queueing delay the
        # capacity simulator's queueing model calibrates against
        # (serve_stream_lag_s's missing sibling)
        self._m_queue_wait = m.histogram("router_queue_wait_s", unit="s")
        # disaggregation: chains re-homed prefill pool -> decode pool
        # over the wire, and the migrations that didn't make it (an
        # efficiency loss, never a lost request)
        self._m_migrations = m.counter("router_migrations_total",
                                       unit="chains")
        self._m_mig_failed = m.counter("router_migration_failed_total",
                                       unit="chains")
        self._m_health = [m.gauge(f"router_replica{i}_healthy",
                                  unit="bool")
                          for i in range(int(num_replicas))]
        # HA vocabulary: the fencing epoch this router drives the tier
        # under, takeovers performed, requests recovered across a
        # router death (re-attached to a live engine vs re-dispatched
        # from scratch), stale-epoch rejections observed (any > 0 =
        # a superseded controller tried to drive the tier), journal
        # append→fsync lag (the bound on what a host crash can lose),
        # and KV pages pulled by the heal-time prefetch
        self._m_epoch = m.gauge("router_ha_epoch", unit="epoch")
        self._m_epoch.set(self.epoch)
        self._m_takeover = m.counter("router_takeover_total",
                                     unit="takeovers")
        self._m_readopted = m.counter("router_readopted_total",
                                      unit="requests")
        self._m_redispatched = m.counter("router_redispatched_total",
                                         unit="requests")
        self._m_stale_epoch = m.counter("router_stale_epoch_total",
                                        unit="msgs")
        self._m_jlag = m.histogram("router_journal_lag_s", unit="s")
        self._m_prefetch = m.counter("router_prefetch_pages_total",
                                     unit="pages")
        # the crash-recovery WAL (None = HA off, zero overhead):
        # created AFTER the metrics so fsync lag lands in the histogram
        self._journal: Optional[journal_mod.RequestJournal] = None
        if journal_path:
            self._journal = journal_mod.RequestJournal(
                journal_path, fsync_interval_s=journal_fsync_s,
                lag_observe=self._m_jlag.observe)

        self._threads: List[threading.Thread] = []
        self._started = False

    # -- lifecycle -----------------------------------------------------
    def start(self, wait_s: float = 0.0,
              adopt: bool = False) -> "Router":
        """Spawn replicas (proc mode), start the dispatcher + prober.
        ``wait_s`` > 0 blocks until every replica is healthy (raises
        on timeout) — the smoke/bench posture; 0 returns immediately
        and traffic queues until replicas register.

        ``adopt=True`` is the TAKEOVER posture (serve/ha.py): the tier
        is already running under a dead predecessor — do NOT unlink
        its announce/heartbeat files or spawn fresh processes, just
        discover the live replicas through the rendezvous and connect.
        A takeover that respawned the tier would turn a router blip
        into N replica cold-starts."""
        if self._started:
            raise RuntimeError("router already started")
        self._started = True
        if self._spawn is not None and not adopt:
            from dtf_tpu.serve.replica import announce_path
            for r in self._replicas:
                # a heartbeat/announce surviving a previous run must not
                # masquerade as this generation's registration
                for path in (heartbeat_path(self.rendezvous_dir, r.id),
                             announce_path(self.rendezvous_dir, r.id)):
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                r.proc = self._spawn(r.id, r.generation)
        for name, fn in (("router-dispatch", self._dispatch_loop),
                         ("router-probe", self._probe_loop)):
            t = threading.Thread(target=fn, daemon=True, name=name)
            t.start()
            self._threads.append(t)
        if wait_s > 0:
            deadline = time.monotonic() + wait_s
            while time.monotonic() < deadline:
                with self._mu:
                    if all(r.healthy for r in self._replicas):
                        return self
                time.sleep(0.05)
            with self._mu:
                unhealthy = [r.id for r in self._replicas if not r.healthy]
            # a failed start must not leak the tier it spawned: N jax
            # serve processes surviving a TimeoutError would starve the
            # host for whatever runs next
            self.stop(drain=False)
            raise TimeoutError(
                f"replicas {unhealthy} not healthy after {wait_s:.0f}s "
                f"(no heartbeat/announce under {self.rendezvous_dir})")
        return self

    def begin_drain(self) -> None:
        """Stop admitting; queued + in-flight traffic still resolves."""
        with self._mu:
            self._draining = True

    def stop(self, drain: bool = True, timeout: float = 60.0) -> None:
        if drain:
            self.begin_drain()
            deadline = time.monotonic() + timeout
            with self._mu:
                while self._outstanding > 0 and time.monotonic() < deadline:
                    self._mu.wait(timeout=0.1)
        with self._mu:
            self._stopping = True
            stranded = list(self._live.values())
            self._queue.clear()
            self._live.clear()
            for req in stranded:
                if not req.done:
                    req.done = True
                    req.handle._fail(RuntimeError("router stopped"))
            self._outstanding = 0
            self._mu.notify_all()
        for r in self._replicas:
            self._close_conn(r)
            if r.proc is not None and r.proc.poll() is None:
                r.proc.terminate()   # SIGTERM: replicas drain + exit 0
        for r in self._replicas:
            if r.proc is not None:
                try:
                    r.proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    r.proc.kill()
                    r.proc.wait()
        if self._journal is not None:
            self._journal.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop(drain=False)

    # -- client side ---------------------------------------------------
    @property
    def outstanding(self) -> int:
        with self._mu:
            return self._outstanding

    def submit(self, prompt, max_new_tokens: int = 32,
               temperature: float = 0.0, eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               trace_id: Optional[str] = None) -> RouterHandle:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        deadline_s = float(deadline_s if deadline_s is not None
                           else self.deadline_s)
        if deadline_s <= 0:
            raise ValueError(f"deadline must be positive, got {deadline_s}")
        # the request's distributed-trace id is minted HERE (or carried
        # in from an upstream caller) — before admission, so even a
        # shed is attributable to the request that suffered it
        trace_id = trace_id or trace.new_trace_id()
        digests = self._digest_chain(prompt)
        with self._mu:
            if self._stopping:
                raise RuntimeError("router is stopped")
            if self._fenced:
                # a successor holds the lease: this router must not
                # drive the tier (the replicas would reject it anyway)
                raise RuntimeError(
                    f"router fenced (epoch {self.epoch} superseded) — "
                    f"submit to the current leader")
            if self._draining or self._outstanding >= self.admission_limit:
                self._m_shed.inc()
                retry = max(0.05, self._ewma_latency
                            * (1 + self._outstanding
                               / max(1, self.admission_limit)))
                reason = ("draining" if self._draining else
                          f"admission limit {self.admission_limit}")
                log.error("router: shedding request (%s; %d outstanding; "
                          "retry_after=%.2fs)", reason, self._outstanding,
                          retry)
                trace.anomaly("router_shed", reason=reason,
                              outstanding=self._outstanding,
                              retry_after=retry, trace=trace_id)
                raise Backpressure(retry)
            self._ids += 1
            # the request's sampling identity is minted HERE, once —
            # every dispatch (attempt N, hedge twin, failover replay)
            # ships the same seed, so SAMPLED requests replay
            # token-exactly on any same-version replica
            req = _Request(self._ids, prompt, int(max_new_tokens),
                           float(temperature), eos_id, deadline_s, digests,
                           trace_id=trace_id,
                           rng_seed=int(self._rng.integers(0, 2**31 - 1)))
            self._queue.append(req)
            self._live[req.id] = req
            self._outstanding += 1
            self._m_queue_depth.set(len(self._queue))
            if self._journal is not None:
                # journal AFTER admission (a shed is not recovery
                # state) and BEFORE the handle is returned: once the
                # client owns a handle, a successor can always finish
                # the request
                self._journal.submit(
                    str(req.id), prompt=req.prompt,
                    max_new_tokens=req.max_new_tokens,
                    temperature=req.temperature, eos_id=req.eos_id,
                    rng_seed=req.rng_seed, trace=req.trace)
            trace.event("router_submit", request=req.id, trace=req.trace,
                        span_id=req.span, prompt_len=int(prompt.size),
                        deadline_s=deadline_s,
                        queue_depth=len(self._queue))
            self._mu.notify_all()
        return req.handle

    def generate(self, prompt, **kw) -> RouterResult:
        return self.submit(prompt, **kw).result(timeout=600)

    # -- takeover (serve/ha.py drives this) ----------------------------
    def adopt_requests(self, state: dict,
                       delivered: Optional[dict] = None) -> dict:
        """Adopt a dead predecessor's unresolved requests — the
        journal-replay half of a router takeover (``state`` is
        ``journal.unresolved(journal.replay(...))``).

        Each request is rebuilt BIT-IDENTICALLY from its journaled
        submit record (prompt, budget, eos, and above all the minted
        ``rng_seed`` — the sampling identity that makes any replay
        token-exact) and, when its last journaled dispatch points at a
        replica that is still up, RE-ATTACHED there: the ``reattach``
        op makes the replica replay its retained token tail through
        the ordinary verify+dedupe path, so the engine's uninterrupted
        decode is simply picked back up.  A nack (the replica died
        too, or never got it) falls through to ordinary budgeted
        failover re-dispatch.

        Exactly-once across the router death: ``delivered`` maps
        request id -> the token list the CLIENT acknowledges having
        received (the re-connecting client echoes it); those tokens
        are verified, never re-emitted.  Without it, the journal's
        delivery watermark seeds sentinel entries — a lower bound, so
        at most ``watermark cadence - 1`` trailing tokens re-emit to a
        client that can't say what it saw.

        Deadlines restart at takeover (the journal records budgets,
        not wall-clock promises).  Returns ``{"readopted",
        "redispatched", "handles": {id: RouterHandle}}`` — the handles
        are how re-connecting clients resume their streams."""
        readopted = redispatched = 0
        handles: Dict[int, RouterHandle] = {}
        with self._mu:
            self._m_takeover.inc()
            for rid_key in sorted(state, key=lambda k: int(k)):
                st = state[rid_key]
                rid = int(rid_key)
                if rid in self._live:
                    handles[rid] = self._live[rid].handle
                    continue   # idempotent: already adopted
                sub = st["submit"]
                prompt = np.asarray(sub["prompt"], np.int32)
                req = _Request(rid, prompt,
                               int(sub["max_new_tokens"]),
                               float(sub["temperature"]),
                               sub.get("eos_id"), self.deadline_s,
                               self._digest_chain(prompt),
                               trace_id=sub.get("trace"),
                               rng_seed=sub.get("rng_seed"))
                if sub.get("version"):
                    req.version = sub["version"]
                acked = None
                if delivered is not None:
                    acked = delivered.get(rid, delivered.get(str(rid)))
                if acked is not None:
                    req.delivered = [int(t) for t in acked]
                else:
                    req.delivered = [-1] * int(st.get("watermark", 0))
                # the id counter must clear every adopted id, or a new
                # submit would collide with a live adopted request
                self._ids = max(self._ids, rid)
                self._live[rid] = req
                self._outstanding += 1
                handles[rid] = req.handle
                rep = None
                last = st["dispatches"][-1] if st["dispatches"] else None
                if last is not None:
                    k = int(last["replica"])
                    if 0 <= k < len(self._replicas):
                        cand = self._replicas[k]
                        if cand.healthy and cand.wfile is not None:
                            rep = cand
                if rep is not None:
                    # reattach under the PREDECESSOR'S wire id — the
                    # replica's retained tail is keyed by it
                    req.attempt = int(last["attempt"])
                    wire_id = f"{rid}.{req.attempt}"
                    try:
                        send_msg(rep.wfile, rep.wlock,
                                 {"op": "reattach", "id": wire_id,
                                  "epoch": self.epoch})
                    except (OSError, ValueError):
                        rep = None
                    else:
                        req.active[wire_id] = rep.id
                        rep.inflight[wire_id] = req
                        req.last_dispatch = time.monotonic()
                        readopted += 1
                if rep is None:
                    redispatched += 1
                    self._m_redispatched.inc()
                    self._queue.append(req)
            self._m_queue_depth.set(len(self._queue))
            self._mu.notify_all()
        return {"readopted": readopted, "redispatched": redispatched,
                "handles": handles}

    # -- placement -----------------------------------------------------
    def _digest_chain(self, prompt: np.ndarray) -> List[str]:
        """Chained digests of the prompt's FULL pages — the same chain
        the replica-side PrefixRegistry keys on, so routing by it is
        routing to warm registry entries."""
        ps = self.page_size
        out: List[str] = []
        digest = ""
        for d in range(int(prompt.size) // ps):
            digest = _page_digest(
                digest, np.ascontiguousarray(prompt[d * ps:(d + 1) * ps],
                                             np.int32))
            out.append(digest)
        return out

    def _eligible_locked(self, req: _Request, now: float) -> List[_Replica]:
        return [r for r in self._replicas
                if not r.gave_up and r.healthy and r.conn is not None
                and not r.draining and not r.shadow_only
                and (req.version is None or r.version == req.version)
                and r.saturated_until <= now
                and r.id not in req.bp_replicas
                and len(r.inflight) < self.replica_inflight]

    def _place_locked(self, req: _Request,
                      now: float) -> Optional[_Replica]:
        eligible = self._eligible_locked(req, now)
        if not eligible:
            return None
        if self.placement == "random":
            return eligible[int(self._rng.integers(len(eligible)))]
        if self.placement == "affinity" and req.digests:
            # deepest registered digest wins: the replica whose
            # registry holds the longest chain of this prompt
            for digest in reversed(req.digests):
                owner = self._prefix_owner.get(digest)
                if owner is not None:
                    rep = self._replicas[owner]
                    if rep in eligible:
                        self._m_affinity_hit.inc()
                        return rep
            self._m_affinity_miss.inc()
            if self.prefill_replicas:
                # disaggregation: a COLD paged prompt is prefill work —
                # keep it in the prefill pool (the chain re-homes to
                # the decode pool once prefill completes).  Fallback
                # to the full eligible set when the pool is out:
                # availability beats pool purity.
                pool = [r for r in eligible if r.role != "decode"]
                if pool:
                    eligible = pool
        return min(eligible, key=lambda r: (len(r.inflight), r.id))

    # -- dispatcher ----------------------------------------------------
    def _dispatch_loop(self) -> None:
        while not self._stopping:
            with self._mu:
                self._mu.wait(timeout=0.02)
                if self._stopping:
                    return
                now = time.monotonic()
                self._check_deadlines_locked(now)
                for req in list(self._queue):
                    if req.done or req.next_try > now:
                        continue
                    rep = self._place_locked(req, now)
                    if rep is None:
                        self._maybe_shed_locked(req, now)
                        continue
                    self._queue.remove(req)
                    self._dispatch_locked(req, rep)
                if self.hedge_s > 0:
                    self._maybe_hedge_locked(now)
                self._m_queue_depth.set(len(self._queue))
                self._m_inflight.set(sum(len(r.inflight)
                                         for r in self._replicas))

    def _check_deadlines_locked(self, now: float) -> None:
        # canary shadows outlive nothing: one that hasn't completed
        # within its primary's deadline will never gate anything —
        # drop it so the gate's pending count drains
        for sh in [s for s in self._shadows.values()
                   if now - s.created > s.req.deadline_s]:
            self._drop_shadow_locked(sh, "shadow_timeout")
        # migrations that never acked: a wedged transfer must not pin
        # its bookkeeping (or block this chain's next migration) forever
        for xfer in [x for x, m in self._migrations.items()
                     if now - m["t0"] > self.migrate_timeout_s]:
            self._fail_migration_locked(
                xfer, self._migrations.pop(xfer), "timeout")
        for req in list(self._live.values()):
            if req.done or now <= req.deadline:
                continue
            self._m_deadline.inc()
            trace.anomaly("router_deadline", request=req.id,
                          trace=req.trace, deadline_s=req.deadline_s,
                          delivered=len(req.delivered),
                          redispatches=req.redispatches)
            self._resolve_locked(
                req, exc=DeadlineExceeded(
                    req.id, req.deadline_s,
                    detail=f"{len(req.delivered)} tokens delivered, "
                           f"{req.redispatches} re-dispatches"))

    def _maybe_shed_locked(self, req: _Request, now: float) -> None:
        """A queued request no replica can take right now: if every
        candidate is LIVE and has shed it (or is marked saturated),
        propagate Backpressure — waiting would be a retry storm, not a
        queue.  A candidate that is merely dead/partitioned keeps the
        request queued: recovery or the deadline resolves it."""
        alive = [r for r in self._replicas if not r.gave_up]
        # candidates = replicas that could EVER take this request:
        # version-compatible, not shadow-only.  A draining or
        # version-mismatched replica set is a TRANSIENT rollout state,
        # not saturation — the request stays queued (the rollout's
        # drain/rollback restores capacity; the deadline bounds it)
        candidates = [r for r in alive
                      if not r.shadow_only
                      and (req.version is None
                           or r.version == req.version)]
        if not alive:
            retry = max(0.5, self.respawn_backoff_s)
        elif candidates and all(
                r.healthy and not r.draining
                and (r.id in req.bp_replicas
                     or r.saturated_until > now)
                for r in candidates):
            retry = max(0.05, max(r.saturated_until for r in candidates)
                        - now) + self._ewma_latency
        else:
            return
        self._m_bp_relayed.inc()
        trace.anomaly("router_shed", reason="all_replicas_saturated",
                      request=req.id, trace=req.trace, retry_after=retry)
        self._resolve_locked(req, exc=Backpressure(retry))

    def _dispatch_locked(self, req: _Request, rep: _Replica) -> None:
        req.attempt += 1
        wire_id = f"{req.id}.{req.attempt}"
        req.active[wire_id] = rep.id
        rep.inflight[wire_id] = req
        req.last_dispatch = time.monotonic()
        if req.queue_wait is None:
            # queue wait = submit → FIRST dispatch attempt (a
            # failover's later attempts are service disruption, not
            # queueing).  Latched BEFORE the send: a dead replica at
            # first dispatch must not erase the sample — attempt 1
            # never comes again
            req.queue_wait = max(0.0, time.time() - req.submit_time)
            self._m_queue_wait.observe(req.queue_wait)
        seq = self._dispatch_seq
        self._dispatch_seq += 1
        self._m_dispatch.inc()
        # span context + sampling identity ride the wire: the replica
        # tags its per-request records with the SAME trace id and
        # samples with the SAME rng_seed (attempt 2 after a failover
        # included — the replay keeps the request's identity, token
        # stream included)
        msg = {"op": "submit", "id": wire_id,
               "prompt": [int(t) for t in req.prompt],
               "max_new_tokens": req.max_new_tokens,
               "temperature": req.temperature, "eos_id": req.eos_id,
               "rng_seed": req.rng_seed,
               "trace": req.trace, "pspan": req.span,
               "epoch": self.epoch}
        try:
            send_msg(rep.wfile, rep.wlock, msg)
        except (OSError, ValueError, AttributeError):
            self._replica_down_locked(rep, "send_failed")
            return
        if self._journal is not None:
            # a successor reads the LAST dispatch to know which replica
            # may hold this request's retained token tail
            self._journal.dispatch(str(req.id), req.attempt, rep.id)
        # model-version affinity latches at the FIRST successful
        # dispatch: from here on this request only ever runs on
        # replicas serving the same model version (rollout invariant:
        # no client stream mixes checkpoints)
        if req.version is None:
            req.version = rep.version
        # every dispatch record carries the latched first-attempt wait,
        # so the trace keeps the queueing ground truth even when the
        # attempt-1 send itself failed (no attempt-1 record exists)
        trace.event("router_dispatch", request=req.id, trace=req.trace,
                    span_id=req.span, replica=rep.id,
                    attempt=req.attempt,
                    queue_wait_s=round(req.queue_wait, 6))
        # prefix ownership: this replica's registry will hold these
        # pages once the prefill completes — route siblings here
        for digest in req.digests:
            self._prefix_owner.pop(digest, None)   # re-insert at tail
            self._prefix_owner[digest] = rep.id
        while len(self._prefix_owner) > self._prefix_owner_cap:
            self._prefix_owner.pop(next(iter(self._prefix_owner)))
        # hot-chain heat for the heal-time prefetch: remember the
        # paged prompts traffic keeps landing on (and what to replay
        # into migrate_in to pull them)
        if req.digests:
            deepest = req.digests[-1]
            heat = self._chain_heat.pop(deepest, (0, None, None))[0]
            self._chain_heat[deepest] = (
                heat + 1, list(req.digests),
                [int(t) for t in req.prompt])
            while len(self._chain_heat) > self._chain_heat_cap:
                self._chain_heat.pop(next(iter(self._chain_heat)))
        # canary mirroring: a slice of greedy attempt-1 traffic ALSO
        # runs on the new-checkpoint canary, compare-only
        if (self._mirror is not None and req.attempt == 1
                and req.temperature == 0.0):
            self._maybe_mirror_locked(req)
        # chaos replica_kill@req:N — fire AFTER the dispatch so the
        # killed replica holds in-flight work (the case under test)
        target = chaos.replica_kill(seq, rep.id)
        if target is not None:
            self._kill_replica(target)
        # chaos router_kill@req:N — the ROUTER dies at the Nth
        # dispatch, mid-burst, journal un-synced past the fsync
        # cadence: the takeover case router_ha_smoke pins
        if chaos.router_kill(seq):
            self._crash()

    def _maybe_hedge_locked(self, now: float) -> None:
        for req in self._live.values():
            if (req.done or not req.active or len(req.active) != 1
                    or now - max(req.last_dispatch,
                                 req.last_progress) < self.hedge_s):
                continue
            current = next(iter(req.active.values()))
            eligible = [r for r in self._eligible_locked(req, now)
                        if r.id != current]
            if not eligible:
                continue
            rep = min(eligible, key=lambda r: (len(r.inflight), r.id))
            self._m_hedge.inc()
            trace.event("router_hedge", request=req.id, trace=req.trace,
                        slow_replica=current, hedge_replica=rep.id)
            self._dispatch_locked(req, rep)

    # -- canary mirroring (the rollout's token-exact gate arm) ----------
    def start_mirror(self, replica_id: int, fraction: float = 1.0) -> None:
        """Mirror ``fraction`` of greedy attempt-1 traffic to replica
        ``replica_id`` (the new-checkpoint canary) as compare-only
        shadows: the canary's tokens are verified token-by-token
        against the old model's answer and NEVER delivered to a
        client.  Greedy determinism makes any mismatch a model
        difference, not noise — the measurable, gateable quantity the
        rollout's canary gate rides on."""
        if not 0.0 < fraction <= 1.0:
            raise ValueError(f"mirror fraction must be in (0, 1], got "
                             f"{fraction}")
        with self._mu:
            self._mirror = (int(replica_id), float(fraction))
            self._mirror_acc = 0.0
            # per-session gauge: a previous rollout's first-divergence
            # position must not masquerade as this canary's
            self._m_first_div.set(-1)

    def stop_mirror(self) -> None:
        with self._mu:
            self._mirror = None
            self._drop_shadows_locked("mirror_stopped")

    def canary_stats(self) -> dict:
        """The canary gate's inputs: comparisons completed, divergences
        observed, and the first divergence position (-1 = none)."""
        with self._mu:
            return {
                "mirrored": self._m_mirrored.value,
                "compared": self._m_compared.value,
                "diverged": self._m_canary_div.value,
                "first_divergence_pos": self._m_first_div.value,
                "pending": len(self._shadows),
            }

    def _maybe_mirror_locked(self, req: _Request) -> None:
        rid, fraction = self._mirror
        rep = self._replicas[rid]
        if rep.wfile is None or not rep.healthy:
            return
        # deterministic fractional selection: an accumulator, not a
        # coin flip — "mirror 1 in k" means exactly that
        self._mirror_acc += fraction
        if self._mirror_acc < 1.0:
            return
        self._mirror_acc -= 1.0
        wire_id = f"s{req.id}"
        sh = _Shadow(req, wire_id, rid)
        try:
            send_msg(rep.wfile, rep.wlock,
                     {"op": "submit", "id": wire_id,
                      "prompt": [int(t) for t in req.prompt],
                      "max_new_tokens": req.max_new_tokens,
                      "temperature": req.temperature,
                      "eos_id": req.eos_id, "rng_seed": req.rng_seed,
                      "trace": req.trace, "pspan": req.span,
                      "epoch": self.epoch})
        except (OSError, ValueError):
            return
        self._shadows[wire_id] = sh
        self._shadow_by_req[req.id] = sh
        self._m_mirrored.inc()
        trace.event("canary_mirror", request=req.id, trace=req.trace,
                    replica=rid)

    def _on_shadow_msg_locked(self, sh: _Shadow, msg: dict) -> None:
        op = msg.get("op")
        if op == "done":
            sh.tokens = [int(t) for t in msg.get("tokens", [])]
            sh.shadow_done = True
            self._compare_shadow_locked(sh)
        elif op in ("backpressure", "error"):
            # the canary refused the shadow: not a comparison, not a
            # divergence — drop it (the gate counts COMPLETED compares)
            self._drop_shadow_locked(sh, f"shadow_{op}")
        # token msgs are ignored: the comparison runs on the final
        # answer (greedy: the prefix property makes them equivalent)

    def _compare_shadow_locked(self, sh: _Shadow) -> None:
        if sh.tokens is None or sh.primary is None:
            return   # the other half hasn't answered yet
        self._shadows.pop(sh.wire_id, None)
        self._shadow_by_req.pop(sh.req.id, None)
        self._m_compared.inc()
        first_div = -1
        if sh.tokens != sh.primary:
            n = min(len(sh.tokens), len(sh.primary))
            first_div = next(
                (i for i in range(n) if sh.tokens[i] != sh.primary[i]),
                n)
            self._m_canary_div.inc()
            if (self._m_first_div.value < 0
                    or first_div < self._m_first_div.value):
                self._m_first_div.set(first_div)
            trace.anomaly("canary_divergence", request=sh.req.id,
                          trace=sh.req.trace, first_divergence=first_div,
                          old=sh.primary[:8], new=sh.tokens[:8])
        trace.event("canary_compare", request=sh.req.id,
                    trace=sh.req.trace, diverged=first_div >= 0,
                    first_divergence=first_div)

    def _drop_shadow_locked(self, sh: _Shadow, reason: str) -> None:
        """Abandon one shadow: forget it AND tell the canary to stop
        decoding it — a dropped comparison must not keep burning the
        canary capacity the remaining comparisons are waiting on."""
        self._shadows.pop(sh.wire_id, None)
        self._shadow_by_req.pop(sh.req.id, None)
        if not sh.shadow_done:
            rep = self._replicas[sh.replica]
            if rep.wfile is not None:
                try:
                    send_msg(rep.wfile, rep.wlock,
                             {"op": "cancel", "id": sh.wire_id,
                              "epoch": self.epoch})
                    self._m_cancel.inc()
                except (OSError, ValueError):
                    pass
        trace.event("canary_drop", request=sh.req.id, trace=sh.req.trace,
                    reason=reason)

    def _drop_shadows_locked(self, reason: str) -> None:
        for sh in list(self._shadows.values()):
            self._drop_shadow_locked(sh, reason)

    def kill_replica(self, replica_id: int) -> None:
        """SIGKILL a replica (chaos drills, the bench's kill-under-load
        scenario).  The death is then DETECTED like any other — probe/
        conn-EOF/proc-poll — so the full failover + respawn machinery
        runs; nothing is short-circuited."""
        self._kill_replica(int(replica_id))

    def _kill_replica(self, target: int) -> None:
        rep = self._replicas[target]
        if rep.proc is not None:
            rep.proc.kill()
        elif self._kill_hook is not None:
            self._kill_hook(target)
        else:
            log.error("router: chaos wants replica %d killed but the "
                      "router neither owns its process nor has a "
                      "kill_hook", target)

    def _crash(self) -> None:
        """Die NOW, uncleanly — chaos router_kill.  No drain, no
        journal sync, no request resolution: the successor must
        recover from exactly what a SIGKILL leaves behind.  In-process
        tiers (tests) substitute ``crash_hook``, which must freeze
        this router the same way (close transports, stop loops,
        resolve nothing)."""
        if self._crash_hook is not None:
            self._crash_hook()
            return
        log.error("router: chaos router_kill — dying uncleanly")
        os._exit(chaos.EXIT_INJECTED_CRASH)

    # -- replica message handling --------------------------------------
    def _on_msg(self, rep: _Replica, msg: dict) -> None:
        op = msg.get("op")
        if op == "stale_epoch":
            # a replica refused one of our ops: a successor holds a
            # higher fencing epoch.  LATCH fenced — this router must
            # stop driving the tier entirely (shed new submits, stop
            # resolving), because anything it delivered from here on
            # could double what the real leader delivers.
            with self._mu:
                self._m_stale_epoch.inc()
                if not self._fenced:
                    self._fenced = True
                    log.error(
                        "router: FENCED — replica %d rejected epoch %d "
                        "(current %s); a successor has taken over",
                        rep.id, self.epoch, msg.get("current"))
                    trace.anomaly("router_fenced", epoch=self.epoch,
                                  current=msg.get("current"),
                                  replica=rep.id)
                req = rep.inflight.pop(msg.get("id"), None)
                if req is not None and not req.done:
                    req.active.pop(msg.get("id"), None)
                    self._resolve_locked(req, exc=RuntimeError(
                        f"request {req.id} fenced: router epoch "
                        f"{self.epoch} superseded by "
                        f"{msg.get('current')} — the new leader "
                        f"re-adopted it"))
            return
        if op == "stats":
            tag = msg.get("tag", "")
            with self._mu:
                ev = self._stats_events.pop((rep.id, tag), None)
                if ev is not None:
                    # only a live waiter stores the snapshot (and pops
                    # it on read): an operator polling stats every few
                    # seconds must not grow this dict for the router's
                    # lifetime
                    rep.last_stats[tag] = msg
                    ev.set()
            return
        if op == "migrated":
            with self._mu:
                self._finish_migration_locked(
                    str(msg.get("xfer", "")), rep,
                    ok=bool(msg.get("ok")),
                    pages=int(msg.get("pages", 0)),
                    error=msg.get("error"))
            return
        with self._mu:
            wire_id = msg.get("id")
            sh = self._shadows.get(wire_id)
            if sh is not None and rep.id == sh.replica:
                # canary shadow traffic: compared, never delivered
                self._on_shadow_msg_locked(sh, msg)
                return
            req = rep.inflight.get(wire_id)
            if req is None or req.done:
                self._m_stale.inc()
                return
            if op == "reattached":
                # the replica still held this request's retained tail:
                # re-adoption confirmed.  The tail itself arrives as
                # ordinary token/done msgs (replayed from i=0) and runs
                # the SAME verify+dedupe as a failover replay — nothing
                # else to do here but say so.
                self._m_readopted.inc()
                trace.event("router_readopt", request=req.id,
                            trace=req.trace, replica=rep.id,
                            retained=int(msg.get("n", 0)),
                            engine_done=bool(msg.get("done")))
                return
            if op == "reattach_nack":
                # the request died WITH the replica during the outage
                # (respawn, or the tail was pruned): fall through to
                # ordinary budgeted failover — the journaled rng_seed
                # makes the re-dispatch token-exact anyway
                rep.inflight.pop(wire_id, None)
                req.active.pop(wire_id, None)
                self._m_redispatched.inc()
                self._requeue_locked(req, reason="reattach_nack")
                return
            if op == "token":
                i = int(msg["i"])
                tok = int(msg["token"])
                if i < len(req.delivered):
                    # re-dispatched attempt replaying delivered ground:
                    # verify, don't re-emit (greedy decode makes this an
                    # equality by construction).  A -1 is a takeover
                    # WATERMARK SENTINEL — the journal said the dead
                    # router delivered this index but not its value:
                    # fill it in, still don't re-emit.
                    if req.delivered[i] == -1:
                        req.delivered[i] = tok
                    elif req.delivered[i] != tok and not req.diverged:
                        req.diverged = True
                        self._m_diverged.inc()
                        trace.anomaly("redispatch_divergence",
                                      request=req.id, trace=req.trace,
                                      index=i,
                                      expected=req.delivered[i], got=tok)
                elif i == len(req.delivered):
                    if not req.delivered:
                        # once per request across failovers (a replay's
                        # token 0 lands in the verify branch above):
                        # the stream-delivery milestone of the timeline
                        trace.event("router_first_token", request=req.id,
                                    trace=req.trace, replica=rep.id)
                        if self._journal is not None:
                            self._journal.first_token(str(req.id))
                    req.delivered.append(tok)
                    req.last_progress = time.monotonic()
                    req.handle._emit(tok)
                    if (self._journal is not None
                            and len(req.delivered) % 16 == 0):
                        # bounded-cadence watermark: a successor seeds
                        # its dedupe index at >= this, so re-adoption
                        # VERIFIES the delivered prefix instead of
                        # re-emitting it.  Every 16 tokens, not every
                        # token — the journal stays O(1)-ish per
                        # request, and the watermark only has to be a
                        # LOWER bound (the sentinel fill covers the gap)
                        self._journal.watermark(str(req.id),
                                                len(req.delivered))
                else:
                    self._m_stale.inc()
            elif op == "done":
                rep.inflight.pop(wire_id, None)
                req.active.pop(wire_id, None)
                if msg.get("cancelled"):
                    # the replica cancelled it (unclean shutdown path):
                    # that is a failover, not an answer
                    self._requeue_locked(req, reason="cancelled")
                    return
                tokens = [int(t) for t in msg["tokens"]]
                for i in range(len(req.delivered), len(tokens)):
                    req.handle._emit(tokens[i])
                # -1s are takeover watermark sentinels (value unknown,
                # delivery known) — they verify against anything
                if ((len(req.delivered) > len(tokens)
                     or any(d != -1 and d != t
                            for d, t in zip(req.delivered, tokens)))
                        and not req.diverged):
                    req.diverged = True
                    self._m_diverged.inc()
                    trace.anomaly("redispatch_divergence", request=req.id,
                                  trace=req.trace)
                if req.version is not None and rep.version != req.version:
                    # must be unreachable: version-affine placement
                    # forbids it.  Counted + flagged so a regression
                    # is an alarm, not a silent mixed-model answer
                    self._m_mixed.inc()
                    trace.anomaly("mixed_model", request=req.id,
                                  trace=req.trace,
                                  latched=req.version,
                                  served=rep.version)
                # the canary comparison's old-model half, if this
                # request was mirrored
                csh = self._shadow_by_req.get(req.id)
                if csh is not None:
                    csh.primary = tokens
                    self._compare_shadow_locked(csh)
                # disaggregation: a prefill-pool replica finished a
                # paged prompt — re-home its KV chain to the decode
                # pool so sibling traffic decodes there prefill-free
                if (self.prefill_replicas and rep.role == "prefill"
                        and req.digests):
                    self._maybe_migrate_locked(req, rep)
                rep.completed += 1
                finish = time.time()
                latency = finish - req.submit_time
                self._ewma_latency = (0.8 * self._ewma_latency
                                      + 0.2 * latency)
                self._m_completed.inc()
                self._m_latency.observe(latency)
                trace.event("router_complete", request=req.id,
                            trace=req.trace, span_id=req.span,
                            replica=rep.id, tokens=len(tokens),
                            redispatches=req.redispatches,
                            latency_s=latency)
                self._resolve_locked(req, result=RouterResult(
                    request_id=req.id, tokens=tokens,
                    prompt_len=int(req.prompt.size), latency_s=latency,
                    replica=rep.id, redispatches=req.redispatches,
                    diverged=req.diverged, submit_time=req.submit_time,
                    finish_time=finish, trace_id=req.trace,
                    version=req.version or rep.version))
            elif op == "backpressure":
                rep.inflight.pop(wire_id, None)
                req.active.pop(wire_id, None)
                retry = float(msg.get("retry_after", 0.5))
                rep.saturated_until = time.monotonic() + retry
                req.bp_replicas.add(rep.id)
                self._requeue_locked(req, reason="backpressure",
                                     backoff=False)
            elif op == "error":
                rep.inflight.pop(wire_id, None)
                self._resolve_locked(
                    req, exc=RuntimeError(
                        f"replica {rep.id} rejected request {req.id}: "
                        f"{msg.get('error')}"))

    def _requeue_locked(self, req: _Request, reason: str,
                        backoff: bool = True) -> None:
        if req.done or req.active:
            return   # a hedged twin is still running it
        if backoff:
            req.redispatches += 1
            self._m_failover.inc()
            req.next_try = time.monotonic() + min(
                self.retry_backoff_s * (2.0 ** (req.redispatches - 1)),
                self.max_retry_backoff_s)
        else:
            req.next_try = 0.0
        # the failover leg of the request timeline: same trace id, next
        # dispatch will carry attempt N+1
        trace.event("router_requeue", request=req.id, trace=req.trace,
                    reason=reason, redispatches=req.redispatches,
                    delivered=len(req.delivered))
        if req not in self._queue:
            self._queue.append(req)
        self._mu.notify_all()

    # -- chain migration (disaggregation's re-home path) ----------------
    def _maybe_migrate_locked(self, req: _Request,
                              source: _Replica) -> None:
        """Command a decode replica to PULL ``req``'s KV-page chain
        from ``source`` (a prefill-pool replica that just completed
        it).  Skips quietly when the chain is already decode-homed,
        already in flight, or no decode replica can take it — the
        colocated fallback is always correct, just warmer-pool-less."""
        deepest = req.digests[-1]
        owner = self._prefix_owner.get(deepest)
        if (owner is not None
                and self._replicas[owner].role == "decode"):
            return
        if any(m["digests"] and m["digests"][-1] == deepest
               for m in self._migrations.values()):
            return   # this chain is already migrating
        targets = [r for r in self._replicas
                   if r.role == "decode" and r.healthy
                   and not r.gave_up and not r.draining
                   and not r.shadow_only and r.wfile is not None
                   and (req.version is None or r.version == req.version)]
        if not targets:
            return
        target = min(targets, key=lambda r: (len(r.inflight), r.id))
        xfer = f"m{req.id}.{source.id}.{target.id}"
        try:
            send_msg(target.wfile, target.wlock,
                     {"op": "migrate_in", "xfer": xfer,
                      "host": source.host, "port": source.port,
                      "prompt": [int(t) for t in req.prompt],
                      "epoch": self.epoch})
        except (OSError, ValueError):
            return
        self._migrations[xfer] = {
            "digests": list(req.digests), "source": source.id,
            "target": target.id, "t0": time.monotonic(),
            "trace": req.trace}
        trace.event("chain_migrate", request=req.id, trace=req.trace,
                    xfer=xfer, source=source.id, target=target.id,
                    pages=len(req.digests))

    def _finish_migration_locked(self, xfer: str, rep: _Replica,
                                 ok: bool, pages: int,
                                 error=None) -> None:
        mig = self._migrations.pop(xfer, None)
        if mig is None or rep.id != mig["target"]:
            self._m_stale.inc()
            return
        if ok:
            # re-home the owner map: sibling traffic now finds its
            # warm chain in the decode pool (insertion at tail keeps
            # the bounded map's LRU-ish eviction honest)
            for d in mig["digests"]:
                self._prefix_owner.pop(d, None)
                self._prefix_owner[d] = rep.id
            self._m_migrations.inc()
            if mig.get("prefetch"):
                # heal-time prefetch: these pages were pulled to warm
                # a healed/respawned replica instead of re-prefilling
                self._m_prefetch.inc(pages)
            trace.event("chain_migrated", xfer=xfer, trace=mig["trace"],
                        source=mig["source"], target=rep.id,
                        pages=pages)
        else:
            self._fail_migration_locked(xfer, mig,
                                        str(error or "unknown"))

    def _fail_migration_locked(self, xfer: str, mig: dict,
                               error: str) -> None:
        """A migration that didn't make it: counted + flagged, owner
        map untouched (the chain is still warm at the source) — an
        efficiency loss, never a correctness event."""
        self._m_mig_failed.inc()
        trace.anomaly("migration_failed", xfer=xfer, trace=mig["trace"],
                      source=mig["source"], target=mig["target"],
                      error=error)

    def migration_stats(self) -> dict:
        """The disagg smoke/bench's gate inputs."""
        with self._mu:
            return {"migrated": self._m_migrations.value,
                    "failed": self._m_mig_failed.value,
                    "pending": len(self._migrations)}

    def _resolve_locked(self, req: _Request, result=None,
                        exc=None) -> None:
        if req.done:
            return
        req.done = True
        if self._journal is not None:
            # terminal either way — a failed request needs nothing
            # from a successor any more than a completed one does
            self._journal.complete(str(req.id), ok=exc is None)
        self._live.pop(req.id, None)
        if req in self._queue:
            self._queue.remove(req)
        for wid, rid in list(req.active.items()):
            rep = self._replicas[rid]
            rep.inflight.pop(wid, None)
            # CANCEL the attempts nobody is waiting on anymore (a
            # deadline-exceeded request, a losing hedge twin): the
            # replica frees the slot + pages at its next engine
            # iteration instead of decoding the full budget into the
            # stale-discard bin — exactly the capacity an overloaded
            # or mid-rollout tier is short of.  Best-effort: a dead
            # replica's conn is gone, and that's fine (so is it).
            if rep.wfile is not None:
                try:
                    send_msg(rep.wfile, rep.wlock,
                             {"op": "cancel", "id": wid,
                              "epoch": self.epoch})
                    self._m_cancel.inc()
                except (OSError, ValueError):
                    pass
        req.active.clear()
        if exc is not None:
            # a request that resolved in failure has no old-model
            # answer to compare — drop (and cancel) its shadow too
            csh = self._shadow_by_req.get(req.id)
            if csh is not None:
                self._drop_shadow_locked(csh, "primary_failed")
        self._outstanding -= 1
        if exc is not None:
            req.handle._fail(exc)
        else:
            req.handle._deliver(result)
        self._mu.notify_all()

    # -- health / failover / respawn -----------------------------------
    def _connect_locked(self, rep: _Replica) -> bool:
        ann = read_announce(self.rendezvous_dir, rep.id)
        if ann is None:
            return False
        if rep.proc is not None and rep.proc.poll() is None \
                and ann.get("pid") != rep.proc.pid:
            return False   # stale announce from the previous generation
        try:
            # the announce carries the replica's own host:port — a
            # replica on ANOTHER HOST (shared rendezvous storage,
            # --serve_host a routable address) registers identically
            # to a local one; "host" missing = a pre-fabric announce,
            # loopback by construction
            conn = socket.create_connection(
                (str(ann.get("host", "127.0.0.1")), int(ann["port"])),
                timeout=2.0)
            if conn.getsockname() == conn.getpeername():
                # TCP self-connect: dialing a DEAD replica's ephemeral
                # port can succeed via simultaneous open when the
                # kernel picks the same source port — the router would
                # be talking to itself and reading its own submits
                # back.  A real replica's accept socket can never have
                # sockname == peername.
                conn.close()
                return False
            # the connect timeout must NOT linger as the socket's i/o
            # timeout: an idle tier has no wire traffic, and a reader
            # whose blocking read times out after 2 quiet seconds reads
            # as a dead connection — a reconnect flap every idle gap
            conn.settimeout(None)
            # …but SENDS must stay bounded: dispatch writes under the
            # router lock, and a wedged-but-alive replica that stops
            # draining its socket would otherwise block sendall()
            # forever with _mu held — freezing admission, deadlines,
            # and the prober (the component built to survive wedged
            # replicas wedged by one).  SO_SNDTIMEO bounds send only;
            # the reader's blocking recv is untouched.
            conn.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                            struct.pack("ll", 5, 0))
        except OSError:
            return False
        self._close_conn(rep)
        rep.conn = conn
        rep.wfile = conn.makefile("wb")
        rep.host = str(ann.get("host", "127.0.0.1"))
        rep.port = int(ann["port"])
        rep.announced_pid = ann.get("pid")
        # reader threads are daemons that exit with their connection —
        # NOT retained (a long-lived router reconnects on every heal/
        # respawn, and a list of dead Thread objects is a slow leak)
        threading.Thread(target=self._reader, args=(rep, conn),
                         daemon=True, name=f"router-read{rep.id}").start()
        return True

    def _close_conn(self, rep: _Replica) -> None:
        conn, rep.conn, rep.wfile = rep.conn, None, None
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def _reader(self, rep: _Replica, conn: socket.socket) -> None:
        rfile = conn.makefile("rb")
        try:
            for line in rfile:
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    continue
                self._on_msg(rep, msg)
        except (OSError, ValueError):
            pass
        finally:
            with self._mu:
                if not self._stopping and rep.conn is conn:
                    self._replica_down_locked(rep, "conn_lost")

    def _replica_down_locked(self, rep: _Replica, reason: str) -> None:
        """The router's verdict that a replica is gone (heartbeat
        silence, dead socket, process exit).  Close the transport,
        re-dispatch everything it held, and say so — loudly when it
        was healthy a moment ago."""
        was_healthy = rep.healthy
        rep.healthy = False
        self._m_health[rep.id].set(0)
        self._close_conn(rep)
        stranded = list(rep.inflight.values())
        rep.inflight.clear()
        for req in stranded:
            for wid in [w for w, rid in req.active.items()
                        if rid == rep.id]:
                req.active.pop(wid, None)
            self._requeue_locked(req, reason=reason)
        # shadows running on a lost canary can never complete —
        # drop them (the gate counts completed comparisons only)
        for sh in [s for s in self._shadows.values()
                   if s.replica == rep.id]:
            self._drop_shadow_locked(sh, reason)
        # migrations with a dead endpoint can never complete either
        for xfer in [x for x, m in self._migrations.items()
                     if rep.id in (m["source"], m["target"])]:
            self._fail_migration_locked(
                xfer, self._migrations.pop(xfer), f"replica_lost:{reason}")
        # prefix owner-map HANDOFF: this replica's chained-digest
        # entries re-home to the warmest sibling instead of going
        # affinity-cold — the group re-prefills ONCE there and stays
        # warm, instead of scattering across the tier
        self._rehome_owners_locked(rep.id)
        if was_healthy:
            log.error("router: replica %d lost (%s) — %d in-flight "
                      "request(s) re-dispatched", rep.id, reason,
                      len(stranded))
            # the stranded requests' trace ids make the loss part of
            # each request's timeline, not just the replica's
            trace.anomaly("replica_lost", replica=rep.id, reason=reason,
                          redispatched=len(stranded),
                          traces=[r.trace for r in stranded])

    def _rehome_owners_locked(self, from_id: int) -> None:
        """Re-home ``from_id``'s prefix-owner entries to the WARMEST
        eligible sibling — the one already owning the most digests
        (registry-warmth proxy), ties to the least loaded.  With no
        eligible sibling the entries drop (stale owners only cost a
        least-loaded fallback, but a wrong owner would pin traffic to
        a cold replica forever)."""
        owned = [d for d, o in self._prefix_owner.items()
                 if o == from_id]
        if not owned:
            return
        cands = [r for r in self._replicas
                 if r.id != from_id and r.healthy and not r.gave_up
                 and not r.draining and not r.shadow_only]
        if not cands:
            for d in owned:
                self._prefix_owner.pop(d, None)
            return
        counts = collections.Counter(self._prefix_owner.values())
        target = max(cands, key=lambda r: (counts.get(r.id, 0),
                                           -len(r.inflight), -r.id))
        for d in owned:
            self._prefix_owner[d] = target.id
        self._m_rehomed.inc(len(owned))
        trace.event("prefix_rehome", from_replica=from_id,
                    to_replica=target.id, digests=len(owned))

    def _probe_loop(self) -> None:
        while not self._stopping:
            time.sleep(self.probe_interval_s)
            if self._stopping:
                return
            now = time.monotonic()
            with self._mu:
                traffic = self._dispatch_seq > 0
                for rep in self._replicas:
                    if rep.gave_up:
                        continue
                    self._probe_one_locked(rep, now, traffic)

    def _probe_one_locked(self, rep: _Replica, now: float,
                          traffic: bool) -> None:
        # process supervision (proc mode): exits schedule a respawn
        # under the sliding-window budget.  hold_respawn parks this
        # machinery while the rollout controller owns the process —
        # a PLANNED drain-restart must not eat the crash budget (and
        # a crash-looping NEW checkpoint must not burn it either; the
        # controller detects that failure and rolls back)
        if (rep.proc is not None and rep.proc.poll() is not None
                and rep.respawn_at is None and not rep.hold_respawn):
            code = rep.proc.returncode
            self._replica_down_locked(rep, f"exit:{code}")
            while (rep.respawn_times and now - rep.respawn_times[0]
                    > self.respawn_window_s):
                rep.respawn_times.popleft()
            if len(rep.respawn_times) >= self.max_respawns:
                rep.gave_up = True
                log.error("router: replica %d gave up (%d respawns in "
                          "window)", rep.id, len(rep.respawn_times))
                trace.anomaly("replica_give_up", replica=rep.id,
                              respawns=len(rep.respawn_times),
                              window_s=self.respawn_window_s)
                return
            rep.respawn_times.append(now)
            backoff = (self.respawn_backoff_s
                       * (2.0 ** (len(rep.respawn_times) - 1)))
            rep.respawn_at = now + backoff
            trace.event("replica_respawn", replica=rep.id, code=code,
                        backoff_s=backoff,
                        respawns=len(rep.respawn_times),
                        budget=self.max_respawns)
        if (rep.respawn_at is not None and now >= rep.respawn_at
                and not rep.hold_respawn):
            rep.respawn_at = None
            rep.generation += 1
            self._m_respawns.inc()
            rep.proc = self._spawn(rep.id, rep.generation)
            rep.last_beat_mono = now   # fresh startup grace
            log.warning("router: respawned replica %d (generation %d)",
                        rep.id, rep.generation)

        # chaos net_partition: drop this probe — the router sees
        # SILENCE, exactly what a partition or stalled host looks like
        partitioned = chaos.net_partition(rep.id, traffic)
        if not partitioned:
            try:
                mt = os.stat(heartbeat_path(self.rendezvous_dir,
                                            rep.id)).st_mtime
            except OSError:
                mt = rep.hb_mtime
            if mt != rep.hb_mtime:
                rep.hb_mtime = mt
                hb = read_heartbeat(heartbeat_path(self.rendezvous_dir,
                                                   rep.id))
                if hb is not None and hb.get("ts") != rep.last_beat_ts:
                    rep.last_beat_ts = hb.get("ts")
                    rep.last_beat_mono = now

        fresh = (now - rep.last_beat_mono) <= self.health_timeout_s
        if rep.healthy:
            if partitioned or not fresh:
                self._replica_down_locked(
                    rep, "net_partition_or_stall" if partitioned
                    else "heartbeat_timeout")
        elif fresh and not partitioned and not rep.reconnect_block:
            # beats are fresh again: (re)connect and fold it back in
            if rep.conn is None and not self._connect_locked(rep):
                return
            rep.healthy = True
            self._m_health[rep.id].set(1)
            trace.event("replica_registered", replica=rep.id,
                        port=rep.port, pid=rep.announced_pid)
            log.info("router: replica %d registered (port %s, pid %s)",
                     rep.id, rep.port, rep.announced_pid)
            self._maybe_prefetch_locked(rep)
            self._mu.notify_all()

    def _maybe_prefetch_locked(self, rep: _Replica) -> None:
        """Warm a just-healed/respawned replica: pull the HOTTEST
        tracked prompt chain from its current owner over the existing
        ``migrate_in`` wire pull, so the first affinity-miss burst the
        prober routes here decodes against warm pages instead of
        re-prefilling the system prompt.  Pure efficiency — every
        skip condition just means the next miss re-prefills, exactly
        the pre-prefetch behavior."""
        if self.prefill_replicas and rep.role != "decode":
            return   # the prefill pool re-prefills by design
        for deepest, (heat, digests, prompt) in sorted(
                self._chain_heat.items(), key=lambda kv: -kv[1][0]):
            owner = self._prefix_owner.get(deepest)
            if owner is None or owner == rep.id:
                continue
            src = self._replicas[owner]
            if (not src.healthy or src.port is None
                    or src.version != rep.version):
                continue
            if any(m["digests"] and m["digests"][-1] == deepest
                   for m in self._migrations.values()):
                continue
            xfer = f"p{rep.id}.{rep.generation}.{deepest[:8]}"
            try:
                send_msg(rep.wfile, rep.wlock,
                         {"op": "migrate_in", "xfer": xfer,
                          "host": src.host, "port": src.port,
                          "prompt": list(prompt),
                          "epoch": self.epoch})
            except (OSError, ValueError):
                return
            self._migrations[xfer] = {
                "digests": list(digests), "source": src.id,
                "target": rep.id, "t0": time.monotonic(),
                "trace": trace.new_trace_id(), "prefetch": True}
            trace.event("chain_migrate", trace=self._migrations[
                            xfer]["trace"], xfer=xfer, source=src.id,
                        target=rep.id, pages=len(digests),
                        prefetch=True, heat=heat)
            return   # one chain per heal: warmth, not a transfer storm

    # -- rollout control surface (serve/rollout.py drives these) --------
    def set_replica_version(self, replica_id: int, version: str) -> None:
        """Label the model version replica ``replica_id`` serves.
        Version-affine placement matches requests to it (all replicas
        at the same label → no constraint, the steady state)."""
        with self._mu:
            self._replicas[replica_id].version = str(version)

    def replica_version(self, replica_id: int) -> str:
        with self._mu:
            return self._replicas[replica_id].version

    def relabel_version(self, old_label: str, new_label: str) -> None:
        """Rename a model-version label fleet-wide: replicas AND the
        live requests latched to it move together (a rollout baselines
        the unlabeled incumbent fleet this way — in-flight requests
        latched to the old label must not read as mixed-model when
        their replica is relabeled under them)."""
        with self._mu:
            for rep in self._replicas:
                if rep.version == old_label:
                    rep.version = str(new_label)
            for req in self._live.values():
                if req.version == old_label:
                    req.version = str(new_label)

    def set_shadow(self, replica_id: int, shadow: bool) -> None:
        """Shadow-only: the replica takes NO client placements, only
        mirrored canary traffic — a new-checkpoint canary must never
        answer a real client until the gate passes."""
        with self._mu:
            self._replicas[replica_id].shadow_only = bool(shadow)

    def hold_replica(self, replica_id: int) -> None:
        """Take operational ownership of one replica for a planned
        replacement: placement stops (draining), the prober's
        auto-respawn parks (hold_respawn), and its prefix-owner
        entries re-home to the warmest sibling."""
        with self._mu:
            rep = self._replicas[replica_id]
            rep.draining = True
            rep.hold_respawn = True
            self._rehome_owners_locked(replica_id)
            self._mu.notify_all()

    def release_replica(self, replica_id: int,
                        shadow: bool = False) -> None:
        """Return a held replica to service (``shadow=True`` = canary
        posture: healthy and heartbeating but shadow-only)."""
        with self._mu:
            rep = self._replicas[replica_id]
            rep.draining = False
            rep.hold_respawn = False
            rep.shadow_only = bool(shadow)
            self._mu.notify_all()

    def drain_replica(self, replica_id: int,
                      timeout: float = 120.0) -> bool:
        """Drain one replica: no new placements (the caller held it),
        the replica engine sheds its own direct admissions, in-flight
        work finishes.  True when its in-flight map emptied inside
        ``timeout``."""
        rep = self._replicas[replica_id]
        with self._mu:
            if rep.wfile is not None:
                try:
                    send_msg(rep.wfile, rep.wlock,
                             {"op": "drain", "epoch": self.epoch})
                except (OSError, ValueError):
                    pass
            trace.event("replica_drain", replica=replica_id,
                        inflight=len(rep.inflight))
            deadline = time.monotonic() + timeout
            while rep.inflight and time.monotonic() < deadline:
                self._mu.wait(timeout=0.05)
            return not rep.inflight

    def terminate_replica(self, replica_id: int,
                          timeout: float = 30.0) -> None:
        """Stop a held replica's process for a planned replacement:
        mark it down QUIETLY (no replica_lost anomaly — a drained
        planned exit is not a casualty), SIGTERM, reap.  Proc-less
        tiers (tests) just close the transport."""
        rep = self._replicas[replica_id]
        with self._mu:
            rep.healthy = False
            # park the prober's reconnect too: between this terminate
            # and the successor's announce, the OLD endpoint (or its
            # stale-but-fresh heartbeat) must not be folded back in.
            # spawn_replica / allow_reconnect lifts it.
            rep.reconnect_block = True
            self._m_health[rep.id].set(0)
            self._close_conn(rep)
            # anything still in flight (drain timed out) fails over
            stranded = list(rep.inflight.values())
            rep.inflight.clear()
            for req in stranded:
                for wid in [w for w, rid in req.active.items()
                            if rid == rep.id]:
                    req.active.pop(wid, None)
                self._requeue_locked(req, reason="planned_restart")
        if rep.proc is not None and rep.proc.poll() is None:
            rep.proc.terminate()
            try:
                rep.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                rep.proc.kill()
                rep.proc.wait()

    def spawn_replica(self, replica_id: int) -> None:
        """Spawn a held replica's next generation (proc mode).  The
        spawner consults ``replica_checkpoints[replica_id]`` — set it
        first to point the new process at a different checkpoint.
        Counted as a REPLACEMENT, not a respawn: planned restarts
        must not look like crashes on any dashboard."""
        if self._spawn is None:
            raise RuntimeError(
                "router does not own replica processes (no spawner) — "
                "pass restart_hook to the rollout controller instead")
        from dtf_tpu.serve.replica import announce_path
        rep = self._replicas[replica_id]
        with self._mu:
            for path in (heartbeat_path(self.rendezvous_dir, rep.id),
                         announce_path(self.rendezvous_dir, rep.id)):
                try:
                    os.unlink(path)
                except OSError:
                    pass
            rep.generation += 1
            rep.respawn_at = None
            rep.hb_mtime = None
            rep.last_beat_ts = None
            rep.last_beat_mono = time.monotonic()   # startup grace
            rep.saturated_until = 0.0
            rep.reconnect_block = False
            gen = rep.generation
        self._m_replaced.inc()
        rep.proc = self._spawn(rep.id, gen)
        trace.event("replica_replaced", replica=rep.id, generation=gen,
                    checkpoint=self.replica_checkpoints.get(rep.id, ""))

    def allow_reconnect(self, replica_id: int) -> None:
        """Lift the terminate-window reconnect block (proc-less tiers:
        the restart_hook's successor replica has announced)."""
        with self._mu:
            rep = self._replicas[replica_id]
            rep.reconnect_block = False
            rep.last_beat_mono = time.monotonic()   # startup grace

    def replica_exit_code(self, replica_id: int) -> Optional[int]:
        """The replica process's exit code, or None while it runs (and
        in proc-less tiers) — the rollout controller's fast-fail
        signal for a new checkpoint that cannot even start."""
        proc = self._replicas[replica_id].proc
        return None if proc is None else proc.poll()

    def replica_draining(self, replica_id: int) -> bool:
        with self._mu:
            return self._replicas[replica_id].draining

    def prefix_owner_count(self, replica_id: int) -> int:
        """How many prefix digests currently route to this replica
        (the owner-map-handoff observability hook)."""
        with self._mu:
            return sum(1 for o in self._prefix_owner.values()
                       if o == replica_id)

    def rollout(self, new_checkpoint: str, **kw):
        """The router's rollout control-surface op: run a zero-downtime
        rolling rollout of the whole tier onto ``new_checkpoint`` (see
        serve/rollout.py for the state machine).  Returns the final
        RolloutState."""
        from dtf_tpu.serve.rollout import RolloutController
        return RolloutController(self, new_checkpoint, **kw).run()

    def fence(self) -> None:
        """Mark this router superseded (serve/ha.py LeaseKeeper's
        ``on_fenced``: the lease shows a higher epoch).  Latched — a
        fenced router sheds every new submit and never drives the tier
        again; the replicas' stale-epoch rejections enforce the same
        verdict at the wire for anything already in flight."""
        with self._mu:
            if self._fenced:
                return
            self._fenced = True
            log.error("router: FENCED (epoch %d) — lease lost to a "
                      "successor", self.epoch)
            trace.anomaly("router_fenced", epoch=self.epoch,
                          source="lease")

    # -- introspection -------------------------------------------------
    def health(self) -> dict:
        """The /healthz payload (obs/prom.py MetricsServer health_fn):
        ``ok`` while the router can still place work — at least one
        replica healthy and not draining/stopping."""
        with self._mu:
            healthy = [r.healthy for r in self._replicas]
            return {
                "ok": any(healthy) and not self._stopping
                      and not self._draining and not self._fenced,
                "draining": self._draining,
                "replicas_healthy": healthy,
                "outstanding": self._outstanding,
                # HA posture for external probes: which role this
                # process plays, under which fencing epoch, and
                # whether a successor has fenced it off
                "role": self.role,
                "epoch": self.epoch,
                "fenced": self._fenced,
            }

    def replica_healthy(self, replica_id: int) -> bool:
        with self._mu:
            return self._replicas[replica_id].healthy

    def replica_completed(self, replica_id: int) -> int:
        """Requests this replica finished (router-side count — survives
        replica respawns, unlike the replica's own counter)."""
        with self._mu:
            return self._replicas[replica_id].completed

    def replica_stats(self, replica_id: int,
                      timeout: float = 5.0) -> Optional[dict]:
        """Round-trip a stats snapshot from a replica's engine (the
        bench reads prefix-registry hit counters through this)."""
        rep = self._replicas[replica_id]
        tag = f"s{time.monotonic_ns()}"
        ev = threading.Event()
        with self._mu:
            if rep.wfile is None:
                return None
            self._stats_events[(rep.id, tag)] = ev
            try:
                send_msg(rep.wfile, rep.wlock,
                         {"op": "stats", "tag": tag,
                          "epoch": self.epoch})
            except (OSError, ValueError):
                self._stats_events.pop((rep.id, tag), None)
                return None
        if not ev.wait(timeout):
            with self._mu:
                self._stats_events.pop((rep.id, tag), None)
                # the reply may have raced the timeout: _on_msg popped
                # the event and stored the snapshot before this lock
                # acquisition — drop it, or every timed-out poll
                # leaves one permanent last_stats entry (tags are
                # unique per call)
                rep.last_stats.pop(tag, None)
            return None
        return rep.last_stats.pop(tag, None)

    def reset_replica_measurement(self, replica_id: int) -> bool:
        """Zero a replica engine's decode-gap/peak measurement state
        over the wire (fire-and-forget ``reset_measurement`` op).
        Benches call this after warmup so compile stalls don't
        masquerade as serving gaps in the replica's distributions."""
        rep = self._replicas[replica_id]
        with self._mu:
            if rep.wfile is None:
                return False
            try:
                send_msg(rep.wfile, rep.wlock,
                         {"op": "reset_measurement",
                          "epoch": self.epoch})
            except (OSError, ValueError):
                return False
        return True


def replica_spawner(cmd: List[str], rendezvous_dir: str,
                    log_dir: Optional[str] = None,
                    env_extra: Optional[dict] = None,
                    cwd: Optional[str] = None,
                    extra_flags: Optional[Callable] = None,
                    checkpoint_map: Optional[Dict[int, str]] = None
                    ) -> Callable:
    """Standard spawn callable for :class:`Router`: runs ``cmd`` with
    the replica-tier environment contract — DTF_PROCESS_ID = replica
    id (announce/heartbeat/trace rank identity), DTF_HEARTBEAT_DIR =
    the rendezvous dir, DTF_RESTART_GENERATION = respawn generation
    (the PR-4/PR-5 restart-tagging contract) — logging each replica to
    ``replica{K}.log`` (``.retry{G}`` suffixed on respawn, keeping the
    first failure's log like the launcher does).  ``extra_flags``
    (``replica_id -> [flag, ...]``) appends PER-REPLICA flags — the
    metrics-port fan-out (router_main gives replica K port base+1+K so
    one ``--metrics_port`` makes the whole tier scrapable).
    ``checkpoint_map`` (shared BY REFERENCE with
    ``Router.replica_checkpoints``) is consulted at SPAWN time: a
    non-empty entry exports DTF_SERVE_CHECKPOINT, which replica_main
    serves instead of its flag-configured checkpoint — the mechanism a
    rollout uses to restart one replica at a time onto a new
    checkpoint without touching the other replicas' command line."""
    rendezvous_dir = os.path.abspath(rendezvous_dir)
    log_dir = os.path.abspath(log_dir or rendezvous_dir)
    # the replica must import dtf_tpu no matter where the ROUTER was
    # launched from — or what ``cwd`` the caller picked: the repo root
    # goes on PYTHONPATH unconditionally (a spawn that only imports
    # from one directory is a crash-loop that eats the whole respawn
    # budget before anyone reads replica0.log)
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    cwd = os.path.abspath(cwd) if cwd else repo_root

    def spawn(replica_id: int, generation: int) -> subprocess.Popen:
        env = dict(os.environ)
        env["DTF_PROCESS_ID"] = str(replica_id)
        env["DTF_HEARTBEAT_DIR"] = rendezvous_dir
        env["DTF_RESTART_GENERATION"] = str(generation)
        env["PYTHONPATH"] = (repo_root + os.pathsep
                             + env.get("PYTHONPATH", ""))
        env.update(env_extra or {})
        ckpt = (checkpoint_map or {}).get(replica_id, "")
        if ckpt:
            env["DTF_SERVE_CHECKPOINT"] = ckpt
        os.makedirs(log_dir, exist_ok=True)
        suffix = f".retry{generation}" if generation else ""
        logf = open(os.path.join(
            log_dir, f"replica{replica_id}{suffix}.log"), "wb")
        full = cmd + ["--replica_id", str(replica_id)]
        if extra_flags is not None:
            full += [str(f) for f in (extra_flags(replica_id) or [])]
        try:
            return subprocess.Popen(full, env=env, cwd=cwd, stdout=logf,
                                    stderr=subprocess.STDOUT)
        finally:
            logf.close()

    return spawn
