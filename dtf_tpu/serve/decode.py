"""KV-cache incremental decoding for the transformer LM.

The training forward is teacher-forced: logits for every position in
one pass.  Serving needs the autoregressive form — one new token per
step — without recomputing the whole prefix.  The model side lives in
``models/transformer.py`` (``decode=True``: every attention keeps a KV
cache in the 'cache' collection and takes a per-row ``cache_index``);
this module owns the jit-compiled step functions around it:

  - ``init_cache``      — zeros cache pytree with fixed [B, L] shapes
  - ``prefill``         — write one padded prompt into one cache slot and
                          sample the first generated token
  - ``prefill_chunk``   — (paged mode) write one chunk of a prompt into
                          the slot's pages; the final chunk also samples
  - ``decode_step``     — one token for every slot in the batch
  - ``teacher_forced_logits`` — the training-style forward, the oracle
                          the decode path is verified token-exact against

Two cache layouts, selected by ``Decoder(kv_page_size=...)``:

  contiguous (legacy) — [num_slots, max_seq_len, H, Dh] per layer;
      prefill pads the prompt to max_seq_len and runs ONE dense
      [L, L]-masked pass.  Simple, but every admit pays O(L²) attention
      and every slot reserves worst-case HBM.
  paged — a shared [pool_pages, page_size, H, Dh] pool per layer plus
      per-slot block tables (ops.paged_attention).  Prefill runs in
      page-aligned chunks: the FIRST chunk goes through the flash
      kernel (pure causal self-attention, no gather), later chunks
      gather the paged prefix.  Work scales with the PROMPT length, not
      the cache capacity, and the engine can interleave decode steps
      between chunks.  Compiles once per chunk length (the engine uses
      one fixed chunk size, so in practice: first-chunk body, continue
      body, and the short-prompt whole-pad shapes).

Everything is shaped for slot-based continuous batching: ``cache_index``
is [B], and the decode step compiles ONCE (fixed shapes; scalars like
the slot id and prompt length are traced arrays, never Python ints).

Sampling: greedy when temperature == 0, else softmax sampling at
``logits / temperature`` — per-row, so one batch can mix both.

Sampling RNG comes in two forms, and the distinction is a durability
contract, not a convenience: the legacy ``key`` argument (a single
PRNG key, split per row) makes a sampled token depend on engine-global
step order — unreproducible after a failover — while the ``seed`` /
``seeds`` form derives each sampled position's key as
``fold_in(key(request_seed), position)``: a pure function of (request
seed, position).  Two replicas holding identical params re-decoding
the same request with the same wire-carried seed produce IDENTICAL
sampled tokens, which is what lets the serving router re-dispatch a
SAMPLED request token-exactly — the same failover contract greedy
decode gets for free (serve/router.py).
"""

from __future__ import annotations

import functools
import logging
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

log = logging.getLogger("dtf_tpu")


def make_decode_model(model, kv_page_size=None, kv_pool_pages=None,
                      model_axis=None):
    """Clone a (training-configured) TransformerLM into decode mode.

    The seq axis is stripped (ring attention does not compose with the
    KV cache); ``model_axis`` selects serving tensor parallelism —
    None (the default) strips it for single-device decode, a mesh axis
    name keeps Megatron head/ff sharding live (the Decoder then runs
    the model inside shard_map with the KV pool's head dim sharded).
    Remat is stripped too — there is no backward pass to save memory
    for, and jax.checkpoint does not compose with the mutable cache.
    ``kv_page_size``/``kv_pool_pages`` select the paged cache layout."""
    kw = {"decode": True, "model_axis": model_axis}
    if getattr(model, "seq_axis", None) is not None:
        kw["seq_axis"] = None
    if getattr(model, "shard_vocab", False):
        kw["shard_vocab"] = False
    if getattr(model, "remat", False):
        kw["remat"] = False
    if getattr(model, "remat_policy", None) is not None:
        kw["remat_policy"] = None
    if kv_page_size is not None:
        kw["kv_page_size"] = int(kv_page_size)
        kw["kv_pool_pages"] = int(kv_pool_pages)
    return model.clone(**kw)


def init_cache(model, num_slots: int, max_seq_len: int):
    """Zeros KV cache for ``num_slots`` sequences of ≤ ``max_seq_len``
    tokens.  Shapes come from an eval_shape of the decode model's init
    (no params are materialized); values are zeros by construction."""
    decode_model = make_decode_model(model)
    tokens = jax.ShapeDtypeStruct((num_slots, max_seq_len), jnp.int32)
    idx = jax.ShapeDtypeStruct((num_slots,), jnp.int32)
    shapes = jax.eval_shape(
        functools.partial(decode_model.init, jax.random.key(0)),
        tokens, cache_index=idx)["cache"]
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes)


def paged_cache_shapes(model, kv_page_size: int, kv_pool_pages: int):
    """ShapeDtypeStruct pytree of the paged cache: a
    [kv_pool_pages, kv_page_size, H, Dh] pool per layer per K/V, from
    an eval_shape of the paged decode model's init (no params — and no
    cache — materialized)."""
    decode_model = make_decode_model(model, kv_page_size=kv_page_size,
                                     kv_pool_pages=kv_pool_pages)
    tokens = jax.ShapeDtypeStruct((1, kv_page_size), jnp.int32)
    idx = jax.ShapeDtypeStruct((1,), jnp.int32)
    table = jax.ShapeDtypeStruct((1, 1), jnp.int32)
    return jax.eval_shape(
        functools.partial(decode_model.init, jax.random.key(0)),
        tokens, cache_index=idx, block_table=table)["cache"]


def init_paged_cache(model, kv_page_size: int, kv_pool_pages: int):
    """Zeros paged-cache pytree (single-device layout)."""
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype),
        paged_cache_shapes(model, kv_page_size, kv_pool_pages))


def _sample(logits, temperature, key):
    """logits [..., V] → token ids [...]; greedy at temperature 0."""
    greedy = jnp.argmax(logits, axis=-1)
    safe_t = jnp.maximum(temperature, 1e-6)
    sampled = jax.random.categorical(
        key, logits / safe_t[..., None], axis=-1)
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)


def position_key(seed, position):
    """The per-request sampling key for one sequence position:
    ``fold_in(key(seed), position)``.  A pure function of (request
    seed, position) — the property that makes a sampled request's
    re-dispatch token-exact on a replica with identical params."""
    return jax.random.fold_in(
        jax.random.key(jnp.asarray(seed, jnp.uint32)),
        jnp.asarray(position, jnp.int32))


# [B] seeds + [B] positions -> [B] typed keys (jitted once; the engine
# calls this every decode step, so it must not re-trace)
_seed_row_keys = jax.jit(jax.vmap(position_key))


class Decoder:
    """Jitted prefill/decode pair bound to one model + param set.

    ``params`` may include 'batch_stats' siblings conceptually, but the
    LM family is LN-only — only 'params' is applied.

    ``kv_page_size`` selects the paged cache (None = contiguous):
    ``kv_pool_pages`` TOTAL pool pages including the scratch page 0
    (None = full reservation, 1 + num_slots × pages-per-slot — the
    engine shrinks it to provision for tokens in flight).

    ``mesh`` selects TENSOR-PARALLEL decode: a runtime mesh whose
    'model' axis has size > 1.  Params shard per
    ``param_partition_specs`` (heads/ff column-parallel, out/fc2
    row-parallel) and each layer's KV page pool shards its HEAD dim —
    every apply runs inside shard_map, tokens/block tables replicated,
    logits replicated out (the last block exits through tp_psum).
    Paged cache only: the page pool is the layout built for
    production serving, and sharding the contiguous per-slot slabs
    would buy nothing the pool doesn't."""

    def __init__(self, model, params, *, num_slots: int, max_seq_len: int,
                 kv_page_size: Optional[int] = None,
                 kv_pool_pages: Optional[int] = None, mesh=None,
                 ledger=None):
        from dtf_tpu.runtime.mesh import MODEL_AXIS

        self.mesh = mesh
        # MFU/cost ledger (obs/ledger.py): each compiled body (decode
        # step, prefill chunk per shape) registers its XLA flop/byte
        # counts at compile time — pulled from the AOT executable the
        # decoder then RUNS, so nothing compiles twice
        self.ledger = ledger
        self._execs = {}
        self.tp = int(mesh.shape[MODEL_AXIS]) if mesh is not None else 1
        self._model_axis = MODEL_AXIS if self.tp > 1 else None
        self.num_slots = int(num_slots)
        self.max_seq_len = int(max_seq_len)
        if getattr(model, "max_seq_len", max_seq_len) < max_seq_len:
            raise ValueError(
                f"max_seq_len {max_seq_len} exceeds the model's position "
                f"table ({model.max_seq_len})")
        self.paged = kv_page_size is not None
        if self.tp > 1 and not self.paged:
            raise ValueError(
                "tensor-parallel decode needs the paged KV cache "
                "(kv_page_size > 0) — the page pool is the layout that "
                "shards")
        if self.tp > 1 and model.num_heads % self.tp:
            raise ValueError(
                f"num_heads {model.num_heads} not divisible by the "
                f"mesh's model axis ({self.tp})")
        if self.paged:
            self.page_size = int(kv_page_size)
            if self.page_size < 1:
                raise ValueError(f"kv_page_size must be >= 1, got "
                                 f"{kv_page_size}")
            self.pages_per_slot = -(-self.max_seq_len // self.page_size)
            self.pool_pages = int(
                kv_pool_pages or 1 + self.num_slots * self.pages_per_slot)
            if self.pool_pages < 2:
                raise ValueError(
                    f"kv_pool_pages must be >= 2 (page 0 is the scratch "
                    f"page), got {self.pool_pages}")
            self.model = make_decode_model(
                model, kv_page_size=self.page_size,
                kv_pool_pages=self.pool_pages,
                model_axis=self._model_axis)
            if self.tp > 1:
                params = self._shard_params(params)
            # window_pages / flash_prefill are STATIC (they select the
            # attention formulation and the gather extent); start is
            # TRACED.  Gather path: window_pages = the chunk's visible
            # pages → one compile per (chunk shape, window), buying the
            # O(prompt²/2) static trim.  Kernel path: the kernel trims
            # dynamically (pl.when dead-page skip), so prefill_chunk
            # passes window_pages=None and the body compiles ONCE per
            # chunk shape — the per-chunk-index compile storm is gone,
            # not just the gather
            self._chunk = jax.jit(self._chunk_impl, donate_argnums=(1,),
                                  static_argnums=(8, 9))
            up = getattr(self.model, "use_pallas", None)
            self._kernel_attn = bool(
                up if up is not None
                else jax.default_backend() == "tpu")
            self._decode = jax.jit(self._decode_paged_impl,
                                   donate_argnums=(1,))
            # COW page copy (engine prefix sharing): one whole
            # [page_size, H, Dh] row per layer per K/V — page dim is
            # unsharded, so the copy is shard-local under TP too
            self._copy_page = jax.jit(
                lambda cache, src, dst: jax.tree_util.tree_map(
                    lambda c: c.at[dst].set(c[src]), cache),
                donate_argnums=(0,))
            # migration import: write a host page payload (one
            # [page_size, H, Dh] row per leaf) into pool page ``dst``
            self._write_page = jax.jit(
                lambda cache, dst, payload: jax.tree_util.tree_map(
                    lambda c, p: c.at[dst].set(p.astype(c.dtype)),
                    cache, payload),
                donate_argnums=(0,))
        else:
            self.model = make_decode_model(model)
            self._prefill = jax.jit(self._prefill_impl, donate_argnums=(1,))
            self._decode = jax.jit(self._decode_impl, donate_argnums=(1,))
        self.params = params

    # -- tensor-parallel plumbing --------------------------------------
    def _shard_params(self, params):
        """Place a full param tree into the Megatron layout on the
        mesh — one host→shard transfer per leaf, no replicated
        intermediate.  The layout definition is the bridge's
        (tp_param_shardings — one source for placement AND the
        shard_map in_specs kept here)."""
        from dtf_tpu.serve.bridge import tp_param_shardings

        self._pspecs, shardings = tp_param_shardings(params, self.mesh)
        return jax.device_put(params, shardings)

    def _cache_pspec(self):
        # KV pool sharding: [pool_pages, page_size, H, Dh] splits H
        from jax.sharding import PartitionSpec as P
        return P(None, None, self._model_axis, None)

    def _apply_model(self, params, cache, tokens, index, block_table,
                     flash_prefill, window_pages):
        """model.apply with mutable cache — direct on one device,
        shard_mapped over the mesh under TP (tokens/index/tables
        replicated in, logits replicated out, cache specs on the pool
        head dim; flash_prefill/window_pages are trace-time statics
        closed over)."""
        if self.tp == 1:
            return self.model.apply(
                {"params": params, "cache": cache}, tokens,
                cache_index=index, block_table=block_table,
                flash_prefill=flash_prefill, window_pages=window_pages,
                mutable=["cache"])
        from jax.sharding import PartitionSpec as P

        cspec = jax.tree_util.tree_map(lambda _: self._cache_pspec(),
                                       cache)

        def body(p, c, t, i, bt):
            return self.model.apply(
                {"params": p, "cache": c}, t, cache_index=i,
                block_table=bt, flash_prefill=flash_prefill,
                window_pages=window_pages, mutable=["cache"])

        return jax.shard_map(
            body, mesh=self.mesh,
            in_specs=(self._pspecs, cspec, P(), P(), P()),
            out_specs=(P(), {"cache": cspec}),
            check_vma=False)(params, cache, tokens, index, block_table)

    def fresh_cache(self):
        if self.paged:
            if self.tp > 1:
                # global-shaped zeros (full head count) created
                # DIRECTLY sharded on the pool head dim via jit
                # out_shardings — each device materializes only its
                # own shard.  A replicated zeros-then-device_put would
                # allocate the FULL pool on one chip first, the exact
                # never-fits-on-one-chip trap the sharded params
                # restore avoids.  Shapes come from a single-device
                # clone because the TP model's init cannot trace
                # outside shard_map (unbound axis)
                from jax.sharding import NamedSharding

                base = self.model.clone(model_axis=None)
                shapes = paged_cache_shapes(base, self.page_size,
                                            self.pool_pages)
                sharding = NamedSharding(self.mesh, self._cache_pspec())
                return jax.jit(
                    lambda: jax.tree_util.tree_map(
                        lambda s: jnp.zeros(s.shape, s.dtype), shapes),
                    out_shardings=jax.tree_util.tree_map(
                        lambda _: sharding, shapes))()
            return init_paged_cache(self.model, self.page_size,
                                    self.pool_pages)
        return init_cache(self.model, self.num_slots, self.max_seq_len)

    def copy_page(self, cache, src: int, dst: int):
        """Physically copy pool page ``src`` onto ``dst`` in every
        layer's K and V pool — the engine's copy-on-write primitive
        (prefix sharing: a shared page about to be written is copied
        onto a fresh page first)."""
        return self._copy_page(cache, jnp.asarray(src, jnp.int32),
                               jnp.asarray(dst, jnp.int32))

    def read_page(self, cache, page: int):
        """Host copy of pool page ``page`` from every layer's K and V
        pool — the migration EXPORT primitive (serve/migrate.py).
        Returns a flat LIST of [page_size, H, Dh] numpy leaves in
        ``tree_leaves`` order (deterministic for a given model, so the
        sender's list zips onto the receiver's cache leaves).  Pure
        device_get, no casts or layout changes: the bytes are exactly
        what the device holds, which is what the bit-identity contract
        on migrated pages is built on."""
        if not self.paged:
            raise RuntimeError("page migration needs the paged cache")
        return [np.asarray(jax.device_get(c[int(page)]))
                for c in jax.tree_util.tree_leaves(cache)]

    def write_page(self, cache, page: int, leaves):
        """Write a host page payload (:meth:`read_page`'s leaf list)
        into pool page ``page`` of every layer — the migration IMPORT
        primitive.  The pool's page dim is unsharded under TP (the
        head dim shards), so a whole-page write lowers to shard-local
        updates, same as :meth:`copy_page`."""
        if not self.paged:
            raise RuntimeError("page migration needs the paged cache")
        treedef = jax.tree_util.tree_structure(cache)
        payload = jax.tree_util.tree_unflatten(
            treedef, [jnp.asarray(a) for a in leaves])
        return self._write_page(cache, jnp.asarray(int(page), jnp.int32),
                                payload)

    @property
    def compiled_count(self) -> int:
        """How many decode/chunk executables exist so far — the engine
        compares it across a call to tell 'this call compiled' (whose
        wall time is compile, not compute: the MFU ledger must not
        average it in)."""
        return len(self._execs)

    def _aot(self, name: str, jitfn, args: tuple):
        """AOT-compile ``jitfn`` at these example args (statics
        included, in position) and register the executable's XLA cost
        with the ledger.  Returns the compiled callable — which takes
        only the DYNAMIC args — or None when AOT lowering is
        unavailable on this backend (the caller keeps the plain jit
        path; the ledger entry is simply absent)."""
        try:
            compiled = jitfn.lower(*args).compile()
        except Exception as e:  # noqa: BLE001 — observability must
            # never take down the decode path it measures
            log.debug("decoder: AOT compile failed for %s (%s) — "
                      "falling back to the jit path", name, e)
            return None
        if self.ledger is not None:
            self.ledger.register(name, compiled=compiled)
        return compiled

    # -- jitted bodies -------------------------------------------------
    def _prefill_impl(self, params, cache, tokens, slot, length,
                      temperature, key):
        """tokens [1, max_seq_len] (prompt padded with zeros), slot/
        length scalar arrays.  Writes the slot's cache row, returns
        (first generated token scalar, new cache, last-position logits).
        """
        row = jax.tree_util.tree_map(
            lambda c: jax.lax.dynamic_slice_in_dim(c, slot, 1, axis=0),
            cache)
        logits, mut = self.model.apply(
            {"params": params, "cache": row}, tokens,
            cache_index=jnp.zeros((1,), jnp.int32), mutable=["cache"])
        cache = jax.tree_util.tree_map(
            lambda c, r: jax.lax.dynamic_update_slice_in_dim(
                c, r, slot, axis=0),
            cache, mut["cache"])
        # next token comes from the last REAL prompt position
        last = jax.lax.dynamic_slice_in_dim(
            logits[0], length - 1, 1, axis=0)[0]          # [V]
        tok = _sample(last, temperature, key)
        return tok, cache, last

    def _decode_impl(self, params, cache, tokens, index, temperature,
                     rowkeys):
        """tokens [B, 1] (the previous step's output per slot), index [B]
        current lengths, temperature [B], rowkeys [B] per-row sampling
        keys.  One step for every slot — inactive slots decode garbage
        that the engine ignores."""
        logits, mut = self.model.apply(
            {"params": params, "cache": cache}, tokens,
            cache_index=index, mutable=["cache"])
        last = logits[:, -1]                               # [B, V]
        toks = jax.vmap(_sample)(last, temperature, rowkeys)
        return toks, mut["cache"], last

    # -- paged jitted bodies -------------------------------------------
    def _chunk_impl(self, params, cache, tokens, block_row, sample_pos,
                    temperature, key, start, window_pages, flash_prefill):
        """One prefill chunk.  tokens [1, C] (page-aligned, tail-padded
        with zeros), block_row [1, M] the slot's page ids, sample_pos
        scalar (offset WITHIN the chunk of the last real prompt token —
        only read on the final chunk; earlier chunks' sampled token is
        discarded by the engine).  ``start`` (the chunk's first logical
        position) is a traced scalar; ``window_pages`` (pages covering
        [0, start + C), gather path — None under the kernel) and
        ``flash_prefill`` (start == 0: causal-only via the flash
        kernel) are static.  Returns (token, cache, sampled-position
        logits)."""
        logits, mut = self._apply_model(
            params, cache, tokens,
            jnp.broadcast_to(jnp.asarray(start, jnp.int32), (1,)),
            block_row, flash_prefill, window_pages)
        last = jax.lax.dynamic_slice_in_dim(
            logits[0], sample_pos, 1, axis=0)[0]           # [V]
        tok = _sample(last, temperature, key)
        return tok, mut["cache"], last

    def _decode_paged_impl(self, params, cache, tokens, index,
                           block_tables, temperature, rowkeys):
        """tokens [B, 1], index [B], block_tables [B, M] — rows not in
        decode phase carry an ALL-ZEROS block row, steering their
        garbage write/gather at the scratch page (ops.paged_attention).
        ``rowkeys`` [B] are the per-row sampling keys."""
        logits, mut = self._apply_model(
            params, cache, tokens, index, block_tables, False, None)
        last = logits[:, -1]                               # [B, V]
        toks = jax.vmap(_sample)(last, temperature, rowkeys)
        return toks, mut["cache"], last

    # -- public API ----------------------------------------------------
    def prefill(self, cache, prompt, slot: int, temperature: float,
                key=None, seed=None) -> Tuple[Any, Any, Any]:
        """prompt: 1-D int32 (unpadded).  Returns (token, cache, logits)
        with the first sampled token as a device scalar.  Contiguous
        mode only — paged prefill goes through :meth:`prefill_chunk`.

        Pass exactly one of ``key`` (a PRNG key — legacy, step-order-
        dependent sampling) or ``seed`` (a per-request int: the sampled
        token becomes a pure function of (seed, position) — the
        failover-exactness form)."""
        if self.paged:
            raise RuntimeError("paged Decoder: use prefill_chunk")
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1 or prompt.shape[0] == 0:
            raise ValueError("prompt must be a non-empty 1-D token array")
        length = int(prompt.shape[0])
        if (key is None) == (seed is None):
            raise ValueError("pass exactly one of key= or seed=")
        if seed is not None:
            key = position_key(int(seed), length - 1)
        if length > self.max_seq_len:
            raise ValueError(
                f"prompt length {length} exceeds max_seq_len "
                f"{self.max_seq_len}")
        padded = np.zeros((1, self.max_seq_len), np.int32)
        padded[0, :length] = prompt
        return self._prefill(self.params, cache, jnp.asarray(padded),
                             jnp.asarray(slot, jnp.int32),
                             jnp.asarray(length, jnp.int32),
                             jnp.asarray(temperature, jnp.float32), key)

    def prefill_chunk(self, cache, chunk, block_row, start: int,
                      sample_pos: int, temperature: float, key=None,
                      seed=None):
        """One page-aligned prefill chunk for one slot (paged mode).

        chunk: 1-D int32, len(chunk) % page_size == 0 (engine-padded);
        block_row: [M] int32 page ids for the slot; start: the chunk's
        first logical position; sample_pos: offset within the chunk of
        the last REAL prompt token (engine passes 0 for non-final
        chunks and ignores the sampled token).  Returns (token, cache,
        logits) — the first-chunk (start == 0) body routes attention
        through the flash kernel; continuation chunks gather the paged
        prefix.  Exactly one of ``key``/``seed`` (see :meth:`prefill`);
        the seed form keys the sample to the chunk's GLOBAL sampled
        position, so every chunking of a prompt samples identically."""
        chunk = np.asarray(chunk, np.int32).reshape(1, -1)
        if (key is None) == (seed is None):
            raise ValueError("pass exactly one of key= or seed=")
        if seed is not None:
            key = position_key(int(seed), int(start) + int(sample_pos))
        if chunk.shape[1] % self.page_size or start % self.page_size:
            raise ValueError(
                f"prefill chunk (len {chunk.shape[1]}, start {start}) "
                f"must be page-aligned (kv_page_size {self.page_size}) — "
                f"whole-page writes depend on it")
        block_row = np.asarray(block_row, np.int32).reshape(1, -1)
        # gather path: static window trim (one compile per window, the
        # O(prompt²/2) contract); kernel path: None — the kernel skips
        # dead pages dynamically, so every chunk index shares ONE
        # compile per chunk shape
        window = (None if self._kernel_attn
                  else (int(start) + chunk.shape[1]) // self.page_size)
        dyn = (self.params, cache, jnp.asarray(chunk),
               jnp.asarray(block_row),
               jnp.asarray(sample_pos, jnp.int32),
               jnp.asarray(temperature, jnp.float32), key,
               jnp.asarray(int(start), jnp.int32))
        ekey = ("chunk", chunk.shape[1], window, start == 0)
        fn = self._execs.get(ekey)
        if fn is None:
            # ledger name is per chunk SHAPE: gather-path window
            # variants share it (latest compile's counts stand for the
            # family — obs/ledger.py documents the approximation)
            fn = self._aot(f"serve_prefill_chunk_c{chunk.shape[1]}",
                           self._chunk, dyn + (window, start == 0))
            if fn is None:
                fn = (lambda *a, _w=window, _f=(start == 0):
                      self._chunk(*a, _w, _f))
            self._execs[ekey] = fn
        return fn(*dyn)

    def decode_step(self, cache, tokens, index, temperature, key=None,
                    block_tables=None, seeds=None):
        """tokens [B], index [B], temperature [B] → (tokens [B], cache,
        logits [B, V]).  Paged mode additionally takes ``block_tables``
        [B, M] (all-zeros rows for slots not decoding).

        Exactly one of ``key`` (single PRNG key, split per row —
        legacy) or ``seeds`` ([B] per-request ints: row b samples with
        ``fold_in(key(seeds[b]), index[b])``, a pure function of the
        request's seed and position — the failover-exactness form).
        Both feed the SAME compiled body (a [B] key array), so the
        choice never costs a recompile."""
        tokens = jnp.asarray(tokens, jnp.int32).reshape(-1, 1)
        index = jnp.asarray(index, jnp.int32)
        temperature = jnp.asarray(temperature, jnp.float32)
        if (key is None) == (seeds is None):
            raise ValueError("pass exactly one of key= or seeds=")
        if seeds is not None:
            rowkeys = _seed_row_keys(
                jnp.asarray(seeds, jnp.uint32), index)
        else:
            rowkeys = jax.random.split(key, tokens.shape[0])
        if self.paged:
            if block_tables is None:
                raise ValueError("paged decode_step needs block_tables")
            dyn = (self.params, cache, tokens, index,
                   jnp.asarray(block_tables, jnp.int32), temperature,
                   rowkeys)
            fn = self._execs.get("decode")
            if fn is None:
                fn = (self._aot("serve_decode_step", self._decode, dyn)
                      or self._decode)
                self._execs["decode"] = fn
            return fn(*dyn)
        return self._decode(self.params, cache, tokens, index,
                            temperature, rowkeys)


def teacher_forced_logits(model, params, tokens):
    """The training-style full forward — the decode path's oracle."""
    return model.apply({"params": params}, jnp.asarray(tokens, jnp.int32))
