"""KV-cache incremental decoding for the transformer LM.

The training forward is teacher-forced: logits for every position in
one pass.  Serving needs the autoregressive form — one new token per
step — without recomputing the whole prefix.  The model side lives in
``models/transformer.py`` (``decode=True``: every attention keeps
``cached_key``/``cached_value`` in the 'cache' collection and takes a
per-row ``cache_index``); this module owns the jit-compiled step
functions around it:

  - ``init_cache``      — zeros cache pytree with fixed [B, L] shapes
  - ``prefill``         — write one padded prompt into one cache slot and
                          sample the first generated token
  - ``decode_step``     — one token for every slot in the batch
  - ``teacher_forced_logits`` — the training-style forward, the oracle
                          the decode path is verified token-exact against

Everything is shaped for slot-based continuous batching: the cache is
[num_slots, max_seq_len, H, Dh] per layer, ``cache_index`` is [B], and
both step functions compile ONCE (fixed shapes; scalars like the slot id
and prompt length are traced arrays, never Python ints).

Sampling: greedy when temperature == 0, else softmax sampling at
``logits / temperature`` — per-row, so one batch can mix both.
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def make_decode_model(model):
    """Clone a (training-configured) TransformerLM into decode mode.

    Sharding attributes are stripped: decode is single-device (the
    bridge re-gathers sharded checkpoints into full params first)."""
    kw = {"decode": True}
    for attr in ("seq_axis", "model_axis"):
        if getattr(model, attr, None) is not None:
            kw[attr] = None
    if getattr(model, "shard_vocab", False):
        kw["shard_vocab"] = False
    return model.clone(**kw)


def init_cache(model, num_slots: int, max_seq_len: int):
    """Zeros KV cache for ``num_slots`` sequences of ≤ ``max_seq_len``
    tokens.  Shapes come from an eval_shape of the decode model's init
    (no params are materialized); values are zeros by construction."""
    decode_model = make_decode_model(model)
    tokens = jax.ShapeDtypeStruct((num_slots, max_seq_len), jnp.int32)
    idx = jax.ShapeDtypeStruct((num_slots,), jnp.int32)
    shapes = jax.eval_shape(
        functools.partial(decode_model.init, jax.random.key(0)),
        tokens, cache_index=idx)["cache"]
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes)


def _sample(logits, temperature, key):
    """logits [..., V] → token ids [...]; greedy at temperature 0."""
    greedy = jnp.argmax(logits, axis=-1)
    safe_t = jnp.maximum(temperature, 1e-6)
    sampled = jax.random.categorical(
        key, logits / safe_t[..., None], axis=-1)
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)


class Decoder:
    """Jitted prefill/decode pair bound to one model + param set.

    ``params`` may include 'batch_stats' siblings conceptually, but the
    LM family is LN-only — only 'params' is applied."""

    def __init__(self, model, params, *, num_slots: int, max_seq_len: int):
        self.model = make_decode_model(model)
        self.params = params
        self.num_slots = int(num_slots)
        self.max_seq_len = int(max_seq_len)
        if getattr(model, "max_seq_len", max_seq_len) < max_seq_len:
            raise ValueError(
                f"max_seq_len {max_seq_len} exceeds the model's position "
                f"table ({model.max_seq_len})")
        self._prefill = jax.jit(self._prefill_impl, donate_argnums=(1,))
        self._decode = jax.jit(self._decode_impl, donate_argnums=(1,))

    def fresh_cache(self):
        return init_cache(self.model, self.num_slots, self.max_seq_len)

    # -- jitted bodies -------------------------------------------------
    def _prefill_impl(self, params, cache, tokens, slot, length,
                      temperature, key):
        """tokens [1, max_seq_len] (prompt padded with zeros), slot/
        length scalar arrays.  Writes the slot's cache row, returns
        (first generated token scalar, new cache, last-position logits).
        """
        row = jax.tree_util.tree_map(
            lambda c: jax.lax.dynamic_slice_in_dim(c, slot, 1, axis=0),
            cache)
        logits, mut = self.model.apply(
            {"params": params, "cache": row}, tokens,
            cache_index=jnp.zeros((1,), jnp.int32), mutable=["cache"])
        cache = jax.tree_util.tree_map(
            lambda c, r: jax.lax.dynamic_update_slice_in_dim(
                c, r, slot, axis=0),
            cache, mut["cache"])
        # next token comes from the last REAL prompt position
        last = jax.lax.dynamic_slice_in_dim(
            logits[0], length - 1, 1, axis=0)[0]          # [V]
        tok = _sample(last, temperature, key)
        return tok, cache, last

    def _decode_impl(self, params, cache, tokens, index, temperature, key):
        """tokens [B, 1] (the previous step's output per slot), index [B]
        current lengths, temperature [B].  One step for every slot —
        inactive slots decode garbage that the engine ignores."""
        logits, mut = self.model.apply(
            {"params": params, "cache": cache}, tokens,
            cache_index=index, mutable=["cache"])
        last = logits[:, -1]                               # [B, V]
        keys = jax.random.split(key, last.shape[0])
        toks = jax.vmap(_sample)(last, temperature, keys)
        return toks, mut["cache"], last

    # -- public API ----------------------------------------------------
    def prefill(self, cache, prompt, slot: int, temperature: float,
                key) -> Tuple[Any, Any, Any]:
        """prompt: 1-D int32 (unpadded).  Returns (token, cache, logits)
        with the first sampled token as a device scalar."""
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1 or prompt.shape[0] == 0:
            raise ValueError("prompt must be a non-empty 1-D token array")
        length = int(prompt.shape[0])
        if length > self.max_seq_len:
            raise ValueError(
                f"prompt length {length} exceeds max_seq_len "
                f"{self.max_seq_len}")
        padded = np.zeros((1, self.max_seq_len), np.int32)
        padded[0, :length] = prompt
        return self._prefill(self.params, cache, jnp.asarray(padded),
                             jnp.asarray(slot, jnp.int32),
                             jnp.asarray(length, jnp.int32),
                             jnp.asarray(temperature, jnp.float32), key)

    def decode_step(self, cache, tokens, index, temperature, key):
        """tokens [B], index [B], temperature [B] → (tokens [B], cache,
        logits [B, V])."""
        return self._decode(self.params, cache,
                            jnp.asarray(tokens, jnp.int32).reshape(-1, 1),
                            jnp.asarray(index, jnp.int32),
                            jnp.asarray(temperature, jnp.float32), key)


def teacher_forced_logits(model, params, tokens):
    """The training-style full forward — the decode path's oracle."""
    return model.apply({"params": params}, jnp.asarray(tokens, jnp.int32))
