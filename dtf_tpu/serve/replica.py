"""Replica-side serve server: one ServeEngine behind a TCP socket.

The serving replica tier (serve/router.py) is N of these processes
behind a router.  Each replica owns a full :class:`ServeEngine`
(optionally TP-sharded — the engine doesn't know it's a replica) and
speaks a newline-delimited-JSON wire protocol over TCP:

  router → replica
    {"op":"submit","id":W,"prompt":[...],"max_new_tokens":N,
     "temperature":T,"eos_id":E,"rng_seed":S,
     "trace":TID,"pspan":SID}           dispatch one request; "trace"
                                        is the router-minted
                                        distributed-trace id and
                                        "pspan" the router-side
                                        request span id — the engine
                                        tags every per-request record
                                        with them, so one request's
                                        life is reconstructable across
                                        processes (trace_main
                                        --request TID); "rng_seed"
                                        pins the request's SAMPLING
                                        identity (a re-dispatch ships
                                        the same seed, so sampled
                                        requests replay token-exactly
                                        — greedy's failover contract,
                                        extended)
    {"op":"cancel","id":W}              stop working on request W: the
                                        engine frees its slot + pages
                                        at the next iteration instead
                                        of decoding an answer the
                                        router already stopped wanting
                                        (deadline, failover, losing
                                        hedge)
    {"op":"drain"}                      stop admissions, finish in-flight
    {"op":"stats"}                      request a stats snapshot
    {"op":"reset_measurement"}          zero decode-gap/peak stats
                                        (bench warmup exclusion)
    {"op":"migrate_in","xfer":X,"host":H,"port":P,"prompt":[...]}
                                        pull this prompt's KV page
                                        chain from the replica at H:P
                                        and import it locally (the
                                        router re-homing a finished
                                        chain onto a decode replica —
                                        serve/migrate.py)
    {"op":"reattach","id":W}            a SUCCESSOR router re-adopting
                                        request W across a router
                                        death: the replica replays W's
                                        retained token tail (i=0..)
                                        and its done record on THIS
                                        connection — the engine never
                                        stopped decoding while the old
                                        router's socket was down.
                                        Unknown W → ``reattach_nack``
                                        (the request died with this
                                        replica; the router falls back
                                        to ordinary budgeted failover)

  Every CONTROLLER op may carry ``"epoch": E`` — the sender's fencing
  epoch from the shared leader lease (serve/ha.py).  The replica
  tracks the highest epoch it has seen and REJECTS ops from below it
  with ``{"op":"stale_epoch",...}``: a deposed router that never
  noticed losing the lease (GC pause, partition) is fenced out here,
  at the only place split-brain could corrupt a client stream.  Ops
  without an epoch (peer page_fetch, pre-HA routers) skip the check.

  peer replica (or router) → replica           KV-page migration
    {"op":"page_fetch","xfer":X,"prompt":[...],"lo":L,"n":N}
                                        serve window [L, L+N) of the
                                        prompt's page chain; the first
                                        fetch takes a migration hold
                                        on the whole chain
    {"op":"page_fetch","xfer":X,"release":true}   drop the hold

  replica → router
    {"op":"token","id":W,"token":T,"i":I}   token I of request W retired
    {"op":"done","id":W,"tokens":[...],...} request W finished
    {"op":"backpressure","id":W,"retry_after":S}  engine shed it
    {"op":"error","id":W,"error":MSG}       engine rejected it
    {"op":"stats",...}                      stats snapshot
    {"op":"migrated","xfer":X,"ok":B,"pages":N,...}  migrate_in result

  replica → peer replica
    {"op":"page_push","xfer":X,"depth":D,"digest":C,"tokens":[...],
     "payload":{...},"chain_len":L}     one chain page (+ end-of-
                                        window / error markers —
                                        serve/migrate.py has the full
                                        grammar and the verification
                                        contract)

RENDEZVOUS is file-based, deliberately: the replica binds an EPHEMERAL
port (no port-allocation coordination, no TOCTOU between picking and
binding) and atomically writes ``replica_rank{K}.json`` — {"host",
"port", "pid", "generation", "ts"} — into the shared rendezvous
directory.  The router polls that file to (re)connect, so a RESPAWNED
replica re-registers by construction: new process, new port, new
announce content, same path.  The rendezvous directory is the tier's
only shared-state requirement: put it on shared storage (NFS/GCS-fuse)
and bind replicas to a routable address (``--serve_host``), and
replicas on OTHER HOSTS register, heartbeat, and heal identically to
local ones — the announce carries ``host:port``, and the wire is
already plain TCP.  Liveness travels separately, through the obs
heartbeat files (``heartbeat_rank{K}.json``) the engine rewrites every
iteration — the router's health probe reads those, never the socket,
so a wedged replica with a healthy TCP stack still reads as dead.

The engine is duck-typed (``submit``/``begin_drain``/``outstanding``):
tests drive the full wire protocol against a deterministic fake engine
with no jax in the process, and the subprocess entry
(cli/replica_main.py) passes the real thing.
"""

from __future__ import annotations

import json
import logging
import os
import queue as queue_mod
import socket
import threading
import time
from typing import Optional

import numpy as np

from dtf_tpu.obs import trace
from dtf_tpu.serve import migrate
from dtf_tpu.serve.engine import Backpressure

log = logging.getLogger("dtf_tpu")

# retained per-request tails kept after their request finished: enough
# for a takeover-window's worth of re-adoptions, bounded so a
# long-lived replica's memory does not grow with total traffic
RETAIN_DONE_CAP = 256


def announce_path(rendezvous_dir: str, replica_id: int) -> str:
    return os.path.join(rendezvous_dir, f"replica_rank{replica_id}.json")


def read_announce(rendezvous_dir: str, replica_id: int) -> Optional[dict]:
    """Parse a replica's announce file; None when missing/torn (the
    router treats that as 'not yet registered', not as an error)."""
    try:
        with open(announce_path(rendezvous_dir, replica_id)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def send_msg(wfile, lock: threading.Lock, obj: dict) -> None:
    """One JSON line, atomically w.r.t. other senders on this socket."""
    data = (json.dumps(obj) + "\n").encode()
    with lock:
        wfile.write(data)
        wfile.flush()


class ReplicaServer:
    """Serve one engine over a loopback socket + announce file.

    ``engine`` needs ``submit(prompt, max_new_tokens, temperature,
    eos_id, on_token, trace_id, trace_parent) -> handle`` (handle:
    ``result(timeout)`` → object with ``.tokens``/``.cancelled``),
    ``begin_drain()`` and ``outstanding``;
    :class:`~dtf_tpu.serve.engine.ServeEngine` satisfies it, and the
    router tests use a jax-free fake.

    LOCK DISCIPLINE: ``_conns`` is shared by the accept loop, every
    per-connection thread's teardown, and ``stop()`` — guarded by
    ``_lock`` (declared below, enforced by tools/dtflint lock-guard):
    an unguarded ``list.remove`` racing another teardown throws
    ValueError into the connection thread's finally block.  The same
    lock guards ``_retained`` (the per-request token tails a successor
    router re-adopts — written by engine on_token callbacks, rebound
    by ``reattach`` on a DIFFERENT connection's wire thread) and
    ``_max_epoch`` (the fencing high-water mark every controller wire
    thread checks)."""

    _GUARDED_BY = {"_conns": "_lock", "_retained": "_lock",
                   "_max_epoch": "_lock"}

    def __init__(self, engine, replica_id: int, rendezvous_dir: str,
                 host: str = "127.0.0.1", port: int = 0,
                 result_timeout_s: float = 600.0,
                 announce_host: Optional[str] = None):
        self.engine = engine
        self.replica_id = int(replica_id)
        self.rendezvous_dir = os.path.abspath(rendezvous_dir)
        self.result_timeout_s = float(result_timeout_s)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(8)
        self.port = self._listener.getsockname()[1]
        # the endpoint the ROUTER dials: the bind address, unless that
        # is a wildcard (0.0.0.0 accepts from anywhere but is not
        # dialable) — then the caller must name the routable address
        self.host = announce_host or (
            "127.0.0.1" if host in ("", "0.0.0.0") else host)
        self._stop = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._conns: list = []
        # wire id -> {"tokens": [...], "done": msg|None, "outq": q|None}
        # — the request's retained tail.  Survives the CONNECTION (a
        # router death must not lose tokens the engine keeps retiring);
        # ``reattach`` rebinds "outq" to the successor's connection.
        # Done entries are pruned beyond RETAIN_DONE_CAP, oldest first.
        self._retained: dict = {}
        # fencing epoch high-water mark (serve/ha.py): controller ops
        # carrying an epoch below this are rejected as stale
        self._max_epoch = 0

    # -- rendezvous ----------------------------------------------------
    def _announce(self) -> None:
        os.makedirs(self.rendezvous_dir, exist_ok=True)
        payload = {
            "host": self.host,
            "port": self.port,
            "pid": os.getpid(),
            "generation": int(os.environ.get("DTF_RESTART_GENERATION",
                                             "0")),
            "ts": time.time(),
        }
        path = announce_path(self.rendezvous_dir, self.replica_id)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)   # atomic: the router never reads a torn file

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "ReplicaServer":
        self._announce()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"replica{self.replica_id}-accept")
        self._accept_thread.start()
        log.info("replica %d: serving on %s:%d (rendezvous %s)",
                 self.replica_id, self.host, self.port,
                 self.rendezvous_dir)
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            # shutdown BEFORE close: close() alone does not unblock a
            # thread sitting in accept(2) — the syscall keeps the
            # kernel socket referenced, so the "closed" listener keeps
            # accepting and a router dialing a dead in-process replica
            # reaches a ghost.  shutdown() aborts the accept.
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- connection handling -------------------------------------------
    def _accept_loop(self) -> None:
        # multiple concurrent connections are allowed: after a
        # partition the router reconnects while its old (half-dead)
        # connection may still exist — responses go to the connection
        # their submit arrived on, and writes to a closed one are
        # dropped (the router re-dispatched those requests anyway)
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            with self._lock:
                self._conns.append(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True,
                             name=f"replica{self.replica_id}-conn").start()

    def _serve_conn(self, conn: socket.socket) -> None:
        rfile = conn.makefile("rb")
        wfile = conn.makefile("wb")
        outq: "queue_mod.Queue" = queue_mod.Queue()
        dead = threading.Event()
        wlock = threading.Lock()

        def writer():
            while True:
                item = outq.get()
                if item is None:
                    return
                try:
                    send_msg(wfile, wlock, item)
                except (OSError, ValueError):
                    # router gone (or going): stop queuing work for a
                    # dead pipe; in-flight engine work keeps running —
                    # the router re-dispatches what it still wants
                    dead.set()
                    return

        wthread = threading.Thread(
            target=writer, daemon=True,
            name=f"replica{self.replica_id}-writer")
        wthread.start()
        # wire id -> engine handle, for CANCEL routing (per connection:
        # a reconnected router's cancels can only name work it
        # dispatched on THIS connection; entries die with the request)
        handles: dict = {}
        # xfer id -> in-flight chain export (pages under migration
        # hold).  Per connection, so a client that vanishes releases
        # its holds in the finally below — a dead peer cannot pin
        # pages forever
        exports: dict = {}
        try:
            for line in rfile:
                if self._stop.is_set():
                    break
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    log.warning("replica %d: bad wire line %r",
                                self.replica_id, line[:80])
                    continue
                op = msg.get("op")
                ep = msg.get("epoch")
                if ep is not None:
                    ep = int(ep)
                    with self._lock:
                        cur = self._max_epoch
                        if ep >= cur:
                            self._max_epoch = ep
                    if ep < cur:
                        # fenced controller: a deposed router that
                        # never noticed losing the lease.  Reject the
                        # op LOUDLY — obeying it is exactly the
                        # split-brain a fencing epoch exists to stop.
                        log.error("replica %d: rejecting stale-epoch "
                                  "op %r (epoch %d < %d)",
                                  self.replica_id, op, ep, cur)
                        trace.anomaly("stale_epoch", op=op, epoch=ep,
                                      current=cur,
                                      wire_id=msg.get("id"))
                        outq.put({"op": "stale_epoch",
                                  "id": msg.get("id"), "epoch": ep,
                                  "current": cur})
                        continue
                if op == "submit":
                    self._handle_submit(msg, outq, dead, handles)
                elif op == "reattach":
                    self._handle_reattach(msg, outq)
                elif op == "cancel":
                    h = handles.pop(msg.get("id"), None)
                    if h is not None and hasattr(h, "cancel"):
                        h.cancel()
                elif op == "drain":
                    self.engine.begin_drain()
                elif op == "stats":
                    stats = self._stats()
                    stats["tag"] = msg.get("tag", "")
                    outq.put(stats)
                elif op == "reset_measurement":
                    if hasattr(self.engine, "reset_measurement"):
                        self.engine.reset_measurement()
                elif op == "page_fetch":
                    self._handle_page_fetch(msg, outq, exports)
                elif op == "migrate_in":
                    # own thread: fetch_chain blocks on the peer's
                    # socket + engine jobs, and this wire loop must
                    # keep serving submits/cancels meanwhile
                    threading.Thread(
                        target=self._handle_migrate_in,
                        args=(msg, outq), daemon=True,
                        name=f"replica{self.replica_id}-migrate").start()
                else:
                    log.warning("replica %d: unknown op %r",
                                self.replica_id, op)
        except OSError:
            pass
        finally:
            for st in exports.values():
                # the peer vanished mid-transfer: its migration holds
                # die with the connection
                try:
                    self.engine.export_chain_end(st["pages"])
                except Exception:  # noqa: BLE001 — teardown must not
                    # raise into the accept machinery
                    log.exception("replica %d: export-hold release "
                                  "failed", self.replica_id)
            dead.set()
            outq.put(None)
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)
                # unbind this connection's queue from the retained
                # tails: the engine keeps decoding (and retaining) —
                # deliveries resume when a successor reattaches
                for rec in self._retained.values():
                    if rec["outq"] is outq:
                        rec["outq"] = None

    def _stats(self) -> dict:
        out = {"op": "stats", "replica": self.replica_id,
               "outstanding": int(getattr(self.engine, "outstanding", 0)),
               "pid": os.getpid()}
        metrics = getattr(self.engine, "metrics", None)
        if metrics is not None:
            for name in ("serve_completed_total", "serve_shed_total",
                         "serve_prefix_hit_pages_total",
                         "serve_prefix_cow_total",
                         "serve_pages_exported_total",
                         "serve_pages_imported_total",
                         "serve_migration_torn_total",
                         "serve_prefill_chunks_total"):
                m = metrics.get(name)
                if m is not None:
                    out[name] = m.value
            gap = metrics.get("serve_decode_gap_s")
            if gap is not None:
                # per-replica decode-gap tail: the pool-role
                # comparison number (bench_serve's disaggregated-vs-
                # colocated bar reads it over the wire)
                out["serve_decode_gap_p99"] = gap.percentile(99.0)
                out["serve_decode_gap_count"] = gap.count
        return out

    # -- KV-page migration (serve/migrate.py) --------------------------
    def _handle_page_fetch(self, msg: dict, outq, exports: dict) -> None:
        """Serve one window of a chain export — or release the hold.
        Runs on the wire thread; the engine methods marshal their pool/
        cache work onto the engine thread internally."""
        xfer = msg.get("xfer")
        if msg.get("release"):
            st = exports.pop(xfer, None)
            if st is not None:
                try:
                    self.engine.export_chain_end(st["pages"])
                except Exception as e:  # noqa: BLE001 — a release race
                    # with engine stop is the peer's teardown, not ours
                    log.warning("replica %d: export release failed: %s",
                                self.replica_id, e)
            return
        if not hasattr(self.engine, "export_chain_begin"):
            outq.put({"op": "page_push", "xfer": xfer,
                      "error": "replica does not serve page migration"})
            return
        st = exports.get(xfer)
        if st is None:
            prompt = np.asarray(msg.get("prompt", ()), np.int32)
            try:
                pages, digests = self.engine.export_chain_begin(prompt)
            except Exception as e:  # noqa: BLE001 — the peer gets the
                # failure, the wire loop keeps serving
                outq.put({"op": "page_push", "xfer": xfer,
                          "error": str(e)})
                return
            st = exports[xfer] = {"pages": pages, "digests": digests,
                                  "prompt": prompt}
        lo = max(0, int(msg.get("lo", 0)))
        n = max(0, int(msg.get("n", migrate.DEFAULT_WINDOW)))
        chain_len = len(st["pages"])
        hi = min(lo + n, chain_len)
        try:
            windows = (self.engine.export_chain_read(st["pages"], lo,
                                                     hi - lo)
                       if hi > lo else [])
        except Exception as e:  # noqa: BLE001
            outq.put({"op": "page_push", "xfer": xfer, "error": str(e)})
            return
        ps = int(getattr(self.engine, "page_size", 0) or 0)
        for k, leaves in enumerate(windows):
            d = lo + k
            outq.put({
                "op": "page_push", "xfer": xfer, "depth": d,
                "digest": st["digests"][d],
                "tokens": [int(t) for t in
                           st["prompt"][d * ps:(d + 1) * ps]],
                "payload": migrate.encode_page(leaves),
                "chain_len": chain_len,
            })
        outq.put({"op": "page_push", "xfer": xfer, "end": True,
                  "lo": lo, "sent": hi - lo, "chain_len": chain_len})

    def _handle_migrate_in(self, msg: dict, outq) -> None:
        """Pull a chain from a peer replica and import it (the decode-
        replica side of a router-commanded re-homing)."""
        xfer = msg.get("xfer")
        if not hasattr(self.engine, "import_chain"):
            outq.put({"op": "migrated", "xfer": xfer, "ok": False,
                      "pages": 0,
                      "error": "replica does not import pages"})
            return
        try:
            stats = migrate.fetch_chain(
                self.engine, msg["host"], int(msg["port"]),
                np.asarray(msg.get("prompt", ()), np.int32))
        except Exception as e:  # noqa: BLE001 — migration failure is
            # an efficiency loss, never a correctness event: the
            # router keeps routing this prefix wherever it lives
            log.error("replica %d: migrate_in failed: %s",
                      self.replica_id, e)
            outq.put({"op": "migrated", "xfer": xfer, "ok": False,
                      "pages": 0, "error": str(e)})
            return
        outq.put({"op": "migrated", "xfer": xfer, "ok": True, **stats})

    def _handle_submit(self, msg: dict, outq, dead: threading.Event,
                       handles: dict):
        wire_id = msg["id"]
        with self._lock:
            # the request's retained tail: tokens append here FIRST
            # (under the lock reattach replays under), then go to
            # whatever connection the record is currently bound to —
            # a router death loses the pipe, never the tokens
            rec = self._retained[wire_id] = {
                "tokens": [], "done": None, "outq": outq,
                "ts": time.time()}

        def on_token(tok: int) -> None:
            # engine thread: per-request tokens retire sequentially;
            # the lock orders each append against any concurrent
            # reattach replay, so indices never interleave on the wire
            with self._lock:
                rec["tokens"].append(int(tok))
                i = len(rec["tokens"]) - 1
                q = rec["outq"]
            if q is not None and not (q is outq and dead.is_set()):
                q.put({"op": "token", "id": wire_id, "token": int(tok),
                       "i": i})

        try:
            handle = self.engine.submit(
                np.asarray(msg["prompt"], np.int32),
                max_new_tokens=int(msg.get("max_new_tokens", 32)),
                temperature=float(msg.get("temperature", 0.0)),
                eos_id=msg.get("eos_id"),
                on_token=on_token,
                # distributed span context: the router's trace id and
                # request span id ride the wire so this replica's
                # records join the request's cross-process timeline —
                # including a failover replay, which arrives with the
                # SAME trace id on a sibling
                trace_id=msg.get("trace"),
                trace_parent=msg.get("pspan"),
                # the request's wire-carried sampling identity: a
                # failover replay with the same seed samples the same
                # tokens (serve/decode.py position_key)
                rng_seed=msg.get("rng_seed"))
        except Backpressure as bp:
            # never admitted: nothing to retain — a successor must
            # re-dispatch, not reattach to a shed request
            with self._lock:
                self._retained.pop(wire_id, None)
            outq.put({"op": "backpressure", "id": wire_id,
                      "retry_after": float(bp.retry_after)})
            return
        except Exception as e:  # noqa: BLE001 — a malformed request
            # must fail ITS caller, never the wire loop
            with self._lock:
                self._retained.pop(wire_id, None)
            outq.put({"op": "error", "id": wire_id, "error": str(e)})
            return
        handles[wire_id] = handle

        def waiter():
            try:
                r = handle.result(timeout=self.result_timeout_s)
            except Exception as e:  # noqa: BLE001
                handles.pop(wire_id, None)
                with self._lock:
                    self._retained.pop(wire_id, None)
                outq.put({"op": "error", "id": wire_id, "error": str(e)})
                return
            handles.pop(wire_id, None)
            done = {"op": "done", "id": wire_id,
                    "tokens": [int(t) for t in r.tokens],
                    "cancelled": bool(r.cancelled),
                    "prompt_len": int(r.prompt_len),
                    "latency_s": float(r.latency_s)}
            with self._lock:
                rec["done"] = done
                q = rec["outq"]
                self._prune_retained_locked()
            if q is not None and not (q is outq and dead.is_set()):
                q.put(done)

        threading.Thread(target=waiter, daemon=True,
                         name=f"replica{self.replica_id}-wait").start()

    def _prune_retained_locked(self) -> None:
        """Bound the retained-tail store: finished requests beyond
        RETAIN_DONE_CAP drop, oldest first (unfinished ones are live
        engine work and stay — they are the re-adoption payload)."""
        done = [(rec["ts"], wid) for wid, rec in self._retained.items()
                if rec["done"] is not None]
        if len(done) <= RETAIN_DONE_CAP:
            return
        done.sort()
        for _, wid in done[:len(done) - RETAIN_DONE_CAP]:
            self._retained.pop(wid, None)

    def _handle_reattach(self, msg: dict, outq) -> None:
        """A successor router re-adopting one request (router HA,
        serve/ha.py): rebind the retained record to THIS connection
        and replay its buffered tail — ack, every token from i=0 (the
        router's token-index dedupe verifies what its client already
        has and emits only the rest), then the done record if the
        engine already finished.  All under the lock on_token appends
        under, so replayed and live indices never interleave."""
        wire_id = msg.get("id")
        with self._lock:
            rec = self._retained.get(wire_id)
            if rec is not None:
                outq.put({"op": "reattached", "id": wire_id,
                          "n": len(rec["tokens"]),
                          "done": rec["done"] is not None})
                for i, t in enumerate(rec["tokens"]):
                    outq.put({"op": "token", "id": wire_id,
                              "token": int(t), "i": i})
                if rec["done"] is not None:
                    outq.put(dict(rec["done"]))
                rec["outq"] = outq
        if rec is None:
            # the request died WITH this replica (it was respawned, or
            # never held it): the router falls back to ordinary
            # budgeted failover re-dispatch
            outq.put({"op": "reattach_nack", "id": wire_id})
