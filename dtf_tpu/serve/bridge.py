"""Checkpoint → serving bridge.

Train-side state comes in two on-disk shapes (train/checkpoint.py):

  <model_dir>/checkpoints/<step>/  — the full TrainState (params,
      batch_stats, optimizer state, step) written by the per-epoch
      CheckpointCallback via orbax CheckpointManager
  <export_dir>/model/              — inference variables only
      (params + batch_stats), the --export_dir SavedModel equivalent

Serving needs neither optimizer state nor the step counter.  Params
come out of orbax as host-global arrays regardless of how the run was
sharded — a ZeRO run (--optimizer_sharding) slices only its *optimizer*
state across 'data', and a TP/EP/PP run's params are saved as global
arrays with per-leaf shardings — so placement is one decision per
serving deployment:

  model_parallelism == 1 — device_put the restored tree with the
      replicated sharding of a fresh 1-chip serving mesh (the original
      restore-then-rebroadcast contract).
  model_parallelism N — build an N-chip serving mesh ('model' axis =
      N) and device_put each leaf DIRECTLY into the Megatron layout
      (``param_partition_specs``: heads/ff column-parallel, out/fc2
      row-parallel, everything else replicated).  The host-global
      restore goes straight to its shards — no replicated on-device
      intermediate, so a model that trains sharded loads for serving
      without ever needing to fit on one chip.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax

log = logging.getLogger("dtf_tpu")


def serving_mesh(model_parallelism: int = 1, devices=None):
    """A serving mesh: ``model_parallelism`` devices on the 'model'
    axis (data = seq = 1 — serving data parallelism is replica
    processes, not a mesh axis)."""
    from dtf_tpu.runtime.mesh import make_mesh

    mp = max(int(model_parallelism), 1)
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < mp:
        raise ValueError(
            f"serving model_parallelism {mp} needs {mp} devices, "
            f"{len(devices)} attached")
    return make_mesh(devices[:mp], data=1, seq=1, model=mp)


def load_inference_variables(model_dir: str = "", export_dir: str = "",
                             step: Optional[int] = None) -> dict:
    """Load {"params": ..., "batch_stats": ...} from a train checkpoint
    (``model_dir``) or an exported model (``export_dir``).

    ``export_dir`` wins when both are given (it is the purpose-built
    inference artifact).  ``step`` selects a specific train checkpoint;
    None = latest.  Raises FileNotFoundError when neither location has
    a restorable checkpoint — serving random init would silently answer
    garbage, which is strictly worse than failing."""
    if export_dir and os.path.isdir(os.path.join(
            os.path.abspath(export_dir), "model")):
        from dtf_tpu.train.checkpoint import load_exported_model
        payload = load_exported_model(export_dir)
        log.info("serve bridge: loaded exported model from %s", export_dir)
        return {"params": payload["params"],
                "batch_stats": payload.get("batch_stats", {})}
    if model_dir:
        from dtf_tpu.train.checkpoint import load_train_checkpoint
        payload = load_train_checkpoint(model_dir, step=step)
        if payload is not None:
            return payload
    raise FileNotFoundError(
        f"no checkpoint to serve: export_dir={export_dir!r} has no "
        f"model/, model_dir={model_dir!r} has no checkpoints/")


def tp_param_shardings(params, mesh):
    """(PartitionSpec tree, NamedSharding tree) of the Megatron serving
    layout for a full param pytree — THE single definition both the
    bridge's placement and the Decoder's shard_map in_specs consume, so
    a layout change cannot silently diverge between them."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dtf_tpu.models.transformer import param_partition_specs
    from dtf_tpu.runtime.mesh import MODEL_AXIS

    specs = param_partition_specs(params, MODEL_AXIS)
    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))
    return specs, shardings


def place_for_serving(variables, devices=None, mesh=None,
                      model_parallelism: int = 1):
    """Place the (host-global) inference variables on the serving mesh.

    Replicated at ``model_parallelism`` 1 (the original contract);
    otherwise each params leaf goes DIRECTLY to its tensor-parallel
    shard per ``param_partition_specs`` — train/export/ZeRO
    checkpoints restore into the sharded layout with no replicated
    intermediate.  ``mesh`` overrides the mesh construction (the
    engine and the bridge must agree on one)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dtf_tpu.runtime.mesh import MODEL_AXIS, make_mesh

    if mesh is None:
        if model_parallelism > 1:
            mesh = serving_mesh(model_parallelism, devices)
        else:
            devices = list(devices if devices is not None
                           else jax.devices()[:1])
            mesh = make_mesh(devices, data=1, seq=1, model=1)
    mp = int(mesh.shape[MODEL_AXIS])
    if mp <= 1:
        return jax.device_put(variables, NamedSharding(mesh, P()))
    replicated = NamedSharding(mesh, P())
    shardings = {k: (tp_param_shardings(v, mesh)[1] if k == "params"
                     else jax.tree_util.tree_map(lambda _: replicated, v))
                 for k, v in variables.items()}
    return jax.device_put(variables, shardings)


def load_for_serving(model_dir: str = "", export_dir: str = "",
                     step: Optional[int] = None, devices=None, mesh=None,
                     model_parallelism: int = 1) -> dict:
    """One-call bridge: restore + place (replicated or TP-sharded)."""
    return place_for_serving(
        load_inference_variables(model_dir, export_dir, step=step),
        devices=devices, mesh=mesh, model_parallelism=model_parallelism)


def serving_memory_plan(model, *, num_slots: int, max_seq_len: int,
                        kv_page_size: int = 0,
                        kv_pool_pages: int = 0,
                        model_parallelism: int = 1) -> dict:
    """Byte accounting for a serving deployment: params + KV cache.

    The KV side is where the paged cache earns its keep: the contiguous
    layout reserves ``num_slots × max_seq_len`` token slots per layer
    regardless of traffic, while the paged pool holds
    ``(kv_pool_pages − 1) × kv_page_size`` tokens TOTAL — sized to the
    expected tokens in flight, not the worst case.  ``kv_pool_pages``
    of 0 = the full contiguous-equivalent reservation (plus the scratch
    page).  Returns dict with ``kv_bytes_contiguous``,
    ``kv_bytes_paged``, ``kv_tokens_capacity`` and the layer geometry —
    serve_main logs it so pool sizing is a visible decision, not a
    guess."""
    import numpy as np

    head_dim = model.d_model // model.num_heads
    # 2 arrays (K and V) per layer; cache dtype follows compute dtype
    # (np.dtype resolves jnp scalar types incl. bfloat16 via ml_dtypes)
    elem = np.dtype(model.dtype).itemsize
    per_token = 2 * model.num_layers * model.num_heads * head_dim * elem
    pages_per_slot = -(-max_seq_len // max(kv_page_size, 1))
    full_pages = 1 + num_slots * pages_per_slot
    pool_pages = int(kv_pool_pages) or full_pages
    contiguous_tokens = num_slots * max_seq_len
    paged_tokens = (pool_pages - 1) * kv_page_size if kv_page_size else 0
    mp = max(int(model_parallelism), 1)
    plan = {
        "per_token_kv_bytes": per_token,
        "kv_bytes_contiguous": contiguous_tokens * per_token,
        "kv_bytes_paged": paged_tokens * per_token,
        "kv_tokens_capacity": paged_tokens or contiguous_tokens,
        "pages_per_slot": pages_per_slot if kv_page_size else 0,
        "pool_pages": pool_pages if kv_page_size else 0,
        # TP shards the pool's HEAD dim: each of the mp chips holds
        # 1/mp of every page (and of the params) — the lever that
        # makes a too-big-for-one-chip model servable at all
        "model_parallelism": mp,
        "kv_bytes_per_device":
            ((paged_tokens or contiguous_tokens) * per_token) // mp,
    }
    log.info(
        "serving memory plan: %d slots x %d tokens; KV contiguous %.1f "
        "MB%s%s", num_slots, max_seq_len,
        plan["kv_bytes_contiguous"] / 2**20,
        (f", paged pool {plan['kv_bytes_paged'] / 2**20:.1f} MB "
         f"({pool_pages} pages x {kv_page_size} tokens)"
         if kv_page_size else " (paged cache off)"),
        (f", TP={mp}: {plan['kv_bytes_per_device'] / 2**20:.1f} "
         f"MB KV/device" if mp > 1 else ""))
    return plan
