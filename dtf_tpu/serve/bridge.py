"""Checkpoint → serving bridge.

Train-side state comes in two on-disk shapes (train/checkpoint.py):

  <model_dir>/checkpoints/<step>/  — the full TrainState (params,
      batch_stats, optimizer state, step) written by the per-epoch
      CheckpointCallback via orbax CheckpointManager
  <export_dir>/model/              — inference variables only
      (params + batch_stats), the --export_dir SavedModel equivalent

Serving needs neither optimizer state nor the step counter, and it
needs FULL (un-sharded) parameter arrays on the serving device.  Both
come out of orbax as host-global arrays regardless of how the run was
sharded — a ZeRO run (--optimizer_sharding) slices only its *optimizer*
state across 'data', and a TP/EP/PP run's params are saved as global
arrays with per-leaf shardings — so the re-gather is: restore the
global view, drop everything but params/batch_stats, and device_put the
result with the replicated sharding of a fresh serving mesh
(runtime/mesh.py ``make_mesh`` + ``NamedSharding(mesh, P())``).
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax

log = logging.getLogger("dtf_tpu")


def load_inference_variables(model_dir: str = "", export_dir: str = "",
                             step: Optional[int] = None) -> dict:
    """Load {"params": ..., "batch_stats": ...} from a train checkpoint
    (``model_dir``) or an exported model (``export_dir``).

    ``export_dir`` wins when both are given (it is the purpose-built
    inference artifact).  ``step`` selects a specific train checkpoint;
    None = latest.  Raises FileNotFoundError when neither location has
    a restorable checkpoint — serving random init would silently answer
    garbage, which is strictly worse than failing."""
    if export_dir and os.path.isdir(os.path.join(
            os.path.abspath(export_dir), "model")):
        from dtf_tpu.train.checkpoint import load_exported_model
        payload = load_exported_model(export_dir)
        log.info("serve bridge: loaded exported model from %s", export_dir)
        return {"params": payload["params"],
                "batch_stats": payload.get("batch_stats", {})}
    if model_dir:
        from dtf_tpu.train.checkpoint import load_train_checkpoint
        payload = load_train_checkpoint(model_dir, step=step)
        if payload is not None:
            return payload
    raise FileNotFoundError(
        f"no checkpoint to serve: export_dir={export_dir!r} has no "
        f"model/, model_dir={model_dir!r} has no checkpoints/")


def place_for_serving(variables, devices=None):
    """Re-gather + place: put the (host-global) inference variables on
    the serving mesh, fully replicated — the broadcast half of the
    restore-then-rebroadcast checkpoint contract, reused for serving."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dtf_tpu.runtime.mesh import make_mesh

    devices = list(devices if devices is not None else jax.devices()[:1])
    mesh = make_mesh(devices, data=1, seq=1, model=1)
    return jax.device_put(variables, NamedSharding(mesh, P()))


def load_for_serving(model_dir: str = "", export_dir: str = "",
                     step: Optional[int] = None, devices=None) -> dict:
    """One-call bridge: restore + re-gather + place."""
    return place_for_serving(
        load_inference_variables(model_dir, export_dir, step=step),
        devices=devices)


def serving_memory_plan(model, *, num_slots: int, max_seq_len: int,
                        kv_page_size: int = 0,
                        kv_pool_pages: int = 0) -> dict:
    """Byte accounting for a serving deployment: params + KV cache.

    The KV side is where the paged cache earns its keep: the contiguous
    layout reserves ``num_slots × max_seq_len`` token slots per layer
    regardless of traffic, while the paged pool holds
    ``(kv_pool_pages − 1) × kv_page_size`` tokens TOTAL — sized to the
    expected tokens in flight, not the worst case.  ``kv_pool_pages``
    of 0 = the full contiguous-equivalent reservation (plus the scratch
    page).  Returns dict with ``kv_bytes_contiguous``,
    ``kv_bytes_paged``, ``kv_tokens_capacity`` and the layer geometry —
    serve_main logs it so pool sizing is a visible decision, not a
    guess."""
    import numpy as np

    head_dim = model.d_model // model.num_heads
    # 2 arrays (K and V) per layer; cache dtype follows compute dtype
    # (np.dtype resolves jnp scalar types incl. bfloat16 via ml_dtypes)
    elem = np.dtype(model.dtype).itemsize
    per_token = 2 * model.num_layers * model.num_heads * head_dim * elem
    pages_per_slot = -(-max_seq_len // max(kv_page_size, 1))
    full_pages = 1 + num_slots * pages_per_slot
    pool_pages = int(kv_pool_pages) or full_pages
    contiguous_tokens = num_slots * max_seq_len
    paged_tokens = (pool_pages - 1) * kv_page_size if kv_page_size else 0
    plan = {
        "per_token_kv_bytes": per_token,
        "kv_bytes_contiguous": contiguous_tokens * per_token,
        "kv_bytes_paged": paged_tokens * per_token,
        "kv_tokens_capacity": paged_tokens or contiguous_tokens,
        "pages_per_slot": pages_per_slot if kv_page_size else 0,
        "pool_pages": pool_pages if kv_page_size else 0,
    }
    log.info(
        "serving memory plan: %d slots x %d tokens; KV contiguous %.1f "
        "MB%s", num_slots, max_seq_len,
        plan["kv_bytes_contiguous"] / 2**20,
        (f", paged pool {plan['kv_bytes_paged'] / 2**20:.1f} MB "
         f"({pool_pages} pages x {kv_page_size} tokens)"
         if kv_page_size else " (paged cache off)"))
    return plan
