"""Dynamic batching engine: request queue → slot-based continuous
batching over the KV-cache decoder.

Serving traffic is many small requests arriving at random times;
accelerators want big fixed-shape batches.  The engine bridges the two
with the standard production recipe:

  admission control — ``submit`` validates size up front: a request
      whose prompt + budget cannot fit the cache is rejected loudly
      (ValueError) instead of being admitted and truncated silently.
  backpressure      — the queue is bounded.  A full queue sheds the
      request with :class:`Backpressure` carrying ``retry_after``
      (an EWMA-based estimate), and logs the shed — "loud shed":
      capacity problems must be visible, never silent latency.
  max-batch / max-delay — a fresh batch waits up to ``max_delay_s``
      after the first arrival to fill up to ``max_batch`` slots, then
      goes; once decoding, new arrivals join at any step boundary.
  continuous batching — the decode step always runs the full
      [num_slots, 1] shape (compiled exactly once); each slot carries
      its own ``cache_index``, so sequences of different lengths
      coexist, finish independently, and free their slot for the next
      queued request without draining the batch.

With the PAGED KV cache (``kv_page_size``, the default) two more
production levers land:

  paged admission — HBM is a shared page pool (:class:`PagePool`), and
      a request is admitted when its worst-case page count
      (⌈(prompt + budget) / page_size⌉) is free — so concurrency is
      bounded by TOKENS IN FLIGHT, not num_slots × max_seq_len.  A
      pool sized at 50% of the contiguous reservation serves the same
      slot count whenever mean request length < 50% of max_seq_len.
      When the head of the queue cannot get pages it WAITS (FIFO —
      large requests are not starved by small ones slipping past);
      retiring slots free their pages for the next admit.
  chunked prefill — prompts prefill in ``prefill_chunk``-token
      page-aligned chunks, ONE chunk per engine iteration, with a
      decode step for running slots between chunks — a max-length
      prompt adds bounded (chunk-sized) gaps to running decodes
      instead of head-of-line-blocking them for the whole prompt.
      The first chunk of every prompt runs pure causal self-attention
      through the flash kernel (no cache gather at all), so short
      prompts — the common case — never touch the gather path.

With prefix sharing (on by default in paged mode) the pool pages are
REFCOUNTED and a registry keyed by token-id hash maps every request's
full prompt-prefix pages to their physical pages:

  prefix hits — an admitted prompt whose leading full pages match a
      registered prefix (verified against the stored token ids — a
      hash collision degrades to a miss, never a wrong share) SHARES
      those physical pages instead of allocating + prefilling them: a
      common system prompt costs ONE physical copy across the whole
      batch, and admission needs only the unshared tail's pages.
      The registry OWNS one holder per registered page (cache
      semantics), so a warm prefix survives its requests retiring;
      when admission starves for pages, registry-only pages are
      EVICTED deepest-first (so surviving shallower entries stay a
      valid chain) until the admit fits — cached prefixes never
      block live traffic.
  copy-on-write — shared pages are never written.  The one write that
      can target a shared page (a prompt that is ENTIRELY a registered
      prefix must still re-decode its last token for the first-token
      logits) copies the page onto a fresh one first
      (``Decoder.copy_page``), then diverges there.
  release on retire — refcounts drop at retire; a page returns to the
      free list (and its registry entry is dropped) only when the last
      holder releases it.

Token STREAMING: every handle exposes ``stream()`` — an iterator
yielding each generated token as its decode step retires, and
``submit(on_token=...)`` — a per-token callback from the engine thread.
First-token latency is then one decode step after prefill, not the
whole generation; the ``serve_stream_lag_s`` histogram records how far
consumers run behind the engine.

Tensor-parallel decode: pass ``mesh`` (runtime/mesh, 'model' axis = N)
and the decoder runs every prefill/decode under shard_map with params
and the KV page pool sharded over the axis (serve/decode.py).  The
engine's host-side logic — slots, pages, scheduling — is unchanged:
block tables are replicated, sharding is the decoder's concern.

Single engine thread owns ALL device work (prefill, decode, sampling);
``submit`` only enqueues — so there is no cross-thread jit contention.
Each decode step syncs the sampled tokens to the host (the EOS/budget
check needs them); at CPU/test scale this is negligible, on a real TPU
serving stack the next optimization would be a lookahead pipeline.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import logging
import queue as queue_mod
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from dtf_tpu import chaos
from dtf_tpu.obs import trace
from dtf_tpu.obs.ledger import Ledger
from dtf_tpu.obs.registry import MetricsRegistry
from dtf_tpu.serve.decode import Decoder

log = logging.getLogger("dtf_tpu")


class Backpressure(RuntimeError):
    """Request shed: the queue is full.  ``retry_after`` (seconds) is
    the engine's estimate of when capacity frees up."""

    def __init__(self, retry_after: float):
        super().__init__(
            f"serving queue full — shed; retry after {retry_after:.2f}s")
        self.retry_after = retry_after


@dataclasses.dataclass
class ServeRequest:
    prompt: np.ndarray                  # 1-D int32 token ids
    max_new_tokens: int = 32
    temperature: float = 0.0            # 0 = greedy
    eos_id: Optional[int] = None        # stop token (included in output)
    # distributed-tracing span context: the trace id follows the
    # request across processes (router → wire → here); trace_parent is
    # the upstream span id the per-request records link back to
    trace_id: Optional[str] = None
    trace_parent: Optional[str] = None
    # per-request sampling seed: sampled tokens are a pure function of
    # (rng_seed, position), so a re-dispatched SAMPLED request replays
    # token-exactly on any replica with identical params.  None at
    # submit = the engine derives one from (engine seed, request id)
    rng_seed: Optional[int] = None
    # filled by the engine
    id: int = -1
    submit_time: float = 0.0
    admit_time: float = 0.0
    first_token_time: float = 0.0
    finish_time: float = 0.0


@dataclasses.dataclass
class ServeResult:
    request_id: int
    tokens: List[int]                   # generated tokens (prompt excluded)
    prompt_len: int
    queue_wait_s: float
    time_to_first_token_s: float
    latency_s: float
    # absolute timestamps (time.time()), so metrics can reconstruct the
    # serving window across requests without trusting the caller
    submit_time: float = 0.0
    finish_time: float = 0.0
    cancelled: bool = False
    trace_id: Optional[str] = None      # the request's distributed-trace id


class _Handle:
    """Future-lite returned by submit() — plus a token stream.

    ``result()`` is the retire-granular view (all tokens at once);
    ``stream()`` yields each token as its decode step retires, so a
    client renders output at first-token latency instead of
    full-generation latency.  Both views see the same tokens."""

    def __init__(self, req: ServeRequest,
                 on_token: Optional[Callable] = None,
                 stream_lag_hist=None, cond=None):
        self.request = req
        self._event = threading.Event()
        self._result: Optional[ServeResult] = None
        self._on_token = on_token
        self._lag_hist = stream_lag_hist
        self._q: "queue_mod.Queue" = queue_mod.Queue()
        self._cancel = threading.Event()
        self._cond = cond

    def done(self) -> bool:
        return self._event.is_set()

    @property
    def cancelled(self) -> bool:
        return self._cancel.is_set()

    def cancel(self) -> None:
        """Ask the engine to stop working on this request.  The engine
        thread acts at its next iteration: a queued request resolves
        immediately (``cancelled=True``, no tokens), a running slot
        retires with the tokens decoded so far and frees its pages —
        the capacity a deadline-exceeded, failed-over, or losing-hedge
        attempt would otherwise burn decoding an answer nobody reads.
        Safe from any thread; idempotent."""
        self._cancel.set()
        if self._cond is not None and self._cond.acquire(blocking=False):
            try:
                self._cond.notify_all()
            finally:
                self._cond.release()

    def result(self, timeout: Optional[float] = None) -> ServeResult:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.request.id} not finished in {timeout}s")
        return self._result

    def stream(self, timeout: Optional[float] = None):
        """Iterator over generated tokens, yielding as each retires
        from a decode step.  ``timeout`` bounds the wait for EACH
        token (TimeoutError past it).  Ends when the request finishes
        (or is cancelled — check ``result().cancelled``).  Observes
        the engine's ``serve_stream_lag_s`` histogram: time from the
        engine emitting a token to the consumer receiving it — the
        slow-consumer signal."""
        while True:
            try:
                kind, payload = self._q.get(timeout=timeout)
            except queue_mod.Empty:
                raise TimeoutError(
                    f"request {self.request.id}: no token in {timeout}s"
                ) from None
            if kind == "done":
                return
            tok, t_emit = payload
            if self._lag_hist is not None:
                self._lag_hist.observe(max(0.0, time.time() - t_emit))
            yield tok

    def _emit(self, token: int):
        """Engine thread: one token retired."""
        self._q.put(("token", (int(token), time.time())))
        if self._on_token is not None:
            try:
                self._on_token(int(token))
            except Exception:  # noqa: BLE001 — a client callback must
                # never take down the engine thread
                log.exception("serve: on_token callback raised")

    def _deliver(self, result: ServeResult):
        self._result = result
        self._event.set()
        self._q.put(("done", None))


class PagePool:
    """Host-side REFCOUNTED free-list allocator over the shared KV
    page pool.

    Page 0 is the SCRATCH page — never handed to a request.  Inactive
    rows of the fixed-shape decode batch carry all-zeros block-table
    rows, so their garbage writes/gathers land there and can never
    touch a live sequence (ops.paged_attention has the full invariant).

    Refcounts carry prefix sharing: ``alloc`` grants fresh pages at
    refcount 1, ``share`` adds a holder to a live page, and ``free``
    releases one holder — a page physically returns to the free list
    only when its LAST holder releases it.  ``high_water`` records the
    peak physical pages in use — the number that proves both that
    retired pages are reclaimed AND that shared prefixes really cost
    one physical copy."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError(f"page pool needs >= 2 pages (page 0 is "
                             f"scratch), got {num_pages}")
        self.num_pages = int(num_pages)
        # LIFO free stack: a just-retired request's pages go to the
        # next admit — maximally warm reuse, and the reclamation tests
        # can assert the high-water mark stays at the concurrent need
        self._free = list(range(self.num_pages - 1, 0, -1))
        self._ref: Dict[int, int] = {}
        self.high_water = 0

    @property
    def usable_pages(self) -> int:
        return self.num_pages - 1

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.usable_pages - len(self._free)

    @property
    def shared_refs(self) -> int:
        """Extra holders beyond the first across all pages — how many
        page allocations prefix sharing is currently saving."""
        return sum(c - 1 for c in self._ref.values() if c > 1)

    def refcount(self, page: int) -> int:
        return self._ref.get(page, 0)

    def alloc(self, n: int) -> Optional[List[int]]:
        """n fresh pages at refcount 1, or None when the pool cannot
        cover them (caller waits for a retire — never a partial
        grant)."""
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._ref[p] = 1
        self.high_water = max(self.high_water, self.used_pages)
        return pages

    def share(self, pages: List[int]):
        """Add one holder to each (live) page — the prefix-hit grant."""
        for p in pages:
            if self._ref.get(p, 0) < 1:
                raise ValueError(
                    f"page {p} is not allocated — sharing a freed page "
                    f"would alias a future grant")
            self._ref[p] += 1

    def free(self, pages: List[int]) -> List[int]:
        """Release one holder per page; pages whose last holder left
        return to the free list.  Returns the PHYSICALLY freed pages
        (the engine drops their prefix-registry entries)."""
        freed: List[int] = []
        for p in pages:
            c = self._ref.get(p, 0)
            if c < 1:
                raise ValueError(f"double free of page {p}")
            if c == 1:
                del self._ref[p]
                self._free.append(p)
                freed.append(p)
            else:
                self._ref[p] = c - 1
        return freed


def _tctx(trace_id, parent=None) -> Dict[str, str]:
    """Span-context attrs for a per-request trace record — empty when
    the request carries no trace id (tracing off, or an untraced
    caller), so untagged records stay exactly as small as before."""
    if trace_id is None:
        return {}
    out = {"trace": trace_id}
    if parent is not None:
        out["parent_span"] = parent
    return out


def _page_digest(prev: str, page_tokens: np.ndarray) -> str:
    """Chained content key: depth-d digest = sha1(depth-(d−1) digest ‖
    page d's int32 token bytes).  Chaining makes the whole registry
    walk O(pages) — hashing the full growing prefix at every depth
    would be O(pages²·page_size) sha1 bytes per admission attempt, on
    the engine thread, repeated while a starved head-of-line request
    waits.  Collisions are astronomically unlikely, and the registry
    verifies the stored page tokens on every hit anyway (module-level
    so tests can monkeypatch a colliding hash and pin the guard)."""
    return hashlib.sha1(
        prev.encode()
        + np.ascontiguousarray(page_tokens, np.int32).tobytes()
    ).hexdigest()


class PrefixRegistry:
    """Token-id-hash → physical-page map for FULL prompt-prefix pages.

    Entry at depth d maps the CHAINED digest of
    ``prompt[: (d+1)·page_size]`` (depth-d digest = sha1(depth-(d−1)
    digest ‖ page d's tokens) — same information as hashing the full
    prefix, at O(pages) total work) to the physical page holding
    positions [d·ps, (d+1)·ps) of that prefix — valid because KV
    content is a pure function of (token ids, absolute positions), and
    prefix pages are position-aligned by construction.  Entries are OWNING (cache semantics): the engine
    registers a request's prefix pages when its prefill completes and
    the registry takes one pool holder per newly-registered page — a
    warm prefix outlives the request that wrote it.  Later admits
    share entries (refcount++), and an entry dies two ways: the pool
    physically frees the page (``drop_page``), or the engine EVICTS it
    to un-starve admission (deepest-first; only pages whose sole
    holder is the registry).  Lookup walks depths 0, 1, ... and stops
    at the first miss (prefix property) or at the first stored-token
    mismatch (the hash-collision guard: a colliding digest degrades to
    a miss, never to serving another prompt's KV)."""

    def __init__(self, page_size: int):
        self.page_size = int(page_size)
        # (depth, chain digest) -> (physical page, THAT page's token
        # bytes).  Storing only the page's own tokens suffices: lookup
        # walks from depth 0, so when every ancestor's stored block
        # already matched, matching this block proves the full prefix
        # by induction — O(pages) storage and verification
        self._entries: Dict[Tuple[int, str], Tuple[int, bytes]] = {}
        self._by_page: Dict[int, Tuple[int, str]] = {}

    def __len__(self) -> int:
        return len(self._entries)

    def lookup(self, prompt: np.ndarray) -> List[int]:
        """Longest registered chain of the prompt's full pages —
        pages[d] holds positions [d·ps, (d+1)·ps)."""
        ps = self.page_size
        pages: List[int] = []
        digest = ""
        for depth in range(int(len(prompt)) // ps):
            block = np.ascontiguousarray(
                prompt[depth * ps: (depth + 1) * ps], np.int32)
            digest = _page_digest(digest, block)
            ent = self._entries.get((depth, digest))
            if ent is None or ent[1] != block.tobytes():
                break
            pages.append(ent[0])
        return pages

    def register(self, prompt: np.ndarray, pages: List[int]) -> List[int]:
        """Record a request's full prompt pages (pages[d] = physical
        page of depth d).  First writer wins per key; a page backs at
        most one entry.  Returns the NEWLY registered pages — the
        engine gives the registry one pool holder for exactly those."""
        ps = self.page_size
        fresh: List[int] = []
        digest = ""
        for depth, page in enumerate(pages):
            block = np.ascontiguousarray(
                prompt[depth * ps: (depth + 1) * ps], np.int32)
            digest = _page_digest(digest, block)
            key = (depth, digest)
            if key in self._entries or page in self._by_page:
                continue
            self._entries[key] = (page, block.tobytes())
            self._by_page[page] = key
            fresh.append(page)
        return fresh

    def pages_by_depth_desc(self) -> List[int]:
        """All registered pages, deepest entries first — the eviction
        scan order (evicting depth d+1 before d keeps every surviving
        chain contiguous from depth 0, which is all lookup can use)."""
        return [page for (depth, _), (page, _) in sorted(
            self._entries.items(), key=lambda kv: -kv[0][0])]

    def drop_page(self, page: int):
        """The pool physically freed this page — its content is about
        to be someone else's."""
        key = self._by_page.pop(page, None)
        if key is not None:
            self._entries.pop(key, None)


@dataclasses.dataclass
class _Slot:
    handle: _Handle
    tokens: List[int]                   # generated so far
    last_token: int                     # next decode step's input
    index: int                          # current sequence length
    phase: str = "decode"               # "prefill" until the prompt is in
    # paged mode:
    pages: Optional[List[int]] = None   # pool pages owned by this slot
    block_row: Optional[np.ndarray] = None  # [M] int32 page ids
    prompt_padded: Optional[np.ndarray] = None  # page-aligned prompt
    chunk_plan: Optional[List] = None   # [(start, len), ...]
    chunk_i: int = 0                    # next chunk to run


class ServeEngine:
    """Dynamic batcher over a :class:`~dtf_tpu.serve.decode.Decoder`.

    ``model`` is a TransformerLM (training configuration); ``params``
    its param pytree (from serve.bridge).  ``max_seq_len`` bounds
    prompt + generation per request and fixes the cache shapes.

    ``kv_page_size`` selects the paged KV cache (the default; 0/None =
    the contiguous per-slot layout).  ``kv_pool_pages`` sizes the
    shared pool in TOTAL pages incl. the scratch page (0/None = the
    full contiguous-equivalent reservation; size it down to provision
    for actual tokens in flight).  ``prefill_chunk`` is the chunked-
    prefill unit in tokens (multiple of the page size; 0 = whole
    prompts prefill as one page-aligned chunk; None = the default,
    4 pages).

    ``prefix_sharing`` (paged mode, default on) shares full
    prompt-prefix pages across requests via the refcounted pool +
    prefix registry (module docstring).  ``mesh`` selects
    tensor-parallel decode (paged mode; serve/decode.py Decoder).

    ``heartbeat`` (obs.watchdog.Heartbeat) is beaten once per ENGINE
    ITERATION with step = completed-request count — serving liveness
    for the launcher's hang watchdog and the router's health probe.
    Beating from the engine loop (not a side thread) is the point: a
    deadlocked engine thread stops beating, which is exactly the
    signal a health checker needs (the chatty-deadlock case a log- or
    thread-alive check misses).

    LOCK DISCIPLINE: ``_cond`` guards the submit-side state shared
    between client threads and the engine thread — declared in
    ``_GUARDED_BY`` and enforced statically by tools/dtflint (rule
    lock-guard).  NOT guarded, deliberately: ``_slots`` and ``_cache``
    are ENGINE-THREAD state (only ``_loop_body``/``_step``/``_admit``/
    ``_retire`` touch them — single-writer by construction), ``_stop``
    is a threading.Event, and ``completed`` is append-only from the
    engine thread with len() reads elsewhere (GIL-atomic)."""

    _GUARDED_BY = {
        "_pending": "_cond", "_draining": "_cond",
        "_ewma_latency": "_cond",
    }

    def __init__(self, model, params, *, max_batch: int = 8,
                 max_seq_len: Optional[int] = None,
                 max_delay_s: float = 0.005, queue_size: int = 64,
                 seed: int = 0, kv_page_size: Optional[int] = 16,
                 kv_pool_pages: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 prefix_sharing: bool = True, mesh=None,
                 heartbeat=None):
        if max_batch < 1 or queue_size < 1:
            raise ValueError("max_batch and queue_size must be >= 1")
        self.max_batch = int(max_batch)
        self.max_seq_len = int(max_seq_len or model.max_seq_len)
        self.max_delay_s = float(max_delay_s)
        self.queue_size = int(queue_size)
        self.paged = bool(kv_page_size)
        # metrics registry must exist before the decoder: the MFU/cost
        # ledger (obs/ledger.py) exports through it, and the decoder
        # registers each compiled body's XLA flop/byte counts there
        self.metrics = MetricsRegistry()
        self.ledger = Ledger(self.metrics)
        if self.paged:
            self.page_size = int(kv_page_size)
            # None = default (4 pages — 64 tokens at the default page
            # size, and a page multiple at ANY page size); 0 = whole-
            # prompt single chunks
            self.prefill_chunk = (4 * self.page_size if prefill_chunk
                                  is None else int(prefill_chunk))
            if self.prefill_chunk and self.prefill_chunk % self.page_size:
                raise ValueError(
                    f"prefill_chunk ({self.prefill_chunk}) must be a "
                    f"multiple of kv_page_size ({self.page_size})")
            self.decoder = Decoder(
                model, params, num_slots=self.max_batch,
                max_seq_len=self.max_seq_len,
                kv_page_size=self.page_size,
                kv_pool_pages=(int(kv_pool_pages) if kv_pool_pages
                               else None), mesh=mesh,
                ledger=self.ledger)
            self.pool = PagePool(self.decoder.pool_pages)
            self.prefix_sharing = bool(prefix_sharing)
            self.registry = PrefixRegistry(self.page_size)
        else:
            if mesh is not None:
                raise ValueError("tensor-parallel serving needs the "
                                 "paged cache (kv_page_size > 0)")
            self.prefix_sharing = False
            self.registry = None
            # None is the only "unset" value — an explicit chunk size
            # (including 0) with the contiguous cache is a
            # contradiction, rejected loudly regardless of its value
            if kv_pool_pages or prefill_chunk is not None:
                raise ValueError("kv_pool_pages / prefill_chunk need the "
                                 "paged cache (kv_page_size > 0)")
            self.decoder = Decoder(model, params, num_slots=self.max_batch,
                                   max_seq_len=self.max_seq_len)
            self.pool = None
        self._cache = self.decoder.fresh_cache()
        # base for per-request sampling seeds (requests that arrive
        # without one): a pure function of (engine seed, request id),
        # so two same-seeded engines fed the same submission order
        # sample identically — replica-interchangeable even for
        # direct (router-less) callers
        self._seed = int(seed)

        self._cond = threading.Condition()
        self._pending: List[_Handle] = []
        self._slots: List[Optional[_Slot]] = [None] * self.max_batch
        self._stop = threading.Event()
        self._draining = False
        self._ids = itertools.count()
        # metrics: the raw result list stays (collect_stats consumes
        # it); live operational state goes through the obs registry —
        # queue depth / slot occupancy gauges, shed/admit/complete
        # counters, latency histogram — so benches and the benchmark
        # file logger read one API instead of scraping log lines
        self.completed: List[ServeResult] = []
        self._m_queue_depth = self.metrics.gauge("serve_queue_depth",
                                                 unit="requests")
        self._m_occupancy = self.metrics.gauge("serve_slot_occupancy",
                                               unit="fraction")
        self._m_shed = self.metrics.counter("serve_shed_total",
                                            unit="requests")
        self._m_admitted = self.metrics.counter("serve_admitted_total",
                                                unit="requests")
        self._m_completed = self.metrics.counter("serve_completed_total",
                                                 unit="requests")
        self._m_latency = self.metrics.histogram("serve_latency_s", unit="s")
        self._m_queue_wait = self.metrics.histogram("serve_queue_wait_s",
                                                    unit="s")
        # per-engine-iteration samples of the same two signals, so a
        # finished run still has a distribution (the gauges only hold
        # the final — drained — values)
        self._m_queue_sampled = self.metrics.histogram(
            "serve_queue_depth_sampled", unit="requests")
        self._m_occ_sampled = self.metrics.histogram(
            "serve_slot_occupancy_sampled", unit="fraction")
        # paged-cache operational signals: pool occupancy (gauge + per-
        # iteration samples), prefill chunks run, and the decode-step
        # GAP — wall time between consecutive decode steps while slots
        # are decoding.  The gap p99 is the head-of-line-blocking
        # number chunked prefill exists to bound (bench_serve.py reads
        # it for the chunked vs un-chunked comparison).
        self._m_pages_used = self.metrics.gauge("serve_kv_pages_used",
                                                unit="pages")
        self._m_pages_sampled = self.metrics.histogram(
            "serve_kv_pages_used_sampled", unit="pages")
        self._m_prefill_chunks = self.metrics.counter(
            "serve_prefill_chunks_total", unit="chunks")
        self._m_decode_gap = self.metrics.histogram("serve_decode_gap_s",
                                                    unit="s")
        # per-axis decode metrics: the mesh's tensor-parallel ways and
        # the decode-step time distribution — tokens/s-per-chip and
        # TP-scaling come straight from these two
        self._m_tp_ways = self.metrics.gauge("serve_tp_ways", unit="ways")
        self._m_tp_ways.set(getattr(self.decoder, "tp", 1))
        self._m_step_time = self.metrics.histogram("serve_decode_step_s",
                                                   unit="s")
        # prefix sharing: pages shared instead of allocated, COW
        # copies, and the live shared-holder count
        self._m_prefix_hits = self.metrics.counter(
            "serve_prefix_hit_pages_total", unit="pages")
        self._m_cow = self.metrics.counter("serve_prefix_cow_total",
                                           unit="pages")
        self._m_evicted = self.metrics.counter(
            "serve_prefix_evicted_total", unit="pages")
        self._m_shared = self.metrics.gauge("serve_kv_pages_shared_refs",
                                            unit="refs")
        # streaming: engine-emit → consumer-receive delay per token
        self._m_stream_lag = self.metrics.histogram("serve_stream_lag_s",
                                                    unit="s")
        # KV-page migration (serve/migrate.py): pages shipped out /
        # pulled in over the replica wire, live migration holds (pages
        # pinned above eviction while a transfer is in flight), and
        # torn transfers caught by the payload digest
        self._m_pages_exported = self.metrics.counter(
            "serve_pages_exported_total", unit="pages")
        self._m_pages_imported = self.metrics.counter(
            "serve_pages_imported_total", unit="pages")
        self._m_mig_holds = self.metrics.gauge("serve_migration_holds",
                                               unit="pages")
        self._m_mig_torn = self.metrics.counter(
            "serve_migration_torn_total", unit="pages")
        # migration jobs: wire threads enqueue closures here; the
        # engine thread drains the queue once per iteration, so every
        # pool/registry/_cache touch stays single-writer (the queue is
        # a thread-safe queue.Queue — not _cond-guarded state)
        self._mig_q: "queue_mod.Queue" = queue_mod.Queue()
        self._mig_hold_pages = 0        # engine-thread only
        # cancellation: requests whose caller stopped wanting the
        # answer (deadline-exceeded, failed-over, losing hedge) —
        # each one freed a slot + pages that would otherwise decode
        # a full budget into the stale-discard bin
        self._m_cancelled = self.metrics.counter("serve_cancelled_total",
                                                 unit="requests")
        self._heartbeat = heartbeat
        self._last_step_t: Optional[float] = None
        self._prefill_rr = -1           # round-robin cursor (chunk sched)
        self.max_concurrent = 0         # peak simultaneously-active slots
        self._ewma_latency = 0.25       # seed estimate for retry_after
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-engine")
        self._thread.start()

    @property
    def shed_count(self) -> int:
        """Total requests shed (single source of truth: the registry
        counter the benchmark export reads)."""
        return self._m_shed.value

    @property
    def outstanding(self) -> int:
        """Queued + in-flight requests — the load number a router's
        least-loaded placement and a replica's stats report expose."""
        with self._cond:
            return (len(self._pending)
                    + sum(s is not None for s in self._slots))

    def reset_measurement(self) -> int:
        """Zero the peak/distribution measurement state (decode-gap
        histogram, peak concurrency, pool high-water) under the engine
        lock, and return the current completed-request count — the
        slice point for post-warmup stats.  Benches call this after
        their warmup traffic drains so compile time and idle spans
        don't masquerade as serving behavior; holding ``_cond`` keeps
        the reset from racing the engine thread's own peak updates."""
        with self._cond:
            self._m_decode_gap.reset()
            self._last_step_t = None
            self.max_concurrent = 0
            if self.pool is not None:
                self.pool.high_water = self.pool.used_pages
            return len(self.completed)

    # -- KV-page migration surface (serve/migrate.py) ------------------
    # Every entry point below MARSHALS its work onto the engine thread
    # (run_on_engine): the pool, registry and cache are single-writer
    # engine-thread state, and migration must serialize with admission,
    # eviction and retire — not race them.  Wire threads block on the
    # job's completion; the engine loop drains the job queue once per
    # iteration (≤0.1s latency when idle).

    def _run_migration_jobs(self):
        """Engine thread: run queued migration closures."""
        while True:
            try:
                fn, box, ev = self._mig_q.get_nowait()
            except queue_mod.Empty:
                return
            try:
                box["result"] = fn()
            except Exception as e:  # noqa: BLE001 — the error belongs
                # to the waiting wire thread, never the engine loop
                box["error"] = e
            ev.set()

    def run_on_engine(self, fn, timeout: float = 60.0):
        """Run ``fn()`` on the engine thread; return its result (or
        re-raise its exception) in the calling thread.  Deadlocks by
        construction if called FROM the engine thread — callers are
        wire/client threads only."""
        if self._stop.is_set():
            raise RuntimeError("engine is stopped")
        box: dict = {}
        ev = threading.Event()
        self._mig_q.put((fn, box, ev))
        with self._cond:
            self._cond.notify_all()      # wake an idle engine loop
        if not ev.wait(timeout):
            raise TimeoutError(f"engine job not run in {timeout}s")
        if "error" in box:
            raise box["error"]
        return box.get("result")

    def _chain_digests(self, prompt: np.ndarray, depths: int) -> List[str]:
        ps = self.page_size
        out: List[str] = []
        digest = ""
        for d in range(depths):
            digest = _page_digest(digest, prompt[d * ps:(d + 1) * ps])
            out.append(digest)
        return out

    def export_chain_begin(self, prompt) -> Tuple[List[int], List[str]]:
        """Look up the registry's verified page chain for ``prompt``
        and take a MIGRATION HOLD on it (one extra pool holder per
        page).  Held pages have refcount ≥ 2, which puts them above
        ``_evict_for``'s refcount-1 bar — an in-transfer page can never
        be evicted, by construction, not by bookkeeping.  Returns
        (pages, chained digests); release with
        :meth:`export_chain_end` (transfer done OR aborted — the hold
        must not outlive its transfer)."""
        if not self.paged or not self.prefix_sharing:
            return [], []
        prompt = np.asarray(prompt, np.int32).reshape(-1)

        def job():
            pages = self.registry.lookup(prompt)
            self.pool.share(pages)
            self._mig_hold_pages += len(pages)
            self._m_mig_holds.set(self._mig_hold_pages)
            return pages, self._chain_digests(prompt, len(pages))

        return self.run_on_engine(job)

    def export_chain_read(self, pages: List[int], lo: int, n: int):
        """Host payloads (decoder leaf lists) for ``pages[lo:lo+n]`` —
        one bounded window of an in-flight transfer.  The caller must
        hold the chain (export_chain_begin): the window read trusts
        that the physical pages still carry the chain's KV."""
        def job():
            out = [self.decoder.read_page(self._cache, p)
                   for p in pages[lo:lo + n]]
            self._m_pages_exported.inc(len(out))
            return out

        return self.run_on_engine(job)

    def export_chain_end(self, pages: List[int]) -> None:
        """Drop the migration hold (transfer complete or aborted)."""
        if not pages:
            return

        def job():
            for p in self.pool.free(pages):
                self.registry.drop_page(p)
            self._mig_hold_pages -= len(pages)
            self._m_mig_holds.set(self._mig_hold_pages)

        self.run_on_engine(job)

    def import_chain(self, prompt, payloads) -> int:
        """Write a fetched page chain (``payloads[d]`` = decoder leaf
        list for depth d, verified by the caller) into the local pool
        and register it, so the next admit of this prompt prefix
        SHARES the migrated pages instead of prefilling.  Depths the
        local registry already holds are skipped.  Ownership
        transfers: the fresh pages' alloc holder becomes the
        registry's holder — after import the pages are ordinary warm
        registry pages (refcount 1, evictable under pressure).
        Returns the number of pages imported."""
        if not self.paged or not self.prefix_sharing:
            raise RuntimeError("page import needs the paged cache with "
                               "prefix sharing on")
        prompt = np.asarray(prompt, np.int32).reshape(-1)

        def job():
            existing = self.registry.lookup(prompt)
            todo = payloads[len(existing):]
            if not todo:
                return 0
            need = len(todo)
            pages = self.pool.alloc(need)
            if pages is None:
                self._evict_for(need)
                pages = self.pool.alloc(need)
            if pages is None:
                raise RuntimeError(
                    f"import starved: {need} pages needed, "
                    f"{self.pool.free_pages} free")
            for page, leaves in zip(pages, todo):
                self._cache = self.decoder.write_page(self._cache, page,
                                                      leaves)
            fresh = self.registry.register(prompt, existing + pages)
            # pages the registry refused (key raced in / collision
            # guard) go straight back — nothing may own an
            # unregistered imported page
            stray = [p for p in pages if p not in fresh]
            for p in self.pool.free(stray):
                self.registry.drop_page(p)
            self._m_pages_imported.inc(len(fresh))
            return len(fresh)

        return self.run_on_engine(job)

    # -- client side ---------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 32,
               temperature: float = 0.0,
               eos_id: Optional[int] = None,
               on_token: Optional[Callable] = None,
               trace_id: Optional[str] = None,
               trace_parent: Optional[str] = None,
               rng_seed: Optional[int] = None) -> _Handle:
        """Enqueue a request.  ``on_token`` is an optional per-token
        callback invoked FROM THE ENGINE THREAD as each token retires
        (keep it cheap — it sits on the decode path); the returned
        handle's ``stream()`` is the pull-based alternative.

        ``trace_id``/``trace_parent`` carry the distributed span
        context: the router mints a trace id per client request and
        sends it over the replica wire; a direct caller may pass its
        own.  When tracing is on and no id arrives, the engine mints
        one, so every request's lifecycle records (submit → admit →
        prefill chunks → decode steps → retire) share one id.

        ``rng_seed`` pins the request's SAMPLING identity: every
        sampled token is fold_in(key(rng_seed), position) — so a
        failover replay with the same seed (the router re-ships it)
        is token-exact.  None = derived from (engine seed, request
        id)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{max_new_tokens}")
        total = int(prompt.size) + int(max_new_tokens)
        if total > self.max_seq_len:
            raise ValueError(
                f"oversized request: prompt ({prompt.size}) + "
                f"max_new_tokens ({max_new_tokens}) = {total} exceeds "
                f"max_seq_len {self.max_seq_len}; shorten the prompt or "
                f"lower the budget")
        if self.paged:
            need = -(-total // self.page_size)
            if need > self.pool.usable_pages:
                raise ValueError(
                    f"oversized request for the page pool: needs {need} "
                    f"pages of {self.page_size} tokens but the pool has "
                    f"{self.pool.usable_pages} usable — it could never "
                    f"be admitted; grow --kv_pool_pages or shrink the "
                    f"request")
        if trace_id is None and trace.enabled():
            trace_id = trace.new_trace_id()
        req = ServeRequest(prompt=prompt, max_new_tokens=int(max_new_tokens),
                           temperature=float(temperature), eos_id=eos_id,
                           trace_id=trace_id, trace_parent=trace_parent,
                           rng_seed=(None if rng_seed is None
                                     else int(rng_seed)))
        handle = _Handle(req, on_token=on_token,
                        stream_lag_hist=self._m_stream_lag,
                        cond=self._cond)
        with self._cond:
            # checked under the lock: a submit racing stop() must either
            # land in _pending BEFORE the stop (and get drained or
            # cancelled there) or raise here — never enqueue onto a
            # stopped engine, where nothing would ever deliver it
            if self._stop.is_set():
                raise RuntimeError("engine is stopped")
            if self._draining:
                # SIGTERM drain: admissions stop the moment the signal
                # lands; already-queued + in-flight work still finishes.
                # Shed, not error — the client retries against another
                # replica after retry_after, exactly like a full queue
                self._m_shed.inc()
                retry = max(0.05, self._ewma_latency)
                log.warning("serve: draining — shedding request "
                            "(retry_after=%.2fs)", retry)
                trace.anomaly("serve_shed", reason="draining",
                              shed_total=self.shed_count,
                              retry_after=retry,
                              **_tctx(trace_id, trace_parent))
                raise Backpressure(retry)
            if len(self._pending) >= self.queue_size:
                self._m_shed.inc()
                retry = max(0.05, self._ewma_latency
                            * (1 + len(self._pending) / self.max_batch))
                log.error(
                    "serve: queue full (%d pending, %d slots) — shedding "
                    "request (%d total shed); retry_after=%.2fs",
                    len(self._pending), self.max_batch, self.shed_count,
                    retry)
                trace.anomaly("serve_shed", pending=len(self._pending),
                              shed_total=self.shed_count,
                              retry_after=retry,
                              **_tctx(trace_id, trace_parent))
                raise Backpressure(retry)
            req.id = next(self._ids)
            req.submit_time = time.time()
            if req.rng_seed is None:
                # deterministic per (engine seed, request id); bounded
                # to 31 bits so the wire carries a plain JSON int
                req.rng_seed = (self._seed * 1_000_003 + req.id
                                + 12_345) & 0x7FFFFFFF
            self._pending.append(handle)
            self._m_queue_depth.set(len(self._pending))
            if trace_id is not None:
                trace.event("serve_submit", request=req.id,
                            prompt_len=int(prompt.size),
                            queue_depth=len(self._pending),
                            **_tctx(trace_id, trace_parent))
            self._cond.notify_all()
        return handle

    def generate(self, prompt, **kw) -> ServeResult:
        """Blocking convenience: submit + wait."""
        return self.submit(prompt, **kw).result(timeout=600)

    # -- engine thread -------------------------------------------------
    def _drain_migration_jobs(self):
        """Engine exit: fail queued migration jobs instead of leaving
        their wire threads to time out against a dead loop."""
        while True:
            try:
                _, box, ev = self._mig_q.get_nowait()
            except queue_mod.Empty:
                return
            box["error"] = RuntimeError("engine is stopped")
            ev.set()

    def _loop(self):
        try:
            self._loop_body()
            self._drain_migration_jobs()
        except Exception:
            # a dead engine thread must not strand clients blocked in
            # result(): fail loudly and deliver cancellations
            log.exception("serve engine thread died — cancelling all "
                          "in-flight and queued requests")
            with self._cond:
                self._stop.set()
                stranded = ([s.handle for s in self._slots
                             if s is not None] + list(self._pending))
                self._slots = [None] * self.max_batch
                self._pending.clear()
            for handle in stranded:
                req = handle.request
                handle._deliver(ServeResult(
                    request_id=req.id, tokens=[], prompt_len=0,
                    queue_wait_s=0.0, time_to_first_token_s=0.0,
                    latency_s=0.0, cancelled=True))
            self._drain_migration_jobs()

    def _loop_body(self):
        while True:
            if self._heartbeat is not None:
                # serving liveness: the beat interval gate is inside
                # beat(), so this is one clock read per iteration
                self._heartbeat.beat(step=self._m_completed.value)
            # migration jobs run HERE, on the engine thread, between
            # iterations: exports/imports touch the pool, registry and
            # cache, which are single-writer engine-thread state — a
            # wire thread mutating them directly would race _retire
            self._run_migration_jobs()
            with self._cond:
                # cancellation sweep (queued half): a cancelled request
                # that never reached a slot resolves right here —
                # before it can cost an admission's pages
                cancelled_pending = [h for h in self._pending
                                     if h._cancel.is_set()]
                for handle in cancelled_pending:
                    self._pending.remove(handle)
                    self._finish_cancelled(handle)
                if cancelled_pending:
                    # the idle branch below may wait before the normal
                    # gauge refresh runs — a cancelled-empty queue must
                    # not report phantom depth in the meantime
                    self._m_queue_depth.set(len(self._pending))
                active = any(s is not None for s in self._slots)
                if not self._pending and not active:
                    if self._stop.is_set():
                        return
                    # idle: the next decode step's gap would span this
                    # wait, which is queue emptiness, not head-of-line
                    # blocking — don't let it poison the gap histogram
                    self._last_step_t = None
                    # empty queue: sleep until a submit (or stop) pokes us
                    self._cond.wait(timeout=0.1)
                    continue
                if not active and self._pending and self.max_delay_s > 0:
                    # fresh batch: hold the door up to max_delay after the
                    # FIRST pending arrival so the batch can fill
                    first = self._pending[0].request.submit_time
                    while (len(self._pending) < self.max_batch
                           and not self._stop.is_set()):
                        remaining = first + self.max_delay_s - time.time()
                        if remaining <= 0:
                            break
                        self._cond.wait(timeout=remaining)
                admitted = []
                for i, slot in enumerate(self._slots):
                    if slot is None and self._pending:
                        grant = None
                        if self.paged:
                            req = self._pending[0].request
                            shared, need, cow = self._admission_plan(req)
                            # hold the shared pages BEFORE any alloc/
                            # eviction: a registry-only page this admit
                            # is about to share must not be evicted out
                            # from under it
                            self.pool.share(shared)
                            pages = self.pool.alloc(need)
                            if pages is None:
                                self._evict_for(need)
                                pages = self.pool.alloc(need)
                            if pages is None:
                                # head-of-line FIFO wait: the next
                                # retire frees pages; small requests do
                                # NOT slip past a starved big one.
                                # Un-hold the speculative shares (the
                                # registry's own holder keeps them
                                # warm for the retry)
                                for p in self.pool.free(shared):
                                    self.registry.drop_page(p)
                                break
                            if shared:
                                self._m_prefix_hits.inc(len(shared))
                            grant = (pages, shared, cow)
                        admitted.append((i, self._pending.pop(0), grant))
                pending_depth = len(self._pending)
                self._m_queue_depth.set(pending_depth)
            if self._stop.is_set() and not any(
                    s is not None for s in self._slots) and not admitted:
                return
            if admitted:
                # batch formation: bind each admitted request to its
                # slot (contiguous: full prefill here; paged: allocate +
                # plan chunks, prefill advances below — interleaved).
                # The span carries the admitted requests' trace ids so
                # `trace_main --request` finds the batch work a request
                # rode in (a batch span serves MANY requests — a list,
                # not a single ambient context)
                attrs = {"admitted": len(admitted)}
                if trace.enabled():
                    tids = [h.request.trace_id for _, h, _ in admitted
                            if h.request.trace_id]
                    if tids:
                        attrs["traces"] = tids
                with trace.span("serve_batch_form", **attrs):
                    for i, handle, pages in admitted:
                        self._admit(i, handle, pages)
                self._m_admitted.inc(len(admitted))
            # cancellation sweep (running half): a cancelled slot
            # retires NOW — pages back to the pool, the slot to the
            # next queued request — instead of decoding out its budget
            # into the stale-discard bin (slots are engine-thread
            # state; no lock needed)
            for i, s in enumerate(self._slots):
                if s is not None and s.handle._cancel.is_set():
                    self._retire(i, cancelled=True)
            # chunked prefill: ONE chunk per iteration TOTAL (round-
            # robin across prefilling slots), so the gap running
            # decodes see is bounded by a single chunk's compute no
            # matter how many prompts are prefilling concurrently
            prefilling = [i for i, s in enumerate(self._slots)
                          if s is not None and s.phase == "prefill"]
            if prefilling:
                nxt = next((i for i in prefilling
                            if i > self._prefill_rr), prefilling[0])
                self._advance_prefill(nxt)
                self._prefill_rr = nxt
            active = sum(s is not None for s in self._slots)
            decoding = sum(s is not None and s.phase == "decode"
                           for s in self._slots)
            self.max_concurrent = max(self.max_concurrent, active)
            self._m_occupancy.set(active / self.max_batch)
            if self.paged:
                self._m_pages_used.set(self.pool.used_pages)
                self._m_shared.set(self.pool.shared_refs)
            if active:
                self._m_occ_sampled.observe(active / self.max_batch)
                # pending_depth was read under the lock above — the
                # list mutates under _cond, so len() here would race
                self._m_queue_sampled.observe(pending_depth)
                if self.paged:
                    self._m_pages_sampled.observe(self.pool.used_pages)
            if decoding:
                self._step()
            else:
                # no running decodes: the next decode-step gap is not a
                # head-of-line measurement
                self._last_step_t = None

    def _evict_for(self, need: int):
        """Free registry-only pages (deepest entries first) until
        ``need`` pages are free or nothing evictable remains — cached
        prefixes yield to live traffic, never the other way around.
        Pages any live slot still holds (refcount > 1) are skipped."""
        if not self.prefix_sharing:
            return
        for page in self.registry.pages_by_depth_desc():
            if self.pool.free_pages >= need:
                return
            if self.pool.refcount(page) == 1:
                for p in self.pool.free([page]):
                    self.registry.drop_page(p)
                self._m_evicted.inc()

    def _pages_needed(self, req: ServeRequest) -> int:
        """Worst-case pages for a request: prompt + full budget.
        Reserving up front means a decode step can never OOM the pool
        mid-generation (no preemption machinery needed)."""
        total = int(req.prompt.size) + int(req.max_new_tokens)
        return -(-total // self.page_size)

    def _admission_plan(self, req: ServeRequest):
        """(shared pages, fresh pages needed, cow) for one request —
        engine thread, under the lock.

        ``shared``: the registry's longest verified chain of this
        prompt's full prefix pages.  ``cow`` is True when the chain
        covers the ENTIRE prompt (plen an exact page multiple, all its
        pages registered): the slot then skips prefill and re-decodes
        its last prompt token for the first-token logits — a write
        into the last shared page, which therefore needs a fresh
        copy-on-write target (+1 fresh page)."""
        total_pages = self._pages_needed(req)
        shared = (self.registry.lookup(req.prompt)
                  if self.prefix_sharing else [])
        cow = bool(shared) and len(shared) * self.page_size >= int(
            req.prompt.size)
        if cow and total_pages + 1 > self.pool.usable_pages:
            # the COW target makes physical demand total_pages + 1 —
            # past the submit guard's total_pages <= usable bound, so
            # a request sized exactly to the pool would LIVELOCK here
            # (its own share holds the chain above eviction's
            # refcount-1 bar).  Degrade: drop the chain's last page
            # and prefill it instead — demand is back to total_pages
            shared = shared[:-1]
            cow = False
        need = total_pages - len(shared) + (1 if cow else 0)
        return shared, need, cow

    def _chunk_plan(self, plen: int, start: int = 0):
        """[(start, len), ...] page-aligned chunks covering
        [start, plen) of the prompt (``start`` — the first position
        NOT covered by shared prefix pages — must be page-aligned).
        Full ``prefill_chunk``-token chunks, then one final chunk padded
        to the page size (so the final chunk always contains the last
        real prompt token — the sampled position).  prefill_chunk == 0:
        the whole remainder is one page-aligned chunk."""
        chunk = self.prefill_chunk or -(-(plen - start) //
                                        self.page_size) * self.page_size
        plan = []
        while plen - start > chunk:
            plan.append((start, chunk))
            start += chunk
        rem = plen - start
        plan.append((start, -(-rem // self.page_size) * self.page_size))
        return plan

    def _admit(self, slot_idx: int, handle: _Handle, grant):
        req = handle.request
        req.admit_time = time.time()
        if req.trace_id is not None:
            attrs = {}
            if self.paged and grant is not None:
                # prefix-share depth in TOKENS (pages are an engine
                # detail; the capacity simulator replays recorded hits
                # without knowing this engine's page size)
                attrs["shared_tokens"] = len(grant[1]) * self.page_size
            trace.event("serve_admit", request=req.id, slot=slot_idx,
                        queue_wait_s=req.admit_time - req.submit_time,
                        **attrs, **_tctx(req.trace_id, req.trace_parent))
        if not self.paged:
            tok, self._cache, _ = self.decoder.prefill(
                self._cache, req.prompt, slot_idx, req.temperature,
                seed=req.rng_seed)
            first = int(tok)
            req.first_token_time = time.time()
            slot = _Slot(handle=handle, tokens=[first], last_token=first,
                         index=int(req.prompt.size))
            self._slots[slot_idx] = slot
            handle._emit(first)
            if self._finished(slot):
                self._retire(slot_idx)
            return
        fresh, shared, cow = grant
        plen = int(req.prompt.size)
        ps = self.page_size
        fresh = list(fresh)
        shared = list(shared)
        if cow:
            # the whole prompt is a registered prefix: the slot's only
            # compute is re-decoding its last prompt token (for the
            # first-token logits), and that WRITES position plen−1 —
            # into the last shared page.  Copy-on-write: the write goes
            # to a fresh physical copy; the original stays pristine for
            # its other holders.
            src = shared.pop()
            dst = fresh.pop(0)
            self._cache = self.decoder.copy_page(self._cache, src, dst)
            self._m_cow.inc()
            for p in self.pool.free([src]):   # release our share
                self.registry.drop_page(p)
            logical = shared + [dst] + fresh
        else:
            logical = shared + fresh
        k = len(shared) + (1 if cow else 0)   # depths covered pre-prefill
        block_row = np.zeros((self.decoder.pages_per_slot,), np.int32)
        block_row[:len(logical)] = logical
        # pages this slot must RELEASE at retire: one holder per page
        # it sits on (shared pages decrement, fresh/COW pages free)
        owned = logical
        if cow:
            # no prefill: straight to decode, replaying the last
            # prompt token (its KV write lands in the COW page)
            slot = _Slot(handle=handle, tokens=[],
                         last_token=int(req.prompt[-1]), index=plen - 1,
                         pages=owned, block_row=block_row)
            self._slots[slot_idx] = slot
            return
        plan = self._chunk_plan(plen, start=k * ps)
        padded_len = plan[-1][0] + plan[-1][1]
        prompt_padded = np.zeros((padded_len,), np.int32)
        prompt_padded[:plen] = req.prompt
        self._slots[slot_idx] = _Slot(
            handle=handle, tokens=[], last_token=0, index=0,
            phase="prefill", pages=owned, block_row=block_row,
            prompt_padded=prompt_padded, chunk_plan=plan, chunk_i=0)

    def _advance_prefill(self, slot_idx: int):
        slot = self._slots[slot_idx]
        req = slot.handle.request
        start, clen = slot.chunk_plan[slot.chunk_i]
        is_last = slot.chunk_i == len(slot.chunk_plan) - 1
        plen = int(req.prompt.size)
        sample_pos = plen - 1 - start if is_last else 0
        t0 = time.perf_counter()
        pre_compiled = self.decoder.compiled_count
        with trace.span("serve_prefill_chunk", slot=slot_idx, start=start,
                        tokens=clen, last=is_last,
                        **_tctx(req.trace_id, req.trace_parent)):
            tok, self._cache, _ = self.decoder.prefill_chunk(
                self._cache, slot.prompt_padded[start:start + clen],
                slot.block_row, start, sample_pos, req.temperature,
                seed=req.rng_seed)
        self._m_prefill_chunks.inc()
        slot.chunk_i += 1
        if is_last:
            first = int(tok)
            # the int(tok) sync above makes this the one chunk whose
            # wall time spans a real device sync — the only honest
            # sample the MFU ledger takes for the chunk executable
            # (earlier chunks retire asynchronously; syncing them
            # would reintroduce the head-of-line gap chunking bounds).
            # A call that COMPILED is dropped: its wall is XLA, not
            # compute
            if self.decoder.compiled_count == pre_compiled:
                self.ledger.observe(f"serve_prefill_chunk_c{clen}",
                                    time.perf_counter() - t0)
            req.first_token_time = time.time()
            slot.tokens = [first]
            slot.last_token = first
            slot.index = plen
            slot.phase = "decode"
            if self.prefix_sharing and plen // self.page_size:
                # the slot's full prompt pages are now written and
                # immutable (decode writes land past the prompt) —
                # publish them so later admits with the same prefix
                # share instead of re-prefilling.  The registry takes
                # its own holder on each newly-registered page (cache
                # semantics: the prefix survives this request's
                # retire; eviction reclaims it under pool pressure)
                self.pool.share(self.registry.register(
                    req.prompt,
                    [int(p) for p in slot.block_row[: plen // self.page_size]]))
            slot.handle._emit(first)
            if self._finished(slot):
                self._retire(slot_idx)

    def _step(self):
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._m_decode_gap.observe(now - self._last_step_t)
        tokens = np.zeros((self.max_batch,), np.int32)
        index = np.zeros((self.max_batch,), np.int32)
        temps = np.zeros((self.max_batch,), np.float32)
        seeds = np.zeros((self.max_batch,), np.uint32)
        tables = None
        if self.paged:
            tables = np.zeros((self.max_batch,
                               self.decoder.pages_per_slot), np.int32)
        for i, s in enumerate(self._slots):
            if s is not None and s.phase == "decode":
                tokens[i] = s.last_token
                index[i] = s.index
                temps[i] = s.handle.request.temperature
                seeds[i] = s.handle.request.rng_seed
                if tables is not None:
                    # prefilling / empty rows keep all-zeros rows →
                    # their garbage goes to the scratch page
                    tables[i] = s.block_row
        attrs = {}
        if trace.enabled():
            tids = [s.handle.request.trace_id for s in self._slots
                    if s is not None and s.phase == "decode"
                    and s.handle.request.trace_id]
            if tids:
                attrs["traces"] = tids
        pre_compiled = self.decoder.compiled_count
        with trace.span("serve_decode", **attrs):
            out, self._cache, _ = self.decoder.decode_step(
                self._cache, tokens, index, temps, seeds=seeds,
                block_tables=tables)
            # dtflint: sync-point (the EOS/budget check needs the
            # sampled tokens on the host; the MFU ledger's
            # serve_decode_step wall time is honest BECAUSE this syncs)
            out = np.asarray(out)
        step_dt = time.perf_counter() - now
        self._m_step_time.observe(step_dt)
        # MFU ledger: np.asarray(out) above synced the step, so this
        # wall time is real device time, not async dispatch; the step
        # that COMPILED is dropped (its wall is XLA, not compute)
        if self.decoder.compiled_count == pre_compiled:
            self.ledger.observe("serve_decode_step", step_dt)
        # chaos slow_replica@replica<K>:<F>: stretch each decode step to
        # F× its measured time — the straggler-replica signature the
        # router's deadline + least-loaded placement must absorb.  A
        # None-check when chaos is off, like every probe.
        slow = chaos.slow_replica()
        if slow > 1.0:
            time.sleep((slow - 1.0) * step_dt)
        for i, s in enumerate(self._slots):
            if s is None or s.phase != "decode":
                continue
            tok = int(out[i])
            s.tokens.append(tok)
            s.last_token = tok
            s.index += 1
            req = s.handle.request
            if req.first_token_time == 0.0:
                # the COW fast path skips prefill entirely — its first
                # token comes out of this decode step
                req.first_token_time = time.time()
            s.handle._emit(tok)
            if self._finished(s):
                self._retire(i)
        self._last_step_t = time.perf_counter()

    @staticmethod
    def _finished(slot: _Slot) -> bool:
        req = slot.handle.request
        return (len(slot.tokens) >= req.max_new_tokens
                or (req.eos_id is not None
                    and slot.tokens[-1] == req.eos_id))

    def _finish_cancelled(self, handle: _Handle) -> None:
        """Resolve a cancelled request that never occupied a slot."""
        req = handle.request
        self._m_cancelled.inc()
        if req.trace_id is not None:
            trace.event("serve_cancelled", request=req.id, tokens=0,
                        queued=True,
                        **_tctx(req.trace_id, req.trace_parent))
        handle._deliver(ServeResult(
            request_id=req.id, tokens=[], prompt_len=int(req.prompt.size),
            queue_wait_s=0.0, time_to_first_token_s=0.0, latency_s=0.0,
            submit_time=req.submit_time, finish_time=time.time(),
            cancelled=True, trace_id=req.trace_id))

    def _retire(self, slot_idx: int, cancelled: bool = False):
        slot = self._slots[slot_idx]
        self._slots[slot_idx] = None
        if slot.pages:
            # reclaim: each page loses this slot's holder; pages whose
            # LAST holder left return to the free list, and their
            # prefix-registry entries die with them (the physical page
            # is about to hold someone else's KV)
            for p in self.pool.free(slot.pages):
                if self.registry is not None:
                    self.registry.drop_page(p)
        req = slot.handle.request
        req.finish_time = time.time()
        result = ServeResult(
            request_id=req.id,
            tokens=list(slot.tokens),
            prompt_len=int(req.prompt.size),
            queue_wait_s=req.admit_time - req.submit_time,
            # a slot cancelled mid-prefill never produced a first
            # token — 0.0, not (0.0 − epoch) ≈ −1.7e9
            time_to_first_token_s=(
                req.first_token_time - req.submit_time
                if req.first_token_time else 0.0),
            latency_s=req.finish_time - req.submit_time,
            submit_time=req.submit_time, finish_time=req.finish_time,
            cancelled=cancelled, trace_id=req.trace_id)
        if cancelled:
            # an abandoned answer, not a served one: the pages are
            # reclaimed above, but the request must not pollute the
            # latency/completion statistics real traffic is judged by
            self._m_cancelled.inc()
            if req.trace_id is not None:
                trace.event("serve_cancelled", request=req.id,
                            tokens=len(slot.tokens), queued=False,
                            **_tctx(req.trace_id, req.trace_parent))
            slot.handle._deliver(result)
            with self._cond:
                self._cond.notify_all()
            return
        if req.trace_id is not None:
            trace.event("serve_retire", request=req.id,
                        tokens=len(slot.tokens),
                        latency_s=result.latency_s,
                        **_tctx(req.trace_id, req.trace_parent))
        self._m_completed.inc()
        self._m_latency.observe(result.latency_s)
        self._m_queue_wait.observe(result.queue_wait_s)
        self.completed.append(result)
        slot.handle._deliver(result)
        with self._cond:
            # under the lock: submit's retry_after estimate reads it
            self._ewma_latency = (0.8 * self._ewma_latency
                                  + 0.2 * result.latency_s)
            self._cond.notify_all()

    # -- lifecycle -----------------------------------------------------
    @property
    def draining(self) -> bool:
        with self._cond:
            return self._draining

    def begin_drain(self) -> None:
        """Graceful-shutdown phase 1 (called from the SIGTERM handler,
        so it must be async-signal-tolerant: no blocking lock, no
        logging — the interrupted frame may already hold either lock).
        New submits shed with ``retry_after``; queued and in-flight
        requests keep decoding to completion.  Follow with
        ``stop(drain=True)`` to wait them out and join the engine
        thread — then exit 0: a drained process is a CLEAN exit, not a
        casualty."""
        # dtflint: disable=lock-guard (SIGTERM-handler path: taking
        # _cond here could deadlock against the interrupted frame; the
        # store is GIL-atomic and monotonic, readers see it at their
        # next lock acquisition)
        self._draining = True
        if self._cond.acquire(blocking=False):  # best-effort wake
            try:
                self._cond.notify_all()
            finally:
                self._cond.release()

    def stop(self, drain: bool = True, timeout: float = 60.0):
        """Stop the engine.  ``drain=True`` finishes in-flight AND
        already-queued work first; False cancels queued requests."""
        with self._cond:
            if not drain:
                for handle in self._pending:
                    req = handle.request
                    handle._deliver(ServeResult(
                        request_id=req.id, tokens=[], prompt_len=0,
                        queue_wait_s=0.0, time_to_first_token_s=0.0,
                        latency_s=0.0, cancelled=True))
                self._pending.clear()
            self._stop.set()
            self._cond.notify_all()
        self._thread.join(timeout=timeout)
        # MFU/cost summary into the trace stream (`trace_main --ledger`
        # reads these; the gauges stay live on engine.metrics)
        self.ledger.emit_summary()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop(drain=False)
