"""Dynamic batching engine: request queue → slot-based continuous
batching over the KV-cache decoder.

Serving traffic is many small requests arriving at random times;
accelerators want big fixed-shape batches.  The engine bridges the two
with the standard production recipe:

  admission control — ``submit`` validates size up front: a request
      whose prompt + budget cannot fit the cache is rejected loudly
      (ValueError) instead of being admitted and truncated silently.
  backpressure      — the queue is bounded.  A full queue sheds the
      request with :class:`Backpressure` carrying ``retry_after``
      (an EWMA-based estimate), and logs the shed — "loud shed":
      capacity problems must be visible, never silent latency.
  max-batch / max-delay — a fresh batch waits up to ``max_delay_s``
      after the first arrival to fill up to ``max_batch`` slots, then
      goes; once decoding, new arrivals join at any step boundary.
  continuous batching — the decode step always runs the full
      [num_slots, 1] shape (compiled exactly once); each slot carries
      its own ``cache_index``, so sequences of different lengths
      coexist, finish independently, and free their slot for the next
      queued request without draining the batch.

Single engine thread owns ALL device work (prefill, decode, sampling);
``submit`` only enqueues — so there is no cross-thread jit contention.
Each decode step syncs the sampled tokens to the host (the EOS/budget
check needs them); at CPU/test scale this is negligible, on a real TPU
serving stack the next optimization would be a lookahead pipeline.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import threading
import time
from typing import List, Optional

import jax
import numpy as np

from dtf_tpu.obs import trace
from dtf_tpu.obs.registry import MetricsRegistry
from dtf_tpu.serve.decode import Decoder

log = logging.getLogger("dtf_tpu")


class Backpressure(RuntimeError):
    """Request shed: the queue is full.  ``retry_after`` (seconds) is
    the engine's estimate of when capacity frees up."""

    def __init__(self, retry_after: float):
        super().__init__(
            f"serving queue full — shed; retry after {retry_after:.2f}s")
        self.retry_after = retry_after


@dataclasses.dataclass
class ServeRequest:
    prompt: np.ndarray                  # 1-D int32 token ids
    max_new_tokens: int = 32
    temperature: float = 0.0            # 0 = greedy
    eos_id: Optional[int] = None        # stop token (included in output)
    # filled by the engine
    id: int = -1
    submit_time: float = 0.0
    admit_time: float = 0.0
    first_token_time: float = 0.0
    finish_time: float = 0.0


@dataclasses.dataclass
class ServeResult:
    request_id: int
    tokens: List[int]                   # generated tokens (prompt excluded)
    prompt_len: int
    queue_wait_s: float
    time_to_first_token_s: float
    latency_s: float
    # absolute timestamps (time.time()), so metrics can reconstruct the
    # serving window across requests without trusting the caller
    submit_time: float = 0.0
    finish_time: float = 0.0
    cancelled: bool = False


class _Handle:
    """Future-lite returned by submit()."""

    def __init__(self, req: ServeRequest):
        self.request = req
        self._event = threading.Event()
        self._result: Optional[ServeResult] = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> ServeResult:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.request.id} not finished in {timeout}s")
        return self._result

    def _deliver(self, result: ServeResult):
        self._result = result
        self._event.set()


@dataclasses.dataclass
class _Slot:
    handle: _Handle
    tokens: List[int]                   # generated so far
    last_token: int                     # next decode step's input
    index: int                          # current sequence length


class ServeEngine:
    """Dynamic batcher over a :class:`~dtf_tpu.serve.decode.Decoder`.

    ``model`` is a TransformerLM (training configuration); ``params``
    its param pytree (from serve.bridge).  ``max_seq_len`` bounds
    prompt + generation per request and fixes the cache shapes."""

    def __init__(self, model, params, *, max_batch: int = 8,
                 max_seq_len: Optional[int] = None,
                 max_delay_s: float = 0.005, queue_size: int = 64,
                 seed: int = 0):
        if max_batch < 1 or queue_size < 1:
            raise ValueError("max_batch and queue_size must be >= 1")
        self.max_batch = int(max_batch)
        self.max_seq_len = int(max_seq_len or model.max_seq_len)
        self.max_delay_s = float(max_delay_s)
        self.queue_size = int(queue_size)
        self.decoder = Decoder(model, params, num_slots=self.max_batch,
                               max_seq_len=self.max_seq_len)
        self._cache = self.decoder.fresh_cache()
        self._key = jax.random.key(seed)

        self._cond = threading.Condition()
        self._pending: List[_Handle] = []
        self._slots: List[Optional[_Slot]] = [None] * self.max_batch
        self._stop = threading.Event()
        self._ids = itertools.count()
        # metrics: the raw result list stays (collect_stats consumes
        # it); live operational state goes through the obs registry —
        # queue depth / slot occupancy gauges, shed/admit/complete
        # counters, latency histogram — so benches and the benchmark
        # file logger read one API instead of scraping log lines
        self.completed: List[ServeResult] = []
        self.metrics = MetricsRegistry()
        self._m_queue_depth = self.metrics.gauge("serve_queue_depth",
                                                 unit="requests")
        self._m_occupancy = self.metrics.gauge("serve_slot_occupancy",
                                               unit="fraction")
        self._m_shed = self.metrics.counter("serve_shed_total",
                                            unit="requests")
        self._m_admitted = self.metrics.counter("serve_admitted_total",
                                                unit="requests")
        self._m_completed = self.metrics.counter("serve_completed_total",
                                                 unit="requests")
        self._m_latency = self.metrics.histogram("serve_latency_s", unit="s")
        self._m_queue_wait = self.metrics.histogram("serve_queue_wait_s",
                                                    unit="s")
        # per-engine-iteration samples of the same two signals, so a
        # finished run still has a distribution (the gauges only hold
        # the final — drained — values)
        self._m_queue_sampled = self.metrics.histogram(
            "serve_queue_depth_sampled", unit="requests")
        self._m_occ_sampled = self.metrics.histogram(
            "serve_slot_occupancy_sampled", unit="fraction")
        self._ewma_latency = 0.25       # seed estimate for retry_after
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-engine")
        self._thread.start()

    @property
    def shed_count(self) -> int:
        """Total requests shed (single source of truth: the registry
        counter the benchmark export reads)."""
        return self._m_shed.value

    # -- client side ---------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 32,
               temperature: float = 0.0,
               eos_id: Optional[int] = None) -> _Handle:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{max_new_tokens}")
        total = int(prompt.size) + int(max_new_tokens)
        if total > self.max_seq_len:
            raise ValueError(
                f"oversized request: prompt ({prompt.size}) + "
                f"max_new_tokens ({max_new_tokens}) = {total} exceeds "
                f"max_seq_len {self.max_seq_len}; shorten the prompt or "
                f"lower the budget")
        req = ServeRequest(prompt=prompt, max_new_tokens=int(max_new_tokens),
                           temperature=float(temperature), eos_id=eos_id)
        handle = _Handle(req)
        with self._cond:
            # checked under the lock: a submit racing stop() must either
            # land in _pending BEFORE the stop (and get drained or
            # cancelled there) or raise here — never enqueue onto a
            # stopped engine, where nothing would ever deliver it
            if self._stop.is_set():
                raise RuntimeError("engine is stopped")
            if len(self._pending) >= self.queue_size:
                self._m_shed.inc()
                retry = max(0.05, self._ewma_latency
                            * (1 + len(self._pending) / self.max_batch))
                log.error(
                    "serve: queue full (%d pending, %d slots) — shedding "
                    "request (%d total shed); retry_after=%.2fs",
                    len(self._pending), self.max_batch, self.shed_count,
                    retry)
                trace.anomaly("serve_shed", pending=len(self._pending),
                              shed_total=self.shed_count,
                              retry_after=retry)
                raise Backpressure(retry)
            req.id = next(self._ids)
            req.submit_time = time.time()
            self._pending.append(handle)
            self._m_queue_depth.set(len(self._pending))
            self._cond.notify_all()
        return handle

    def generate(self, prompt, **kw) -> ServeResult:
        """Blocking convenience: submit + wait."""
        return self.submit(prompt, **kw).result(timeout=600)

    # -- engine thread -------------------------------------------------
    def _loop(self):
        try:
            self._loop_body()
        except Exception:
            # a dead engine thread must not strand clients blocked in
            # result(): fail loudly and deliver cancellations
            log.exception("serve engine thread died — cancelling all "
                          "in-flight and queued requests")
            with self._cond:
                self._stop.set()
                stranded = ([s.handle for s in self._slots
                             if s is not None] + list(self._pending))
                self._slots = [None] * self.max_batch
                self._pending.clear()
            for handle in stranded:
                req = handle.request
                handle._deliver(ServeResult(
                    request_id=req.id, tokens=[], prompt_len=0,
                    queue_wait_s=0.0, time_to_first_token_s=0.0,
                    latency_s=0.0, cancelled=True))

    def _loop_body(self):
        while True:
            with self._cond:
                active = any(s is not None for s in self._slots)
                if not self._pending and not active:
                    if self._stop.is_set():
                        return
                    # empty queue: sleep until a submit (or stop) pokes us
                    self._cond.wait(timeout=0.1)
                    continue
                if not active and self._pending and self.max_delay_s > 0:
                    # fresh batch: hold the door up to max_delay after the
                    # FIRST pending arrival so the batch can fill
                    first = self._pending[0].request.submit_time
                    while (len(self._pending) < self.max_batch
                           and not self._stop.is_set()):
                        remaining = first + self.max_delay_s - time.time()
                        if remaining <= 0:
                            break
                        self._cond.wait(timeout=remaining)
                admitted = []
                for i, slot in enumerate(self._slots):
                    if slot is None and self._pending:
                        admitted.append((i, self._pending.pop(0)))
                self._m_queue_depth.set(len(self._pending))
            if self._stop.is_set() and not any(
                    s is not None for s in self._slots) and not admitted:
                return
            if admitted:
                # batch formation: prefill each admitted request into
                # its slot (the fill-the-batch phase of the recipe)
                with trace.span("serve_batch_form", admitted=len(admitted)):
                    for i, handle in admitted:
                        self._admit(i, handle)
                self._m_admitted.inc(len(admitted))
            active = sum(s is not None for s in self._slots)
            self._m_occupancy.set(active / self.max_batch)
            if active:
                self._m_occ_sampled.observe(active / self.max_batch)
                self._m_queue_sampled.observe(len(self._pending))
                self._step()

    def _admit(self, slot_idx: int, handle: _Handle):
        req = handle.request
        req.admit_time = time.time()
        self._key, sub = jax.random.split(self._key)
        tok, self._cache, _ = self.decoder.prefill(
            self._cache, req.prompt, slot_idx, req.temperature, sub)
        first = int(tok)
        req.first_token_time = time.time()
        slot = _Slot(handle=handle, tokens=[first], last_token=first,
                     index=int(req.prompt.size))
        self._slots[slot_idx] = slot
        if self._finished(slot):
            self._retire(slot_idx)

    def _step(self):
        tokens = np.zeros((self.max_batch,), np.int32)
        index = np.zeros((self.max_batch,), np.int32)
        temps = np.zeros((self.max_batch,), np.float32)
        for i, s in enumerate(self._slots):
            if s is not None:
                tokens[i] = s.last_token
                index[i] = s.index
                temps[i] = s.handle.request.temperature
        self._key, sub = jax.random.split(self._key)
        with trace.span("serve_decode"):
            out, self._cache, _ = self.decoder.decode_step(
                self._cache, tokens, index, temps, sub)
            out = np.asarray(out)
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            tok = int(out[i])
            s.tokens.append(tok)
            s.last_token = tok
            s.index += 1
            if self._finished(s):
                self._retire(i)

    @staticmethod
    def _finished(slot: _Slot) -> bool:
        req = slot.handle.request
        return (len(slot.tokens) >= req.max_new_tokens
                or (req.eos_id is not None
                    and slot.tokens[-1] == req.eos_id))

    def _retire(self, slot_idx: int):
        slot = self._slots[slot_idx]
        self._slots[slot_idx] = None
        req = slot.handle.request
        req.finish_time = time.time()
        result = ServeResult(
            request_id=req.id,
            tokens=list(slot.tokens),
            prompt_len=int(req.prompt.size),
            queue_wait_s=req.admit_time - req.submit_time,
            time_to_first_token_s=req.first_token_time - req.submit_time,
            latency_s=req.finish_time - req.submit_time,
            submit_time=req.submit_time, finish_time=req.finish_time)
        self._ewma_latency = (0.8 * self._ewma_latency
                              + 0.2 * result.latency_s)
        self._m_completed.inc()
        self._m_latency.observe(result.latency_s)
        self._m_queue_wait.observe(result.queue_wait_s)
        self.completed.append(result)
        slot.handle._deliver(result)
        with self._cond:
            self._cond.notify_all()

    # -- lifecycle -----------------------------------------------------
    def stop(self, drain: bool = True, timeout: float = 60.0):
        """Stop the engine.  ``drain=True`` finishes in-flight AND
        already-queued work first; False cancels queued requests."""
        with self._cond:
            if not drain:
                for handle in self._pending:
                    req = handle.request
                    handle._deliver(ServeResult(
                        request_id=req.id, tokens=[], prompt_len=0,
                        queue_wait_s=0.0, time_to_first_token_s=0.0,
                        latency_s=0.0, cancelled=True))
                self._pending.clear()
            self._stop.set()
            self._cond.notify_all()
        self._thread.join(timeout=timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop(drain=False)
