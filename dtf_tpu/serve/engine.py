"""Dynamic batching engine: request queue → slot-based continuous
batching over the KV-cache decoder.

Serving traffic is many small requests arriving at random times;
accelerators want big fixed-shape batches.  The engine bridges the two
with the standard production recipe:

  admission control — ``submit`` validates size up front: a request
      whose prompt + budget cannot fit the cache is rejected loudly
      (ValueError) instead of being admitted and truncated silently.
  backpressure      — the queue is bounded.  A full queue sheds the
      request with :class:`Backpressure` carrying ``retry_after``
      (an EWMA-based estimate), and logs the shed — "loud shed":
      capacity problems must be visible, never silent latency.
  max-batch / max-delay — a fresh batch waits up to ``max_delay_s``
      after the first arrival to fill up to ``max_batch`` slots, then
      goes; once decoding, new arrivals join at any step boundary.
  continuous batching — the decode step always runs the full
      [num_slots, 1] shape (compiled exactly once); each slot carries
      its own ``cache_index``, so sequences of different lengths
      coexist, finish independently, and free their slot for the next
      queued request without draining the batch.

With the PAGED KV cache (``kv_page_size``, the default) two more
production levers land:

  paged admission — HBM is a shared page pool (:class:`PagePool`), and
      a request is admitted when its worst-case page count
      (⌈(prompt + budget) / page_size⌉) is free — so concurrency is
      bounded by TOKENS IN FLIGHT, not num_slots × max_seq_len.  A
      pool sized at 50% of the contiguous reservation serves the same
      slot count whenever mean request length < 50% of max_seq_len.
      When the head of the queue cannot get pages it WAITS (FIFO —
      large requests are not starved by small ones slipping past);
      retiring slots free their pages for the next admit.
  chunked prefill — prompts prefill in ``prefill_chunk``-token
      page-aligned chunks, ONE chunk per engine iteration, with a
      decode step for running slots between chunks — a max-length
      prompt adds bounded (chunk-sized) gaps to running decodes
      instead of head-of-line-blocking them for the whole prompt.
      The first chunk of every prompt runs pure causal self-attention
      through the flash kernel (no cache gather at all), so short
      prompts — the common case — never touch the gather path.

Single engine thread owns ALL device work (prefill, decode, sampling);
``submit`` only enqueues — so there is no cross-thread jit contention.
Each decode step syncs the sampled tokens to the host (the EOS/budget
check needs them); at CPU/test scale this is negligible, on a real TPU
serving stack the next optimization would be a lookahead pipeline.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import threading
import time
from typing import List, Optional

import jax
import numpy as np

from dtf_tpu.obs import trace
from dtf_tpu.obs.registry import MetricsRegistry
from dtf_tpu.serve.decode import Decoder

log = logging.getLogger("dtf_tpu")


class Backpressure(RuntimeError):
    """Request shed: the queue is full.  ``retry_after`` (seconds) is
    the engine's estimate of when capacity frees up."""

    def __init__(self, retry_after: float):
        super().__init__(
            f"serving queue full — shed; retry after {retry_after:.2f}s")
        self.retry_after = retry_after


@dataclasses.dataclass
class ServeRequest:
    prompt: np.ndarray                  # 1-D int32 token ids
    max_new_tokens: int = 32
    temperature: float = 0.0            # 0 = greedy
    eos_id: Optional[int] = None        # stop token (included in output)
    # filled by the engine
    id: int = -1
    submit_time: float = 0.0
    admit_time: float = 0.0
    first_token_time: float = 0.0
    finish_time: float = 0.0


@dataclasses.dataclass
class ServeResult:
    request_id: int
    tokens: List[int]                   # generated tokens (prompt excluded)
    prompt_len: int
    queue_wait_s: float
    time_to_first_token_s: float
    latency_s: float
    # absolute timestamps (time.time()), so metrics can reconstruct the
    # serving window across requests without trusting the caller
    submit_time: float = 0.0
    finish_time: float = 0.0
    cancelled: bool = False


class _Handle:
    """Future-lite returned by submit()."""

    def __init__(self, req: ServeRequest):
        self.request = req
        self._event = threading.Event()
        self._result: Optional[ServeResult] = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> ServeResult:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.request.id} not finished in {timeout}s")
        return self._result

    def _deliver(self, result: ServeResult):
        self._result = result
        self._event.set()


class PagePool:
    """Host-side free-list allocator over the shared KV page pool.

    Page 0 is the SCRATCH page — never handed to a request.  Inactive
    rows of the fixed-shape decode batch carry all-zeros block-table
    rows, so their garbage writes/gathers land there and can never
    touch a live sequence (ops.paged_attention has the full invariant).
    ``high_water`` records the peak pages in use — the number that
    proves retired pages are actually reclaimed and reused."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError(f"page pool needs >= 2 pages (page 0 is "
                             f"scratch), got {num_pages}")
        self.num_pages = int(num_pages)
        # LIFO free stack: a just-retired request's pages go to the
        # next admit — maximally warm reuse, and the reclamation tests
        # can assert the high-water mark stays at the concurrent need
        self._free = list(range(self.num_pages - 1, 0, -1))
        self.high_water = 0

    @property
    def usable_pages(self) -> int:
        return self.num_pages - 1

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.usable_pages - len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """n pages, or None when the pool cannot cover them (caller
        waits for a retire — never a partial grant)."""
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        self.high_water = max(self.high_water, self.used_pages)
        return pages

    def free(self, pages: List[int]):
        self._free.extend(pages)


@dataclasses.dataclass
class _Slot:
    handle: _Handle
    tokens: List[int]                   # generated so far
    last_token: int                     # next decode step's input
    index: int                          # current sequence length
    phase: str = "decode"               # "prefill" until the prompt is in
    # paged mode:
    pages: Optional[List[int]] = None   # pool pages owned by this slot
    block_row: Optional[np.ndarray] = None  # [M] int32 page ids
    prompt_padded: Optional[np.ndarray] = None  # page-aligned prompt
    chunk_plan: Optional[List] = None   # [(start, len), ...]
    chunk_i: int = 0                    # next chunk to run


class ServeEngine:
    """Dynamic batcher over a :class:`~dtf_tpu.serve.decode.Decoder`.

    ``model`` is a TransformerLM (training configuration); ``params``
    its param pytree (from serve.bridge).  ``max_seq_len`` bounds
    prompt + generation per request and fixes the cache shapes.

    ``kv_page_size`` selects the paged KV cache (the default; 0/None =
    the contiguous per-slot layout).  ``kv_pool_pages`` sizes the
    shared pool in TOTAL pages incl. the scratch page (0/None = the
    full contiguous-equivalent reservation; size it down to provision
    for actual tokens in flight).  ``prefill_chunk`` is the chunked-
    prefill unit in tokens (multiple of the page size; 0 = whole
    prompts prefill as one page-aligned chunk; None = the default,
    4 pages)."""

    def __init__(self, model, params, *, max_batch: int = 8,
                 max_seq_len: Optional[int] = None,
                 max_delay_s: float = 0.005, queue_size: int = 64,
                 seed: int = 0, kv_page_size: Optional[int] = 16,
                 kv_pool_pages: Optional[int] = None,
                 prefill_chunk: Optional[int] = None):
        if max_batch < 1 or queue_size < 1:
            raise ValueError("max_batch and queue_size must be >= 1")
        self.max_batch = int(max_batch)
        self.max_seq_len = int(max_seq_len or model.max_seq_len)
        self.max_delay_s = float(max_delay_s)
        self.queue_size = int(queue_size)
        self.paged = bool(kv_page_size)
        if self.paged:
            self.page_size = int(kv_page_size)
            # None = default (4 pages — 64 tokens at the default page
            # size, and a page multiple at ANY page size); 0 = whole-
            # prompt single chunks
            self.prefill_chunk = (4 * self.page_size if prefill_chunk
                                  is None else int(prefill_chunk))
            if self.prefill_chunk and self.prefill_chunk % self.page_size:
                raise ValueError(
                    f"prefill_chunk ({self.prefill_chunk}) must be a "
                    f"multiple of kv_page_size ({self.page_size})")
            self.decoder = Decoder(
                model, params, num_slots=self.max_batch,
                max_seq_len=self.max_seq_len,
                kv_page_size=self.page_size,
                kv_pool_pages=(int(kv_pool_pages) if kv_pool_pages
                               else None))
            self.pool = PagePool(self.decoder.pool_pages)
        else:
            # None is the only "unset" value — an explicit chunk size
            # (including 0) with the contiguous cache is a
            # contradiction, rejected loudly regardless of its value
            if kv_pool_pages or prefill_chunk is not None:
                raise ValueError("kv_pool_pages / prefill_chunk need the "
                                 "paged cache (kv_page_size > 0)")
            self.decoder = Decoder(model, params, num_slots=self.max_batch,
                                   max_seq_len=self.max_seq_len)
            self.pool = None
        self._cache = self.decoder.fresh_cache()
        self._key = jax.random.key(seed)

        self._cond = threading.Condition()
        self._pending: List[_Handle] = []
        self._slots: List[Optional[_Slot]] = [None] * self.max_batch
        self._stop = threading.Event()
        self._draining = False
        self._ids = itertools.count()
        # metrics: the raw result list stays (collect_stats consumes
        # it); live operational state goes through the obs registry —
        # queue depth / slot occupancy gauges, shed/admit/complete
        # counters, latency histogram — so benches and the benchmark
        # file logger read one API instead of scraping log lines
        self.completed: List[ServeResult] = []
        self.metrics = MetricsRegistry()
        self._m_queue_depth = self.metrics.gauge("serve_queue_depth",
                                                 unit="requests")
        self._m_occupancy = self.metrics.gauge("serve_slot_occupancy",
                                               unit="fraction")
        self._m_shed = self.metrics.counter("serve_shed_total",
                                            unit="requests")
        self._m_admitted = self.metrics.counter("serve_admitted_total",
                                                unit="requests")
        self._m_completed = self.metrics.counter("serve_completed_total",
                                                 unit="requests")
        self._m_latency = self.metrics.histogram("serve_latency_s", unit="s")
        self._m_queue_wait = self.metrics.histogram("serve_queue_wait_s",
                                                    unit="s")
        # per-engine-iteration samples of the same two signals, so a
        # finished run still has a distribution (the gauges only hold
        # the final — drained — values)
        self._m_queue_sampled = self.metrics.histogram(
            "serve_queue_depth_sampled", unit="requests")
        self._m_occ_sampled = self.metrics.histogram(
            "serve_slot_occupancy_sampled", unit="fraction")
        # paged-cache operational signals: pool occupancy (gauge + per-
        # iteration samples), prefill chunks run, and the decode-step
        # GAP — wall time between consecutive decode steps while slots
        # are decoding.  The gap p99 is the head-of-line-blocking
        # number chunked prefill exists to bound (bench_serve.py reads
        # it for the chunked vs un-chunked comparison).
        self._m_pages_used = self.metrics.gauge("serve_kv_pages_used",
                                                unit="pages")
        self._m_pages_sampled = self.metrics.histogram(
            "serve_kv_pages_used_sampled", unit="pages")
        self._m_prefill_chunks = self.metrics.counter(
            "serve_prefill_chunks_total", unit="chunks")
        self._m_decode_gap = self.metrics.histogram("serve_decode_gap_s",
                                                    unit="s")
        self._last_step_t: Optional[float] = None
        self._prefill_rr = -1           # round-robin cursor (chunk sched)
        self.max_concurrent = 0         # peak simultaneously-active slots
        self._ewma_latency = 0.25       # seed estimate for retry_after
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-engine")
        self._thread.start()

    @property
    def shed_count(self) -> int:
        """Total requests shed (single source of truth: the registry
        counter the benchmark export reads)."""
        return self._m_shed.value

    def reset_measurement(self) -> int:
        """Zero the peak/distribution measurement state (decode-gap
        histogram, peak concurrency, pool high-water) under the engine
        lock, and return the current completed-request count — the
        slice point for post-warmup stats.  Benches call this after
        their warmup traffic drains so compile time and idle spans
        don't masquerade as serving behavior; holding ``_cond`` keeps
        the reset from racing the engine thread's own peak updates."""
        with self._cond:
            self._m_decode_gap.reset()
            self._last_step_t = None
            self.max_concurrent = 0
            if self.pool is not None:
                self.pool.high_water = self.pool.used_pages
            return len(self.completed)

    # -- client side ---------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 32,
               temperature: float = 0.0,
               eos_id: Optional[int] = None) -> _Handle:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{max_new_tokens}")
        total = int(prompt.size) + int(max_new_tokens)
        if total > self.max_seq_len:
            raise ValueError(
                f"oversized request: prompt ({prompt.size}) + "
                f"max_new_tokens ({max_new_tokens}) = {total} exceeds "
                f"max_seq_len {self.max_seq_len}; shorten the prompt or "
                f"lower the budget")
        if self.paged:
            need = -(-total // self.page_size)
            if need > self.pool.usable_pages:
                raise ValueError(
                    f"oversized request for the page pool: needs {need} "
                    f"pages of {self.page_size} tokens but the pool has "
                    f"{self.pool.usable_pages} usable — it could never "
                    f"be admitted; grow --kv_pool_pages or shrink the "
                    f"request")
        req = ServeRequest(prompt=prompt, max_new_tokens=int(max_new_tokens),
                           temperature=float(temperature), eos_id=eos_id)
        handle = _Handle(req)
        with self._cond:
            # checked under the lock: a submit racing stop() must either
            # land in _pending BEFORE the stop (and get drained or
            # cancelled there) or raise here — never enqueue onto a
            # stopped engine, where nothing would ever deliver it
            if self._stop.is_set():
                raise RuntimeError("engine is stopped")
            if self._draining:
                # SIGTERM drain: admissions stop the moment the signal
                # lands; already-queued + in-flight work still finishes.
                # Shed, not error — the client retries against another
                # replica after retry_after, exactly like a full queue
                self._m_shed.inc()
                retry = max(0.05, self._ewma_latency)
                log.warning("serve: draining — shedding request "
                            "(retry_after=%.2fs)", retry)
                trace.anomaly("serve_shed", reason="draining",
                              shed_total=self.shed_count,
                              retry_after=retry)
                raise Backpressure(retry)
            if len(self._pending) >= self.queue_size:
                self._m_shed.inc()
                retry = max(0.05, self._ewma_latency
                            * (1 + len(self._pending) / self.max_batch))
                log.error(
                    "serve: queue full (%d pending, %d slots) — shedding "
                    "request (%d total shed); retry_after=%.2fs",
                    len(self._pending), self.max_batch, self.shed_count,
                    retry)
                trace.anomaly("serve_shed", pending=len(self._pending),
                              shed_total=self.shed_count,
                              retry_after=retry)
                raise Backpressure(retry)
            req.id = next(self._ids)
            req.submit_time = time.time()
            self._pending.append(handle)
            self._m_queue_depth.set(len(self._pending))
            self._cond.notify_all()
        return handle

    def generate(self, prompt, **kw) -> ServeResult:
        """Blocking convenience: submit + wait."""
        return self.submit(prompt, **kw).result(timeout=600)

    # -- engine thread -------------------------------------------------
    def _loop(self):
        try:
            self._loop_body()
        except Exception:
            # a dead engine thread must not strand clients blocked in
            # result(): fail loudly and deliver cancellations
            log.exception("serve engine thread died — cancelling all "
                          "in-flight and queued requests")
            with self._cond:
                self._stop.set()
                stranded = ([s.handle for s in self._slots
                             if s is not None] + list(self._pending))
                self._slots = [None] * self.max_batch
                self._pending.clear()
            for handle in stranded:
                req = handle.request
                handle._deliver(ServeResult(
                    request_id=req.id, tokens=[], prompt_len=0,
                    queue_wait_s=0.0, time_to_first_token_s=0.0,
                    latency_s=0.0, cancelled=True))

    def _loop_body(self):
        while True:
            with self._cond:
                active = any(s is not None for s in self._slots)
                if not self._pending and not active:
                    if self._stop.is_set():
                        return
                    # idle: the next decode step's gap would span this
                    # wait, which is queue emptiness, not head-of-line
                    # blocking — don't let it poison the gap histogram
                    self._last_step_t = None
                    # empty queue: sleep until a submit (or stop) pokes us
                    self._cond.wait(timeout=0.1)
                    continue
                if not active and self._pending and self.max_delay_s > 0:
                    # fresh batch: hold the door up to max_delay after the
                    # FIRST pending arrival so the batch can fill
                    first = self._pending[0].request.submit_time
                    while (len(self._pending) < self.max_batch
                           and not self._stop.is_set()):
                        remaining = first + self.max_delay_s - time.time()
                        if remaining <= 0:
                            break
                        self._cond.wait(timeout=remaining)
                admitted = []
                for i, slot in enumerate(self._slots):
                    if slot is None and self._pending:
                        pages = None
                        if self.paged:
                            req = self._pending[0].request
                            need = self._pages_needed(req)
                            pages = self.pool.alloc(need)
                            if pages is None:
                                # head-of-line FIFO wait: the next
                                # retire frees pages; small requests do
                                # NOT slip past a starved big one
                                break
                        admitted.append((i, self._pending.pop(0), pages))
                self._m_queue_depth.set(len(self._pending))
            if self._stop.is_set() and not any(
                    s is not None for s in self._slots) and not admitted:
                return
            if admitted:
                # batch formation: bind each admitted request to its
                # slot (contiguous: full prefill here; paged: allocate +
                # plan chunks, prefill advances below — interleaved)
                with trace.span("serve_batch_form", admitted=len(admitted)):
                    for i, handle, pages in admitted:
                        self._admit(i, handle, pages)
                self._m_admitted.inc(len(admitted))
            # chunked prefill: ONE chunk per iteration TOTAL (round-
            # robin across prefilling slots), so the gap running
            # decodes see is bounded by a single chunk's compute no
            # matter how many prompts are prefilling concurrently
            prefilling = [i for i, s in enumerate(self._slots)
                          if s is not None and s.phase == "prefill"]
            if prefilling:
                nxt = next((i for i in prefilling
                            if i > self._prefill_rr), prefilling[0])
                self._advance_prefill(nxt)
                self._prefill_rr = nxt
            active = sum(s is not None for s in self._slots)
            decoding = sum(s is not None and s.phase == "decode"
                           for s in self._slots)
            self.max_concurrent = max(self.max_concurrent, active)
            self._m_occupancy.set(active / self.max_batch)
            if self.paged:
                self._m_pages_used.set(self.pool.used_pages)
            if active:
                self._m_occ_sampled.observe(active / self.max_batch)
                self._m_queue_sampled.observe(len(self._pending))
                if self.paged:
                    self._m_pages_sampled.observe(self.pool.used_pages)
            if decoding:
                self._step()
            else:
                # no running decodes: the next decode-step gap is not a
                # head-of-line measurement
                self._last_step_t = None

    def _pages_needed(self, req: ServeRequest) -> int:
        """Worst-case pages for a request: prompt + full budget.
        Reserving up front means a decode step can never OOM the pool
        mid-generation (no preemption machinery needed)."""
        total = int(req.prompt.size) + int(req.max_new_tokens)
        return -(-total // self.page_size)

    def _chunk_plan(self, plen: int):
        """[(start, len), ...] page-aligned chunks covering the prompt.
        Full ``prefill_chunk``-token chunks, then one final chunk padded
        to the page size (so the final chunk always contains the last
        real prompt token — the sampled position).  prefill_chunk == 0:
        the whole prompt is one page-aligned chunk."""
        chunk = self.prefill_chunk or -(-plen // self.page_size) * \
            self.page_size
        plan, start = [], 0
        while plen - start > chunk:
            plan.append((start, chunk))
            start += chunk
        rem = plen - start
        plan.append((start, -(-rem // self.page_size) * self.page_size))
        return plan

    def _admit(self, slot_idx: int, handle: _Handle,
               pages: Optional[List[int]]):
        req = handle.request
        req.admit_time = time.time()
        if not self.paged:
            self._key, sub = jax.random.split(self._key)
            tok, self._cache, _ = self.decoder.prefill(
                self._cache, req.prompt, slot_idx, req.temperature, sub)
            first = int(tok)
            req.first_token_time = time.time()
            slot = _Slot(handle=handle, tokens=[first], last_token=first,
                         index=int(req.prompt.size))
            self._slots[slot_idx] = slot
            if self._finished(slot):
                self._retire(slot_idx)
            return
        plen = int(req.prompt.size)
        plan = self._chunk_plan(plen)
        padded_len = plan[-1][0] + plan[-1][1]
        prompt_padded = np.zeros((padded_len,), np.int32)
        prompt_padded[:plen] = req.prompt
        block_row = np.zeros((self.decoder.pages_per_slot,), np.int32)
        block_row[:len(pages)] = pages
        self._slots[slot_idx] = _Slot(
            handle=handle, tokens=[], last_token=0, index=0,
            phase="prefill", pages=pages, block_row=block_row,
            prompt_padded=prompt_padded, chunk_plan=plan, chunk_i=0)

    def _advance_prefill(self, slot_idx: int):
        slot = self._slots[slot_idx]
        req = slot.handle.request
        start, clen = slot.chunk_plan[slot.chunk_i]
        is_last = slot.chunk_i == len(slot.chunk_plan) - 1
        plen = int(req.prompt.size)
        sample_pos = plen - 1 - start if is_last else 0
        self._key, sub = jax.random.split(self._key)
        with trace.span("serve_prefill_chunk", slot=slot_idx, start=start,
                        tokens=clen, last=is_last):
            tok, self._cache, _ = self.decoder.prefill_chunk(
                self._cache, slot.prompt_padded[start:start + clen],
                slot.block_row, start, sample_pos, req.temperature, sub)
        self._m_prefill_chunks.inc()
        slot.chunk_i += 1
        if is_last:
            first = int(tok)
            req.first_token_time = time.time()
            slot.tokens = [first]
            slot.last_token = first
            slot.index = plen
            slot.phase = "decode"
            if self._finished(slot):
                self._retire(slot_idx)

    def _step(self):
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._m_decode_gap.observe(now - self._last_step_t)
        tokens = np.zeros((self.max_batch,), np.int32)
        index = np.zeros((self.max_batch,), np.int32)
        temps = np.zeros((self.max_batch,), np.float32)
        tables = None
        if self.paged:
            tables = np.zeros((self.max_batch,
                               self.decoder.pages_per_slot), np.int32)
        for i, s in enumerate(self._slots):
            if s is not None and s.phase == "decode":
                tokens[i] = s.last_token
                index[i] = s.index
                temps[i] = s.handle.request.temperature
                if tables is not None:
                    # prefilling / empty rows keep all-zeros rows →
                    # their garbage goes to the scratch page
                    tables[i] = s.block_row
        self._key, sub = jax.random.split(self._key)
        with trace.span("serve_decode"):
            out, self._cache, _ = self.decoder.decode_step(
                self._cache, tokens, index, temps, sub,
                block_tables=tables)
            out = np.asarray(out)
        for i, s in enumerate(self._slots):
            if s is None or s.phase != "decode":
                continue
            tok = int(out[i])
            s.tokens.append(tok)
            s.last_token = tok
            s.index += 1
            if self._finished(s):
                self._retire(i)
        self._last_step_t = time.perf_counter()

    @staticmethod
    def _finished(slot: _Slot) -> bool:
        req = slot.handle.request
        return (len(slot.tokens) >= req.max_new_tokens
                or (req.eos_id is not None
                    and slot.tokens[-1] == req.eos_id))

    def _retire(self, slot_idx: int):
        slot = self._slots[slot_idx]
        self._slots[slot_idx] = None
        if slot.pages:
            # reclaim: these exact pages are the next admit's grant
            self.pool.free(slot.pages)
        req = slot.handle.request
        req.finish_time = time.time()
        result = ServeResult(
            request_id=req.id,
            tokens=list(slot.tokens),
            prompt_len=int(req.prompt.size),
            queue_wait_s=req.admit_time - req.submit_time,
            time_to_first_token_s=req.first_token_time - req.submit_time,
            latency_s=req.finish_time - req.submit_time,
            submit_time=req.submit_time, finish_time=req.finish_time)
        self._ewma_latency = (0.8 * self._ewma_latency
                              + 0.2 * result.latency_s)
        self._m_completed.inc()
        self._m_latency.observe(result.latency_s)
        self._m_queue_wait.observe(result.queue_wait_s)
        self.completed.append(result)
        slot.handle._deliver(result)
        with self._cond:
            self._cond.notify_all()

    # -- lifecycle -----------------------------------------------------
    @property
    def draining(self) -> bool:
        return self._draining

    def begin_drain(self) -> None:
        """Graceful-shutdown phase 1 (called from the SIGTERM handler,
        so it must be async-signal-tolerant: no blocking lock, no
        logging — the interrupted frame may already hold either lock).
        New submits shed with ``retry_after``; queued and in-flight
        requests keep decoding to completion.  Follow with
        ``stop(drain=True)`` to wait them out and join the engine
        thread — then exit 0: a drained process is a CLEAN exit, not a
        casualty."""
        self._draining = True  # atomic under the GIL; read under _cond
        if self._cond.acquire(blocking=False):  # best-effort wake
            try:
                self._cond.notify_all()
            finally:
                self._cond.release()

    def stop(self, drain: bool = True, timeout: float = 60.0):
        """Stop the engine.  ``drain=True`` finishes in-flight AND
        already-queued work first; False cancels queued requests."""
        with self._cond:
            if not drain:
                for handle in self._pending:
                    req = handle.request
                    handle._deliver(ServeResult(
                        request_id=req.id, tokens=[], prompt_len=0,
                        queue_wait_s=0.0, time_to_first_token_s=0.0,
                        latency_s=0.0, cancelled=True))
                self._pending.clear()
            self._stop.set()
            self._cond.notify_all()
        self._thread.join(timeout=timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop(drain=False)
