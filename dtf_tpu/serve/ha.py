"""Router high availability: fenced leader lease + warm-standby
takeover.

The replica tier survives replica SIGKILL, partitions and slow
replicas token-exactly (serve/router.py), but the router itself is a
single point of failure: its death strands every queued and in-flight
request.  This module closes that hole with three shared-storage
artifacts — all in the rendezvous directory, the tier's ONE shared-
storage requirement, all written with the same atomic tmp+``os.replace``
discipline as ``replica_rank{K}.json`` and ``rollout_state.json``:

  ``router_lease.json``    the leader lease.  One holder at a time;
                           every acquisition bumps a MONOTONIC fencing
                           epoch.  Every controller wire op carries the
                           holder's epoch and replicas reject ops with
                           an epoch below the highest they have seen —
                           a deposed leader that never noticed (GC
                           pause, partition) is fenced out at the
                           replicas, so split-brain cannot corrupt a
                           client stream no matter how the lease race
                           resolves.
  ``router_journal.jsonl`` the request journal (serve/journal.py): the
                           successor's re-adoption worklist.
  ``rollout_state.json``   the rollout state machine (serve/rollout.py)
                           — a takeover mid-rollout resumes it through
                           ``RolloutController.resume``.

Why takeover is CRASH-EXACT: replicas keep decoding while the router
socket is down (a dead pipe drops deliveries, not engine work — and
the replica retains each request's token tail, serve/replica.py
``reattach``), and the determinism contract (greedy decode + the
per-request ``rng_seed`` minted once at submit and persisted in the
journal's submit record) means any re-dispatch replays the identical
token stream.  So the standby re-attaches where tails survive and
re-dispatches where they don't, the PR-8 token-index verify+dedupe
de-duplicates the overlap, and the client sees each token exactly
once — the router's death is an efficiency loss (one takeover gap),
never a correctness event.

Lease acquisition is serialized with an ``O_EXCL`` lock file (broken
when stale: a holder that died mid-acquire must not wedge the tier),
and the lease content itself is read/written atomically.  Renewals
that stop (the ``lease_stall@<ticks>`` chaos kind drops them
deterministically) let the lease expire: the standby acquires at
epoch+1 and the old leader — if it is somehow still alive — discovers
the fence on its next renewal or at the replicas' ``stale_epoch``
rejections, whichever comes first.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Callable, Optional

from dtf_tpu import chaos
from dtf_tpu.obs import trace

log = logging.getLogger("dtf_tpu")

LEASE_NAME = "router_lease.json"


def lease_path(rendezvous_dir: str) -> str:
    return os.path.join(rendezvous_dir, LEASE_NAME)


def read_lease(rendezvous_dir: str) -> Optional[dict]:
    """Parse the lease file; None when missing/torn (an atomic writer
    means torn = mid-replace on a non-atomic filesystem — treated as
    'no lease', the safe direction for an acquirer)."""
    try:
        with open(lease_path(rendezvous_dir), encoding="utf-8") as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


class LeaderLease:
    """One contender's view of the shared leader lease.

    ``acquire()`` takes the lease (epoch = highest seen + 1) when it is
    free or expired; ``renew()`` extends it and returns False the
    moment another holder's epoch appears — the FENCED verdict.  The
    epoch this object holds is what the owning router stamps on every
    wire op."""

    def __init__(self, rendezvous_dir: str, *, ttl_s: float = 2.0,
                 holder: str = ""):
        self.rendezvous_dir = os.path.abspath(rendezvous_dir)
        os.makedirs(self.rendezvous_dir, exist_ok=True)
        self.ttl_s = float(ttl_s)
        self.holder = holder or f"pid{os.getpid()}"
        self.path = lease_path(self.rendezvous_dir)
        self._lock_path = self.path + ".lock"
        self.epoch = 0          # the epoch THIS contender holds; 0 = none
        self.fenced = False

    # -- the shared file -----------------------------------------------
    def read(self) -> Optional[dict]:
        return read_lease(self.rendezvous_dir)

    def expired(self, lease: Optional[dict] = None) -> bool:
        """True when the current lease no longer protects its holder
        (missing, torn, or past ts + ttl in shared wall time)."""
        lease = lease if lease is not None else self.read()
        if lease is None:
            return True
        return time.time() > float(lease.get("ts", 0)) + float(
            lease.get("ttl_s", self.ttl_s))

    def _write(self, payload: dict) -> None:
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)   # atomic: readers never see a torn lease

    def _with_acquire_lock(self, fn: Callable, timeout_s: float = 5.0):
        """Serialize lease MUTATION across contenders with an O_EXCL
        lock file.  A lock older than 5×ttl is stale (its taker died
        mid-acquire) and is broken — one dead contender must not wedge
        every future takeover."""
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                fd = os.open(self._lock_path,
                             os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                break
            except FileExistsError:
                try:
                    age = time.time() - os.stat(self._lock_path).st_mtime
                    if age > 5.0 * self.ttl_s:
                        os.unlink(self._lock_path)
                        continue
                except OSError:
                    continue    # raced another breaker/releaser
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"lease lock {self._lock_path} held too long")
                time.sleep(0.01)
        try:
            return fn()
        finally:
            os.close(fd)
            try:
                os.unlink(self._lock_path)
            except OSError:
                pass

    # -- contender API -------------------------------------------------
    def acquire(self, force: bool = False) -> Optional[int]:
        """Try to take the lease: returns the NEW fencing epoch, or
        None while a live holder's lease protects it.  ``force`` takes
        it regardless (operator override) — still at epoch+1, so the
        deposed holder is fenced, not raced."""

        def attempt():
            cur = self.read()
            if cur is not None and not self.expired(cur) and not force \
                    and cur.get("holder") != self.holder:
                return None
            epoch = int(cur.get("epoch", 0) if cur else 0) + 1
            self._write({"epoch": epoch, "holder": self.holder,
                         "ts": time.time(), "ttl_s": self.ttl_s})
            self.epoch = epoch
            self.fenced = False
            log.warning("ha: %s acquired leader lease (epoch %d)",
                        self.holder, epoch)
            return epoch

        return self._with_acquire_lock(attempt)

    def renew(self) -> bool:
        """Extend the held lease.  Returns False — the FENCED verdict,
        latched — when another holder's epoch has appeared: this
        contender must stop acting as leader NOW (its wire ops are
        already being rejected by replicas).  A chaos ``lease_stall``
        drops the renewal write (the renewal tick happens, the file
        write doesn't) — the deterministic stand-in for a GC pause or
        a shared-storage brownout."""
        if self.epoch == 0:
            return False
        cur = self.read()
        if cur is not None and int(cur.get("epoch", 0)) > self.epoch:
            if not self.fenced:
                self.fenced = True
                log.error("ha: %s FENCED (held epoch %d, current %d)",
                          self.holder, self.epoch,
                          int(cur.get("epoch", 0)))
            return False
        if chaos.lease_stall():
            return True     # stalled, not fenced — the lease just ages
        self._write({"epoch": self.epoch, "holder": self.holder,
                     "ts": time.time(), "ttl_s": self.ttl_s})
        return True

    def release(self) -> None:
        """Drop the lease on clean shutdown so the standby takes over
        at the next poll instead of waiting out the ttl."""
        if self.epoch == 0:
            return

        def attempt():
            cur = self.read()
            if cur is not None and int(cur.get("epoch", 0)) == self.epoch:
                try:
                    os.unlink(self.path)
                except OSError:
                    pass
            return None

        try:
            self._with_acquire_lock(attempt)
        except (OSError, TimeoutError):
            pass
        self.epoch = 0


class LeaseKeeper:
    """The leader's renewal heartbeat: a thread that renews at ttl/3
    cadence and calls ``on_fenced`` (once) the moment renew() returns
    the fenced verdict."""

    def __init__(self, lease: LeaderLease,
                 on_fenced: Optional[Callable] = None):
        self.lease = lease
        self._on_fenced = on_fenced
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "LeaseKeeper":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ha-lease-keeper")
        self._thread.start()
        return self

    def _run(self) -> None:
        interval = max(0.05, self.lease.ttl_s / 3.0)
        while not self._stop.wait(interval):
            try:
                if not self.lease.renew():
                    if self._on_fenced is not None:
                        self._on_fenced()
                    return
            except OSError as e:
                # shared storage hiccup: keep trying — the lease ages
                # like a stall, and the standby's takeover fences us
                # if it ages out
                log.warning("ha: lease renewal failed: %s", e)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)


def standby_health(lease: LeaderLease) -> dict:
    """The warm standby's /healthz payload while it waits: role,
    the epoch it watches, and ok (a standby that can read the lease
    is doing its whole job)."""
    cur = lease.read()
    return {"ok": True, "role": "standby",
            "epoch": int(cur.get("epoch", 0)) if cur else 0,
            "lease_expired": lease.expired(cur)}


def wait_for_takeover(lease: LeaderLease, poll_s: float = 0.1,
                      timeout_s: float = 0.0,
                      stop: Optional[threading.Event] = None
                      ) -> Optional[int]:
    """Standby loop: poll the lease until it expires, then acquire.
    Returns the new fencing epoch, or None on timeout/stop.  Polling
    beats watching: the lease lives on shared storage where inotify
    does not travel."""
    deadline = (time.monotonic() + timeout_s) if timeout_s else None
    while True:
        if stop is not None and stop.is_set():
            return None
        if lease.expired():
            epoch = lease.acquire()
            if epoch is not None:
                return epoch
        if deadline is not None and time.monotonic() > deadline:
            return None
        time.sleep(poll_s)


def take_over(router, *, delivered: Optional[dict] = None,
              resume_rollout: bool = True,
              rollout_state_path: str = "",
              restart_hook: Optional[Callable] = None) -> dict:
    """Run the whole takeover sequence on a freshly-built successor
    ``router`` (constructed with the NEW fencing epoch and the shared
    journal path, ``start(adopt=True)`` already done — replicas
    adopted, not respawned):

      1. replay the journal and re-adopt/re-dispatch every unresolved
         request (``Router.adopt_requests`` — reattach where the
         replica retained the tail, ordinary budgeted failover where
         it didn't);
      2. resume a mid-flight rollout state machine, if
         ``rollout_state.json`` shows one (CANARY → rollback, ROLLING
         → forward: serve/rollout.py ``resume`` semantics).

    ``delivered`` maps request id → the token prefix the CLIENT
    acknowledges on reconnect; with it the re-adopted stream is
    exactly-once (tokens the client has are verified, not re-emitted).
    Returns the adoption summary dict."""
    from dtf_tpu.serve import journal as journal_mod
    from dtf_tpu.serve import rollout as rollout_mod

    state = journal_mod.unresolved(journal_mod.replay(
        journal_mod.journal_path(router.rendezvous_dir)))
    summary = router.adopt_requests(state, delivered=delivered)
    if resume_rollout:
        state_path = rollout_state_path or rollout_mod.default_state_path(
            router.rendezvous_dir)
        try:
            rstate = rollout_mod.RolloutState.load(state_path)
        except (OSError, ValueError):
            rstate = None
        if rstate is not None and rstate.phase not in ("IDLE", "DONE"):
            log.warning("ha: takeover found rollout mid-flight (%s) — "
                        "resuming", rstate.phase)
            final = rollout_mod.RolloutController.resume(
                router, state_path=state_path, restart_hook=restart_hook)
            summary["rollout_resumed"] = final.phase
    trace.event("router_takeover", epoch=router.epoch,
                readopted=summary.get("readopted", 0),
                redispatched=summary.get("redispatched", 0),
                unresolved=len(state))
    trace.flush()
    return summary
